"""The vectorized Multi-Raft step kernel.

``node_step`` advances EVERY Raft group on a node by one logical tick in a
single fused XLA program: message-driven term sync, vote grant/tally,
AppendEntries consistency + conflict handling, leader bookkeeping, timer
expiry, client submission, replication fan-out and quorum commit — all as
masked vector operations over group-major arrays.

This replaces the reference's entire per-group concurrency layer (event loops,
CAS role switches, timer fencing: support/EventLoop.java, context/
RaftRoutine.java:86-216) with data parallelism.  Semantics are kept faithful
to the reference's Raft implementation; each phase cites the Java code whose
behavior it vectorizes.

Vectorization notes (why no per-peer sequential folds are needed):

* AppendEntries / InstallSnapshot requests: only ONE peer can be the
  current-term leader of a group (election safety), so after term sync at
  most one inbound request per group passes the term check — selecting it
  with an argmax over the peer axis is equivalent to processing peers in
  order.
* Responses (AE replies, vote replies): pure elementwise [G, P] updates.
* Vote requests: grant exclusivity within a tick is the only order-dependent
  rule; granting the lowest-indexed eligible requester reproduces the
  sequential fold exactly.

Phase order within a tick (messages produced in tick t are delivered in t+1):
  1. term sync           — step down on any higher inbound term
  2. vote requests       — grant PreVote/RequestVote, produce replies
  3. vote responses      — tally; PRE_CANDIDATE→CANDIDATE→LEADER transitions
  4. AppendEntries reqs  — consistency check, conflict truncate, append, commit
  5. InstallSnapshot     — offer handling + completion events from host
  6. AppendEntries resps — leader match/next bookkeeping
  6b. read evidence      — same-term ack receipts/echoes feed the barrier
  6c. CheckQuorum        — leader with no voter-quorum contact within an
                           election timeout steps down (cfg.check_quorum;
                           closes the lease: 8b aborts its pending reads)
  7. timers              — election timeout → PreVote round / new election
                           (voters only; TimeoutNow → immediate candidacy)
  7b. transfer intake    — leadership-transfer requests latch/abort; a
                           pending transfer fences submissions
  8. submissions         — leader accepts client commands into the log
  8b. read plane         — stamp ReadIndex batches, release on quorum
                           barrier (lease fast path: same-tick evidence)
  8c. membership         — config-change intake (§6 joint consensus) +
                           automatic C_new leave once C_old,new commits
  9. replication         — leader builds AppendEntries / snapshot offers
                           over MEMBER lanes (+ barrier-kicked heartbeats,
                           tick-stamped); TimeoutNow to a caught-up target
 10. commit advance      — masked-quorum order statistic over matchIndex
                           (joint: both voter sets), own-term rule; a
                           removed leader resigns once C_new commits
 11. flight recorder     — branchless per-group event-ring writes of the
                           tick's phase-boundary events (cfg.trace_depth;
                           compiled away entirely when 0)
"""

from __future__ import annotations

import functools
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from .types import (
    CANDIDATE, FOLLOWER, LEADER, NIL, PRE_CANDIDATE, I32,
    EngineConfig, HostInbox, LogState, Messages, RaftState, StepInfo,
    conf_learners_of, conf_new_of, conf_pack, conf_voters_of,
)

Array = jax.Array

# StepInfo.debug_viol codes (cfg.debug_checks; see the check block at the
# end of node_step).
DEBUG_CODES = {
    1: "live log window exceeds ring capacity",
    2: "commit passed the log end",
    3: "term regressed",
    4: "continuing leader's matchIndex moved backwards",
    5: "candidate ballot is not itself",
    6: "commit regressed",
    7: "pipeline head behind ack base",
    8: "read FIFO length out of range",
    9: "active config has no voters",
}


def raise_debug_violations(info, where: str = "") -> None:
    """Host-side consumer of StepInfo.debug_viol: raise naming the group
    and the violated invariant (the assert analog of the reference's
    AssertionError surfacing, pinned to the faulting phase)."""
    import numpy as np

    viol = np.asarray(info.debug_viol)
    bad = np.nonzero(viol)
    if len(bad[0]):
        first = tuple(int(i) for i in (b[0] for b in bad))
        code = int(viol[first])
        raise AssertionError(
            f"kernel invariant violated{' in ' + where if where else ''}: "
            f"lane {first} code {code} "
            f"({DEBUG_CODES.get(code, 'unknown')}); "
            f"{len(bad[0])} lane(s) total")


# ---------------------------------------------------------------------------
# Log-ring primitives.  The log is a per-group ring of entry terms: index i
# lives at slot i % L.  Entries (base, last] are live; `base` carries
# base_term (the snapshot milestone, reference StableLock.java:82-91).
# ---------------------------------------------------------------------------

def ring_term_at(log: LogState, idx: Array) -> Array:
    """Term of entry `idx` per group ([G] -> [G]).

    idx == base  -> base_term (milestone);  idx < base -> compacted (returns
    base_term; callers treat anything <= base as matching — compacted entries
    are committed, hence matched, the reference's purgeEntries rationale,
    Follower.java:209-221).  idx > last -> -1 (absent).
    """
    L = log.term.shape[1]
    slot = jnp.remainder(idx, L)
    t = jnp.take_along_axis(log.term, slot[:, None], axis=1)[:, 0]
    return jnp.where(idx <= log.base, log.base_term,
                     jnp.where(idx <= log.last, t, jnp.asarray(-1, I32)))


def ring_terms_batch(log: LogState, idx: Array) -> Array:
    """Terms for a [G, K] index matrix (absent -> -1)."""
    L = log.term.shape[1]
    slot = jnp.remainder(idx, L)
    t = jnp.take_along_axis(log.term, slot, axis=1)
    return jnp.where(idx <= log.base[:, None], log.base_term[:, None],
                     jnp.where(idx <= log.last[:, None], t, jnp.asarray(-1, I32)))


def ring_write_batch(log_term: Array, idx: Array, vals: Array, mask: Array) -> Array:
    """Masked scatter of entry terms at [G, K] indices into the [G, L] ring."""
    G, L = log_term.shape
    rows = jnp.broadcast_to(jnp.arange(G, dtype=I32)[:, None], idx.shape)
    slot = jnp.where(mask, jnp.remainder(idx, L), L)  # L = out of range -> dropped
    return log_term.at[rows, slot].set(vals, mode="drop")


def ring_conf_batch(log: LogState, idx: Array) -> Array:
    """Packed config words for a [G, K] index matrix.

    0 outside the live window (compacted entries' configs are folded into
    ``base_conf``; absent entries carry nothing) — the AE build gathers
    entry config words with exactly these semantics, so followers adopt
    configs with the same window rules as terms."""
    L = log.conf.shape[1]
    slot = jnp.remainder(idx, L)
    w = jnp.take_along_axis(log.conf, slot, axis=1)
    live = (idx > log.base[:, None]) & (idx <= log.last[:, None])
    return jnp.where(live, w, jnp.asarray(0, I32))


def latest_conf(log: LogState, upto: Array) -> Tuple[Array, Array]:
    """The active configuration per group: ``(conf_idx, conf_word)`` of the
    latest config entry in ``(base, min(upto, last)]``, falling back to
    ``(0, base_conf)`` when none is live.

    The §6 apply-on-append rule AND its truncation rollback in one
    derivation: a node uses the newest config present in its log whether
    committed or not, and a conflict truncation that removes an
    uncommitted config entry automatically reverts to the previous one —
    no separate rollback state to maintain.  One [G, L] sweep, the same
    shape of work as the replication gather (``ring_terms_batch`` over
    [G, P*B])."""
    G, L = log.conf.shape
    j = jnp.arange(L, dtype=I32)[None, :]
    # The unique index congruent to slot j (mod L) within (last-L, last].
    idx = log.last[:, None] - jnp.remainder(log.last[:, None] - j, L)
    isc = (idx > log.base[:, None]) & (idx <= upto[:, None]) \
        & (log.conf != 0)
    cidx = jnp.where(isc, idx, 0).max(axis=1)
    w = jnp.take_along_axis(log.conf, jnp.remainder(cidx, L)[:, None],
                            axis=1)[:, 0]
    has = cidx > 0
    return (jnp.where(has, cidx, 0),
            jnp.where(has, w, log.base_conf))


def mask_bits(mask: Array, P: int) -> Array:
    """Expand [G] peer bitmasks into a [G, P] boolean matrix."""
    return ((mask[:, None] >> jnp.arange(P, dtype=I32)[None, :]) & 1) > 0


def dual_quorum(flags: Array, voters: Array, voters_new: Array) -> Array:
    """Popcount-over-masked-lanes quorum: do ``flags`` [G, P] cover a
    majority of ``voters`` — and, when joint (``voters_new`` nonzero), a
    majority of ``voters_new`` TOO (Raft §6: joint decisions need both)?
    Used by vote tallies, PreVote tallies and the leader readiness gate;
    the commit quorum is the order-statistic analog in ops/quorum.py."""
    P = flags.shape[1]
    vb = mask_bits(voters, P)
    nb = mask_bits(voters_new, P)
    ok_v = (flags & vb).sum(axis=1) >= vb.sum(axis=1) // 2 + 1
    ok_n = (flags & nb).sum(axis=1) >= nb.sum(axis=1) // 2 + 1
    return ok_v & ((voters_new == 0) | ok_n)


def _pick_peer(flag_pg: Array) -> Tuple[Array, Array]:
    """Select the lowest-indexed peer whose flag is set, per group.

    Returns (peer_index [G], any_flag [G])."""
    any_f = flag_pg.any(axis=0)
    return jnp.argmax(flag_pg, axis=0).astype(I32), any_f


def _gather_peer(field_pg: Array, peer: Array) -> Array:
    """field[[P, G] or [P, G, K]], peer [G] -> per-group selected [G] / [G, K]."""
    if field_pg.ndim == 2:
        return jnp.take_along_axis(field_pg, peer[None, :], axis=0)[0]
    return jnp.take_along_axis(
        field_pg, peer[None, :, None], axis=0)[0]


# ---------------------------------------------------------------------------
# The step
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnums=0, donate_argnums=1)
def node_step(cfg: EngineConfig, state: RaftState, inbox: Messages,
              host: HostInbox) -> Tuple[RaftState, Messages, StepInfo]:
    G, P, B, L, S = (cfg.n_groups, cfg.n_peers, cfg.batch, cfg.log_slots,
                     cfg.max_submit)
    s = state
    now = s.now + 1
    rng, k_to = jax.random.split(s.rng)
    # One randomized election window per group per tick, consumed by whichever
    # lanes reset their timer (reference RaftConfig.electionTimeout re-draws on
    # every read, support/RaftConfig.java:187-190).
    rand_to = jax.random.randint(k_to, (G,), cfg.election_ticks,
                                 2 * cfg.election_ticks, dtype=I32)

    me = s.node_id
    peer_ids = jnp.arange(P, dtype=I32)
    self_hot = peer_ids[None, :] == me            # [1, P] one-hot row for self
    not_me_col = (peer_ids != me)[:, None]        # [P, 1] mask over peer axis

    active = s.active
    term, role, voted = s.term, s.role, s.voted_for
    leader_id, commit = s.leader_id, s.commit
    log = s.log
    next_idx, match_idx = s.next_idx, s.match_idx
    own_from = s.own_from
    send_next, inflight = s.send_next, s.inflight
    hb_inflight = s.hb_inflight
    sent_at, need_snap = s.sent_at, s.need_snap
    ok_at, fail_at, fail_streak = s.ok_at, s.fail_at, s.fail_streak
    votes, prevotes = s.votes, s.prevotes
    elect_dl, hb_due = s.elect_deadline, s.hb_due

    old_term, old_voted, old_last = term, voted, log.last

    # ---- 0. membership view C0 (tick-start) -------------------------------
    # The active config is a function of the log (§6 apply-on-append +
    # truncation rollback, see latest_conf); the state carries it as the
    # conf_idx/conf_word cache, re-derived at the end of every tick's log
    # mutations — so C0 is two state reads, not a [G, L] sweep.  C0
    # anchors the vote/prevote tallies (phase 3): a tally must count
    # against the config of the log the candidacy was launched from — the
    # same log whose position the vote requests carried.
    cidx0, w0 = s.conf_idx, s.conf_word
    voters0 = conf_voters_of(w0)
    vnew0 = conf_new_of(w0)

    # ---- 1. term sync: adopt the highest real term seen this tick ---------
    # (the universal Raft rule; reference applies it per-RPC via
    # switchTo(Follower, term): Follower.java:45-47, Candidate.java:28-41,
    # Leader step-down Leader.java:224-227.  PreVote requests are excluded:
    # their term is speculative and must not bump ours.)
    neg = jnp.asarray(-1, I32)
    def masked(valid, t):
        return jnp.where(valid, t, neg)
    mt = functools.reduce(jnp.maximum, [
        masked(inbox.ae_valid, inbox.ae_term),
        masked(inbox.aer_valid, inbox.aer_term),
        masked(inbox.rv_valid & ~inbox.rv_prevote, inbox.rv_term),
        masked(inbox.rvr_valid, inbox.rvr_term),
        masked(inbox.is_valid, inbox.is_term),
        masked(inbox.isr_valid, inbox.isr_term),
        masked(inbox.tn_valid, inbox.tn_term),
    ]).max(axis=0)                                           # [G]
    stepdown = active & (mt > term)
    term = jnp.where(stepdown, mt, term)
    role = jnp.where(stepdown, FOLLOWER, role)
    voted = jnp.where(stepdown, NIL, voted)
    leader_id = jnp.where(stepdown, NIL, leader_id)
    elect_dl = jnp.where(stepdown, now + rand_to, elect_dl)

    last_term_v = ring_term_at(log, log.last)

    # ---- 2. vote requests --------------------------------------------------
    # (reference Follower.requestVote:108-127 / preVote:91-105.)
    rv_v = inbox.rv_valid & active[None, :] & not_me_col          # [P, G]
    pv = inbox.rv_prevote
    # Log up-to-date check (reference Follower.logUpToDate:193-207).
    utd = ((inbox.rv_last_term > last_term_v[None, :]) |
           ((inbox.rv_last_term == last_term_v[None, :]) &
            (inbox.rv_last_idx >= log.last[None, :])))
    # RequestVote eligibility: same term (sync already adopted any higher),
    # ballot unburned or already ours.
    elig_rv = (rv_v & ~pv & (inbox.rv_term == term[None, :]) & utd &
               ((voted[None, :] == NIL) | (voted[None, :] == peer_ids[:, None])))
    # Exclusivity: grant the lowest-indexed eligible requester (== the
    # sequential fold order).  Re-grants to the peer we already voted for
    # are always allowed.
    first_elig, _ = _pick_peer(elig_rv)
    grant_rv = elig_rv & ((voted[None, :] == peer_ids[:, None]) |
                          (peer_ids[:, None] == first_elig[None, :]))
    granted_any = (grant_rv & (voted[None, :] == NIL)).any(axis=0)
    voted = jnp.where(granted_any & (voted == NIL), first_elig, voted)
    elect_dl = jnp.where(grant_rv.any(axis=0), now + rand_to, elect_dl)
    # PreVote grant (reference Follower.preVote:91-105): only if we ourselves
    # have detected leader silence (lease), log up-to-date, term ahead.  No
    # durable state changes.
    lease_open = (now >= elect_dl) | (leader_id == NIL)
    grant_pv = (rv_v & pv & (inbox.rv_term > term[None, :]) & utd &
                lease_open[None, :])
    out_rvr_valid = rv_v
    out_rvr_term = jnp.broadcast_to(term[None, :], (P, G))
    out_rvr_granted = jnp.where(pv, grant_pv, grant_rv)
    out_rvr_prevote = pv
    out_rvr_echo = inbox.rv_term

    # ---- 3. vote responses + tallies --------------------------------------
    rr = inbox.rvr_valid & active[None, :]
    # PreVote tally: accept grants only for the round we are still in — the
    # echoed requested term must equal term+1 (vectorized analog of AsyncHead
    # cancellation of stale rounds, Async.java:70-172).
    g_pv = (rr & inbox.rvr_prevote & inbox.rvr_granted &
            (role == PRE_CANDIDATE)[None, :] &
            (inbox.rvr_echo == (term + 1)[None, :]))
    prevotes = prevotes | g_pv.T
    # Real vote tally (reference Candidate.startElection:112-134): a grant
    # implies the responder adopted our term, so term equality is the fence.
    g_rv = (rr & ~inbox.rvr_prevote & inbox.rvr_granted &
            (role == CANDIDATE)[None, :] & (inbox.rvr_term == term[None, :]))
    votes = votes | g_rv.T

    # Tallies are popcount-over-masked-lanes quorums against C0 (§6: a
    # joint config needs a majority in BOTH voter sets; learners and
    # removed slots never count, though their grants are harmless).  The
    # PreVote and RequestVote tallies share one set of masks/thresholds
    # (both count against C0).
    vb0 = mask_bits(voters0, P)
    nb0 = mask_bits(vnew0, P)
    maj_v0 = vb0.sum(axis=1) // 2 + 1
    maj_n0 = nb0.sum(axis=1) // 2 + 1
    not_joint0 = vnew0 == 0

    def tally0(flags):
        return ((flags & vb0).sum(axis=1) >= maj_v0) \
            & (not_joint0 | ((flags & nb0).sum(axis=1) >= maj_n0))

    pv_win = (role == PRE_CANDIDATE) & tally0(prevotes)
    # PreVote majority -> real candidacy at term+1 (reference
    # Follower.prepareElection:264-267 -> trySwitchTo(Candidate, term+1)).
    become_cand_pv = pv_win
    term = jnp.where(become_cand_pv, term + 1, term)
    role = jnp.where(become_cand_pv, CANDIDATE, role)
    voted = jnp.where(become_cand_pv, me, voted)
    leader_id = jnp.where(become_cand_pv, NIL, leader_id)
    votes = jnp.where(become_cand_pv[:, None], self_hot, votes)
    elect_dl = jnp.where(become_cand_pv, now + rand_to, elect_dl)

    vote_win = (role == CANDIDATE) & tally0(votes)
    # Candidate majority -> Leader (reference Candidate.java:128-131 ->
    # Leader ctor + prepareReplication, Leader.java:25-50): reset the
    # replication matrix, health stats and heartbeat immediately.
    role = jnp.where(vote_win, LEADER, role)
    leader_id = jnp.where(vote_win, me, leader_id)
    next_idx = jnp.where(vote_win[:, None], log.last[:, None] + 1, next_idx)
    match_idx = jnp.where(vote_win[:, None], 0, match_idx)
    send_next = jnp.where(vote_win[:, None], log.last[:, None] + 1, send_next)
    inflight = jnp.where(vote_win[:, None], 0, inflight)
    hb_inflight = jnp.where(vote_win[:, None], 0, hb_inflight)
    need_snap = jnp.where(vote_win[:, None], False, need_snap)
    ok_at = jnp.where(vote_win[:, None], 0, ok_at)
    fail_at = jnp.where(vote_win[:, None], 0, fail_at)
    fail_streak = jnp.where(vote_win[:, None], 0, fail_streak)
    hb_due = jnp.where(vote_win, now, hb_due)
    # First index of OUR term as leader: the slot the no-op below takes
    # (or, with a full ring, the first future own entry).  Terms are
    # monotone along the log, so the phase-10 own-term commit rule is
    # exactly `quorum_idx >= own_from` — no ring gather on the hot path.
    own_from = jnp.where(vote_win, log.last + 1, own_from)
    # Raft §8 liveness: a fresh leader appends an OWN-TERM NO-OP entry so
    # its predecessors' entries become committable immediately — the
    # commit rule (phase 10, reference Leader.java:256-261) only counts a
    # quorum at the leader's own term, so without this a cluster with no
    # new client traffic never surfaces a deposed leader's
    # committed-at-majority suffix (the reference shares the gap; its
    # system test masks it with always-on traffic).  Skipped when the
    # ring is full — such a lane is already acceptance-stalled and drains
    # through compaction first.  The host stages the no-op durably with
    # an empty payload (StepInfo.noop_idx/noop_term), and followers adopt
    # it through ordinary replication; machines see one empty command.
    noop_ok = vote_win & (log.last - log.base < L)
    noop_idx = jnp.where(noop_ok, log.last + 1, 0)
    noop_term = jnp.where(noop_ok, term, 0)
    # Every term-ring write clears/overwrites the conf-ring slot too: a
    # reused ring slot must never leak a dead entry's config word into
    # the latest_conf derivation.
    log = log.replace(
        term=ring_write_batch(log.term, (log.last + 1)[:, None],
                              term[:, None], noop_ok[:, None]),
        conf=ring_write_batch(log.conf, (log.last + 1)[:, None],
                              jnp.zeros((G, 1), I32), noop_ok[:, None]),
        last=log.last + noop_ok.astype(I32))

    # ---- 4. AppendEntries requests ----------------------------------------
    # (reference Follower.appendEntries:35-88 — consistency check, conflict
    # truncation, append, passive commit.)  At most one inbound AE per group
    # passes the term check (single current-term leader), so we select it
    # with an argmax and process all groups at once.
    ae_v = inbox.ae_valid & active[None, :] & not_me_col
    ae_t_ok = ae_v & (inbox.ae_term == term[None, :])
    ae_peer, ae_any = _pick_peer(ae_t_ok)
    # A valid leader at our term: candidates/pre-candidates step down
    # (reference Candidate.appendEntries:28-41); election timer resets
    # (Follower.java:43).  A same-term leader receiving an AE is impossible
    # under election safety — guard so it never demotes itself.
    ae_any = ae_any & (role != LEADER)
    role = jnp.where(ae_any, FOLLOWER, role)
    leader_id = jnp.where(ae_any, ae_peer, leader_id)
    elect_dl = jnp.where(ae_any, now + rand_to, elect_dl)

    prev_i = _gather_peer(inbox.ae_prev_idx, ae_peer)
    prev_t = _gather_peer(inbox.ae_prev_term, ae_peer)
    n_e = _gather_peer(inbox.ae_n, ae_peer)
    lc = _gather_peer(inbox.ae_commit, ae_peer)
    ents = _gather_peer(inbox.ae_ents, ae_peer)                  # [G, B]
    cents = _gather_peer(inbox.ae_cents, ae_peer)                # [G, B]
    # Bounded-window partial accept: the live window (base, last] must never
    # exceed the ring capacity L, or new entries would alias committed slots.
    # A follower whose compaction floor lags the leader's clamps the batch to
    # what fits; the success reply's match=tail makes the leader resume from
    # the clamped point, and the commit/compact cycle frees capacity.  (No
    # reference analog — RocksDB logs are unbounded; this is the flow-control
    # rule the HBM-resident ring requires.)
    n_e = jnp.clip(n_e, 0, jnp.maximum(log.base + L - prev_i, 0))
    # Consistency: prev entry matches, or prev is at/under our compaction
    # floor (compacted == committed == matched; reference
    # Follower.logContains:177-191 + purgeEntries:209-221).
    prev_match = ((prev_i <= log.base) |
                  ((prev_i <= log.last) & (ring_term_at(log, prev_i) == prev_t)))
    acc = ae_any & prev_match

    col = jnp.arange(B, dtype=I32)[None, :]
    idxs = prev_i[:, None] + 1 + col                             # [G, B]
    in_n = col < n_e[:, None]
    exists = (idxs <= log.last[:, None]) & (idxs > log.base[:, None])
    cur = ring_terms_batch(log, idxs)
    conflict = (acc[:, None] & in_n & exists & (cur != ents)).any(axis=1)
    wmask = acc[:, None] & in_n & (idxs > log.base[:, None])
    new_ring = ring_write_batch(log.term, idxs, ents, wmask)
    # Config adoption rides the same write mask: a follower appending a
    # config entry USES its config immediately (§6 apply-on-append via
    # the post-phase latest_conf derivation).
    new_cring = ring_write_batch(log.conf, idxs, cents, wmask)
    tail = prev_i + n_e
    # Conflict => truncate-then-append == overwrite + last = prev+n;
    # no conflict => never shrink (stale/duplicate RPC; reference
    # RocksLog.conflict:199-216 + truncate:219-225 + append:169-196).
    new_last = jnp.where(acc,
                         jnp.where(conflict, tail,
                                   jnp.maximum(log.last, tail)),
                         log.last)
    wrote = acc & (n_e > 0) & ((new_last != log.last) | conflict)
    app_from = jnp.where(wrote, prev_i + 1, jnp.zeros((G,), I32))
    app_to = jnp.where(wrote, new_last, jnp.zeros((G,), I32))
    log = log.replace(term=new_ring, conf=new_cring, last=new_last)
    # Passive commit (reference Follower.java:76-82), bounded by the
    # *verified* prefix prev+n — not our log tail, which may still hold an
    # unverified divergent suffix from a deposed leader (Raft fig. 2:
    # min(leaderCommit, index of last NEW entry)).
    commit = jnp.where(acc,
                       jnp.maximum(commit, jnp.minimum(lc, tail)),
                       commit)
    # Replies to every valid AE: the selected peer gets the real verdict;
    # stale-term senders get failure at our (newer) term.  Failure carries a
    # nextIndex hint = min(our last, prev-1) — an accelerated version of the
    # reference's log-scaled backoff (Leadership.updateIndex:75-114).
    is_sel = (peer_ids[:, None] == ae_peer[None, :]) & ae_t_ok
    out_aer_valid = ae_v
    out_aer_term = jnp.broadcast_to(term[None, :], (P, G))
    out_aer_success = is_sel & acc[None, :]
    out_aer_match = jnp.where(
        is_sel & acc[None, :], tail[None, :],
        jnp.minimum(log.last[None, :], inbox.ae_prev_idx - 1))
    # Echo whether the AE was empty (a heartbeat): its sender did not
    # charge the reply against the in-flight window (phase 9), so it must
    # not decrement it either.
    out_aer_empty = ae_v & (inbox.ae_n == 0)
    # Echo the occupancy flag (symmetric with is_probe): only replies to
    # OCCUPYING heartbeats release a sender slot — a reply to a
    # window-full exempt heartbeat must not free a slot whose real ack
    # was lost (it would disarm the RPC-timeout detector one cadence).
    out_aer_occ = ae_v & inbox.ae_occ
    # Echo the AE's send tick unconditionally (success or failure): any
    # same-term reply proves we processed the leader's AE — the read
    # plane's barrier evidence (the occupancy-echo idiom again).
    out_aer_tick = jnp.where(ae_v, inbox.ae_tick, 0)

    # ---- 5. InstallSnapshot ------------------------------------------------
    # Device plane: an offer merely tells the follower's host to start the
    # bulk download (side channel, reference EventNode.SnapChannel:122-267).
    # The host reports completion via HostInbox.snap_done (reference
    # RaftRoutine.restoreCheckpoint:482-541).
    is_v = inbox.is_valid & active[None, :] & not_me_col
    is_t_ok = is_v & (inbox.is_term == term[None, :])
    is_peer, is_any = _pick_peer(is_t_ok)
    is_any = is_any & (role != LEADER)
    role = jnp.where(is_any, FOLLOWER, role)
    leader_id = jnp.where(is_any, is_peer, leader_id)
    elect_dl = jnp.where(is_any, now + rand_to, elect_dl)
    off_idx = _gather_peer(inbox.is_idx, is_peer)
    off_term = _gather_peer(inbox.is_last_term, is_peer)
    off_conf = _gather_peer(inbox.is_conf, is_peer)
    # Success only once the milestone is covered: either our snapshot floor
    # already includes it, or we hold a matching entry at that index.  While
    # the bulk download is in flight we answer failure so the leader keeps
    # the installation pending (reference PendingSnapshot tracking,
    # SnapshotArchive.java:197-211).
    covered = ((off_idx <= log.base) |
               ((off_idx <= log.last) &
                (ring_term_at(log, off_idx) == off_term)))
    useful = is_any & ~covered
    snap_req = useful
    snap_from = jnp.where(useful, is_peer, 0)
    snap_idx_o = jnp.where(useful, off_idx, 0)
    snap_term_o = jnp.where(useful, off_term, 0)
    snap_conf_o = jnp.where(useful, off_conf, 0)
    is_sel_snap = (peer_ids[:, None] == is_peer[None, :]) & is_t_ok
    out_isr_valid = is_v
    out_isr_term = jnp.broadcast_to(term[None, :], (P, G))
    out_isr_success = is_sel_snap & covered[None, :]
    # Echo the window-exemption flag: a reply to a heartbeat-cadence
    # re-offer must not release a slot the offer never took (symmetric
    # with aer_empty).
    out_isr_probe = is_v & inbox.is_probe

    # Host finished installing a snapshot: adopt the milestone as the new
    # log floor.  InstallSnapshot receiver rule (Raft fig. 13): if we hold an
    # entry matching the snapshot's (lastIndex, lastTerm), retain the suffix
    # after it; otherwise the whole log is suspect — discard it.
    sd = host.snap_done & active & (host.snap_idx > log.base)
    tail_matches = ((host.snap_idx <= log.last) &
                    (ring_term_at(log, host.snap_idx) == host.snap_term))
    log = log.replace(
        base=jnp.where(sd, host.snap_idx, log.base),
        base_term=jnp.where(sd, host.snap_term, log.base_term),
        # The installed milestone's config becomes the derivation floor
        # (0 from a legacy host = keep the current base_conf).
        base_conf=jnp.where(sd & (host.snap_conf != 0), host.snap_conf,
                            log.base_conf),
        last=jnp.where(sd, jnp.where(tail_matches, log.last, host.snap_idx),
                       log.last),
    )
    commit = jnp.where(sd, jnp.maximum(commit, host.snap_idx), commit)

    # Compaction grant from host (snapshot taken at compact_to): raise floor,
    # but never past commit (reference compactLog gates on the snapshot
    # milestone, RaftRoutine.java:365-400).  The milestone term is read from
    # the ring *before* the floor moves — and so is the milestone CONFIG
    # (the latest config entry at/under the new floor folds into
    # base_conf before its ring slot leaves the live window).
    ct = jnp.minimum(host.compact_to, commit)
    do_c = active & (ct > log.base)
    ct_term = ring_term_at(log, ct)
    # ONE [G, L] conf sweep serves both consumers: the milestone config
    # (latest conf entry at/under the new floor, folded into base_conf)
    # and the post-compaction active view C1 — what the timers (campaign
    # eligibility), transfer intake and config-entry intake below act
    # on.  (C0 is the state cache; this is the tick's only sweep.)
    jL = jnp.arange(L, dtype=I32)[None, :]
    sw_idx = log.last[:, None] - jnp.remainder(log.last[:, None] - jL, L)
    sw_isc = (sw_idx > log.base[:, None]) & (log.conf != 0)
    cidx_all = jnp.where(sw_isc, sw_idx, 0).max(axis=1)
    w_all = jnp.take_along_axis(
        log.conf, jnp.remainder(cidx_all, L)[:, None], axis=1)[:, 0]
    cidx_ct = jnp.where(sw_isc & (sw_idx <= ct[:, None]), sw_idx, 0) \
        .max(axis=1)
    w_ct = jnp.take_along_axis(
        log.conf, jnp.remainder(cidx_ct, L)[:, None], axis=1)[:, 0]
    ct_conf = jnp.where(cidx_ct > 0, w_ct, log.base_conf)
    log = log.replace(base=jnp.where(do_c, ct, log.base),
                      base_term=jnp.where(do_c, ct_term, log.base_term),
                      base_conf=jnp.where(do_c, ct_conf, log.base_conf))
    live1 = cidx_all > log.base          # post-move floor
    cidx1 = jnp.where(live1, cidx_all, 0)
    w1 = jnp.where(live1, w_all, log.base_conf)
    voters1 = conf_voters_of(w1)
    vnew1 = conf_new_of(w1)
    lrn1 = conf_learners_of(w1)

    # ---- 6. AppendEntries responses (leader bookkeeping) -------------------
    # (reference Leader.java:224-243 + Leadership.State.updateIndex:75-114.)
    # Pure elementwise [G, P] updates.
    aer_r = (inbox.aer_valid & active[None, :] & (role == LEADER)[None, :] &
             (inbox.aer_term == term[None, :])).T                # [G, P]
    aer_suc = aer_r & inbox.aer_success.T
    aer_fail = aer_r & ~inbox.aer_success.T
    aer_m = inbox.aer_match.T
    m_new = jnp.maximum(match_idx, aer_m)
    match_idx = jnp.where(aer_suc, m_new, match_idx)
    nx = jnp.where(aer_suc, jnp.maximum(next_idx, m_new + 1),
                   jnp.where(aer_fail,
                             jnp.clip(aer_m + 1, 1, next_idx), next_idx))
    # Follower fell below our compaction floor -> needs a snapshot
    # (reference Leadership.java:111-113 pendingInstallation trigger).
    need_snap = jnp.where(aer_r, aer_fail & (nx <= log.base[:, None]),
                          need_snap)
    next_idx = jnp.maximum(nx, log.base[:, None] + 1)
    # Pipeline accounting: data-batch replies release a data slot;
    # heartbeat replies release a heartbeat slot ONLY when they echo the
    # occupancy flag (aer_empty & aer_occ) — the AE itself carries
    # whether it occupied a slot (ae_occ, phase 9; symmetric with
    # is_probe), so a reply to a window-full EXEMPT heartbeat can never
    # free a slot whose real ack was lost, and the window count stays
    # exact (ADVICE r4).  A rejection aborts the whole window so
    # replication resumes from the clamped next_idx (reference: nextIndex
    # rollback cancels optimistic sends, Leadership.updateIndex:75-114).
    aer_ack = aer_r & ~inbox.aer_empty.T
    aer_hb_ack = aer_r & inbox.aer_empty.T & inbox.aer_occ.T
    inflight = jnp.where(aer_ack, jnp.maximum(inflight - 1, 0), inflight)
    hb_inflight = jnp.where(aer_hb_ack, jnp.maximum(hb_inflight - 1, 0),
                            hb_inflight)
    inflight = jnp.where(aer_fail, 0, inflight)
    hb_inflight = jnp.where(aer_fail, 0, hb_inflight)
    send_next = jnp.where(aer_fail, next_idx, send_next)
    # Health evidence: any reply — grant or rejection — proves the peer
    # reachable (reference statSuccess on every response incl. rejects,
    # Leadership.java:53-63).
    ok_at = jnp.where(aer_r, now, ok_at)
    fail_streak = jnp.where(aer_r, 0, fail_streak)

    # ---- 6b. read-barrier evidence ----------------------------------------
    # A same-term AE reply proves its sender followed us when it processed
    # the AE (it reset its election timer, phase 4) — the leadership
    # confirmation the ReadIndex barrier needs.  Two anchorings, both
    # comparing only values of OUR OWN clock (stall-induced per-node
    # drift cannot skew them):
    #
    # * lease (cfg.read_lease): store the RECEIPT tick, gated by the echo
    #   freshness bound `now - aer_tick <= read_fresh_ticks`.  Receipt
    #   anchoring is stall-safe by the fault model itself: in-flight
    #   messages addressed to a stalled node are LOST, so anything in the
    #   inbox was sent one live tick ago and the follower processed our
    #   AE at most `read_fresh_ticks - 1` global ticks before receipt
    #   (duplicate-delivery chains add one tick each and require the
    #   receiver awake every hop, so the freshness bound caps them at one
    #   hop).  Term monotonicity then closes the proof: a write acked by
    #   a newer-term leader before a batch's stamp needs a majority at
    #   the newer term strictly earlier, which must intersect our
    #   same-term evidence majority — a node cannot return to an older
    #   term.  No clock-drift assumption anywhere.
    # * strict ReadIndex: store the ECHOED send tick, so release requires
    #   acks to heartbeats SENT at/after the stamp (the textbook
    #   dedicated confirmation round) — sound under arbitrary transport
    #   delay, one round trip slower.
    #
    # host.read_veto (host runtime detected a wall-clock tick gap) drops
    # stored AND same-tick evidence: a paused host's inbox may hold acks
    # queued before the pause, which receipt anchoring must not trust.
    read_evid = s.read_evid
    if cfg.read_lease:
        evid_hit = aer_r & ~self_hot & \
            (now - inbox.aer_tick.T <= cfg.read_fresh_ticks)
        evid_val = jnp.broadcast_to(now, (G, P))
    else:
        evid_hit = aer_r & ~self_hot
        evid_val = jnp.maximum(read_evid, inbox.aer_tick.T)
    read_evid = jnp.where(evid_hit, evid_val, read_evid)
    read_evid = jnp.where(host.read_veto, jnp.zeros_like(read_evid),
                          read_evid)

    # Snapshot response: success means the follower now covers our offered
    # milestone — resume log replication from just past our floor (reference
    # accomplishInstallation -> normal AppendEntries flow,
    # RaftRoutine.java:451-475).  Failure = still downloading; keep pending.
    isr_r = (inbox.isr_valid & active[None, :] & (role == LEADER)[None, :] &
             (inbox.isr_term == term[None, :])).T                # [G, P]
    isr_ok = isr_r & inbox.isr_success.T
    need_snap = jnp.where(isr_ok, False, need_snap)
    next_idx = jnp.where(isr_ok,
                         jnp.maximum(next_idx, log.base[:, None] + 1),
                         next_idx)
    match_idx = jnp.where(isr_ok, jnp.maximum(match_idx, log.base[:, None]),
                          match_idx)
    # Only replies to WINDOW-OCCUPYING offers release a slot (probe
    # re-offers are echoed as isr_probe — symmetric with aer_empty).
    isr_ack = isr_r & ~inbox.isr_probe.T
    inflight = jnp.where(isr_ack, jnp.maximum(inflight - 1, 0), inflight)
    ok_at = jnp.where(isr_r, now, ok_at)
    fail_streak = jnp.where(isr_r, 0, fail_streak)
    # The pipeline head never trails the ack base.
    send_next = jnp.maximum(send_next, next_idx)

    # ---- 6c. CheckQuorum step-down (cfg.check_quorum) ---------------------
    # "Paxos vs Raft" (arXiv:2004.05074) leader stickiness: an inbound-cut
    # leader hears no higher term — phase 1 can never depose it — yet its
    # outbound heartbeats keep suppressing every follower's election
    # timer, so the group is hostage until the cut heals.  Remedy: track
    # the last-heard tick per peer (any valid inbound RPC counts,
    # term-independent — even a stale reply proves the link alive) and
    # step down when one election timeout passes without contact from a
    # voter quorum (joint: both sets, same §6 rule as every quorum).
    # Placement before 7b/8/8b makes the containment automatic: the
    # pending transfer aborts (7b keep_x), submissions are refused
    # (phase 8 role gate), and — the safety-critical part — phase 8b's
    # keep_reads drops the pending lease reads AND zeroes read_evid, so a
    # deposed-but-unaware leader can neither strand writes nor serve
    # stale reads off a dead lease.  The stepped-down node re-arms its
    # election timer and campaigns through PreVote, which cannot disturb
    # a healthy majority's new leader (speculative terms never bump).
    qc = s.qc
    if cfg.check_quorum:
        from ..ops.quorum import contact_quorum
        heard_any = (inbox.ae_valid | inbox.aer_valid | inbox.rv_valid
                     | inbox.rvr_valid | inbox.is_valid | inbox.isr_valid
                     | inbox.tn_valid).T & active[:, None] & ~self_hot
        heard = jnp.where(heard_any, now, qc.heard)
        # The window anchors at election win; a due check that passes
        # advances it (fresh contact must then arrive within the NEXT
        # window — etcd's recent-active reset, vectorized).
        since = jnp.where(vote_win, now, qc.since)
        cq_due = active & (role == LEADER) \
            & (now - since >= cfg.election_ticks)
        cq_ok = contact_quorum(voters1, vnew1, me, heard, since)
        cq_down = cq_due & ~cq_ok
        since = jnp.where(cq_due & cq_ok, now, since)
        role = jnp.where(cq_down, FOLLOWER, role)
        leader_id = jnp.where(cq_down, NIL, leader_id)
        elect_dl = jnp.where(cq_down, now + rand_to, elect_dl)
        qc = qc.replace(heard=heard, since=since)
        # Vetoed lease reads: everything pending in the FIFO at the
        # moment of step-down (8b reads the same s.rq_* and will abort
        # them via keep_reads — this lane just counts what was saved).
        K_cq = cfg.read_slots
        jcol = jnp.arange(K_cq, dtype=I32)[None, :]
        pend_slot = jnp.remainder(s.rq_head[:, None] + jcol, K_cq)
        pend_n = jnp.where(jcol < s.rq_len[:, None],
                           jnp.take_along_axis(s.rq_n, pend_slot, axis=1),
                           0).sum(axis=1)
        cq_veto = jnp.where(cq_down, pend_n, 0)
    else:
        cq_down = None
        cq_veto = None

    # ---- 7. timers ---------------------------------------------------------
    # (reference RaftRoutine.electionTimeout:65-77 -> Follower.onTimeout:
    # 156-168: PreVote round if enabled, else direct candidacy; candidate
    # timeout restarts the election at term+1, Candidate.onTimeout:82-88.)
    # Only VOTERS campaign: learners and removed slots replicate but never
    # start elections (§6 — a server not in the newest config of its own
    # log stays quiet; it still grants votes and accepts AEs).
    voter_self = (jnp.right_shift(voters1 | vnew1, me) & 1) > 0
    expired = active & (now >= elect_dl) & (role != LEADER) & voter_self
    if cfg.pre_vote:
        start_pre = expired & ((role == FOLLOWER) | (role == PRE_CANDIDATE))
        timer_cand = expired & (role == CANDIDATE)
    else:
        start_pre = jnp.zeros((G,), jnp.bool_)
        timer_cand = expired
    # TimeoutNow (§3.10 leadership transfer): a caught-up voter told to
    # campaign does so IMMEDIATELY — no PreVote round, no waiting out the
    # election timer (the whole point: the old leader is alive and its
    # heartbeats would defeat PreVote's leader-stickiness check).  The
    # term check fences stale/duplicate copies: once the target bumps to
    # term+1, re-sent TimeoutNows at the old term are ignored.
    tn_cand = ((inbox.tn_valid & active[None, :] & not_me_col
                & (inbox.tn_term == term[None, :])).any(axis=0)
               & voter_self & (role != LEADER))
    start_pre = start_pre & ~tn_cand
    timer_cand = timer_cand | tn_cand
    term = jnp.where(timer_cand, term + 1, term)
    voted = jnp.where(timer_cand, me, voted)
    role = jnp.where(timer_cand, CANDIDATE, jnp.where(start_pre, PRE_CANDIDATE, role))
    leader_id = jnp.where(timer_cand | start_pre, NIL, leader_id)
    votes = jnp.where(timer_cand[:, None], self_hot, votes)
    prevotes = jnp.where(start_pre[:, None], self_hot, prevotes)
    elect_dl = jnp.where(timer_cand | start_pre, now + rand_to, elect_dl)

    became_cand = become_cand_pv | timer_cand
    last_term_v = ring_term_at(log, log.last)

    # ---- 7b. leadership-transfer intake/abort (§3.10) ---------------------
    # A pending transfer lives only within one continuous leadership at
    # one term and at most one election timeout long; anything else —
    # step-down, term bump, the deadline — aborts it (the host fails the
    # caller's future; the transfer may still have succeeded, which the
    # caller observes via the leader hint, same contract as a submit
    # abort).  While pending, client submissions and config changes are
    # FENCED so the target's catch-up condition (match == last) is a
    # stable target.
    pend0 = s.xfer_to != NIL
    keep_x = (pend0 & active & (role == LEADER) & (term == s.term)
              & (now < s.xfer_dl))
    xfer_abort = pend0 & ~keep_x
    xfer_to = jnp.where(keep_x, s.xfer_to, NIL)
    xfer_dl = jnp.where(keep_x, s.xfer_dl, 0)
    tgt = host.xfer_target
    tgt_voter = (jnp.right_shift(voters1 | vnew1,
                                 jnp.clip(tgt, 0, P - 1)) & 1) > 0
    take_x = (active & (role == LEADER) & (xfer_to == NIL)
              & (tgt >= 0) & (tgt < P) & (tgt != me) & tgt_voter)
    xfer_to = jnp.where(take_x, tgt, xfer_to)
    xfer_dl = jnp.where(take_x, now + cfg.election_ticks, xfer_dl)
    fenced = xfer_to != NIL

    # ---- 8. client submissions --------------------------------------------
    # (reference RaftStub.submit -> Leader.acceptCommand -> log.newEntry,
    # RaftStub.java:65-74, Leader.java:128-140, RocksLog.java:82-89.)
    # Capacity gate: the ring must keep (last - base) <= L.  A pending
    # leadership transfer fences intake (7b).
    free = L - (log.last - log.base)
    n_acc = jnp.where(active & (role == LEADER) & ~fenced,
                      jnp.clip(host.submit_n, 0, jnp.minimum(free, S)), 0)
    sub_start = log.last + 1
    scol = jnp.arange(S, dtype=I32)[None, :]
    sidx = log.last[:, None] + 1 + scol
    smask = scol < n_acc[:, None]
    new_ring = ring_write_batch(log.term, sidx,
                                jnp.broadcast_to(term[:, None], (G, S)), smask)
    new_cring = ring_write_batch(log.conf, sidx, jnp.zeros((G, S), I32),
                                 smask)
    log = log.replace(term=new_ring, conf=new_cring, last=log.last + n_acc)
    app_from = jnp.where((n_acc > 0) & (app_from == 0), sub_start, app_from)
    app_to = jnp.where(n_acc > 0, log.last, app_to)

    # ---- 8b. linearizable read plane: intake + barrier release ------------
    # ReadIndex (Raft dissertation §6.4), vectorized: a read batch is
    # STAMPED with the leader's current commit index and RELEASED once a
    # majority confirms our leadership at/after the stamp (evidence from
    # phase 6b) — reads never touch the log.  Stamping with the
    # pre-phase-10 commit is sound: a write acknowledged to any client
    # before this tick was committed by the end of an earlier tick, so
    # the carried-in commit already covers it.
    from ..ops.quorum import read_barrier_release
    K = cfg.read_slots
    # Pending reads live only within one continuous leadership at one
    # term: any role/term change drops them (the host fails them with
    # NotLeader; reads never enter the log, so the retry is always safe).
    keep_reads = active & (role == LEADER) & (term == s.term)
    read_abort = (s.rq_len > 0) & ~keep_reads
    rq_head = jnp.where(keep_reads, s.rq_head, 0)
    rq_len = jnp.where(keep_reads, s.rq_len, 0)
    read_evid = jnp.where(keep_reads[:, None], read_evid, 0)
    rq_idx, rq_stamp, rq_n = s.rq_idx, s.rq_stamp, s.rq_n
    # Intake: one offered batch per group per tick, accepted whole when a
    # FIFO slot is free and our §8 no-op has committed (commit >= own_from
    # — a fresh leader's commit index may lag entries committed by its
    # predecessors until its own-term entry commits, Raft §5.4.2; serving
    # before that could miss them).
    n_read = jnp.where(keep_reads & (commit >= own_from) & (rq_len < K),
                       jnp.maximum(host.read_n, 0), 0)
    read_acc = n_read > 0
    rows_g = jnp.arange(G, dtype=I32)
    slot_in = jnp.where(read_acc, jnp.remainder(rq_head + rq_len, K), K)
    rq_idx = rq_idx.at[rows_g, slot_in].set(commit, mode="drop")
    rq_stamp = rq_stamp.at[rows_g, slot_in].set(now, mode="drop")
    rq_n = rq_n.at[rows_g, slot_in].set(n_read, mode="drop")
    rq_len = rq_len + read_acc.astype(I32)
    read_index_out = jnp.where(read_acc, commit, 0)
    # Release (ops/quorum.py): with the lease, evidence received THIS
    # tick carries receipt == now == the fresh batch's stamp, so a
    # heartbeat-ack burst releases a same-tick read with zero extra round
    # trips — the lease fast path IS the general rule at its freshness
    # limit.  Strict mode can only release on a later tick's echo.
    n_rel, n_served = read_barrier_release(
        voters1, vnew1, me, read_evid, rq_stamp, rq_head, rq_len, rq_n)
    rq_head = jnp.remainder(rq_head + n_rel, K)
    rq_len = rq_len - n_rel
    read_lease_hit = read_acc & (n_rel > 0) & (rq_len == 0)
    # A batch left pending kicks an immediate barrier heartbeat (phase 9)
    # instead of waiting out the cadence: release latency is one round
    # trip, not heartbeat_ticks + one round trip.
    read_kick = read_acc & (rq_len > 0)

    # ---- 8c. membership-change intake + automatic joint leave (§6) --------
    # A change request (HostInbox.conf_voters/conf_learners, the TARGET
    # config) becomes ONE log entry: a joint C_old,new entry when the
    # voter set moves, a simple entry when only learners change.  One
    # change in flight per group: intake is fenced while the latest
    # config entry is uncommitted, while joint, and while a leadership
    # transfer is pending.  When the joint entry commits, the C_new leave
    # entry is appended AUTOMATICALLY — the leader walks §6's two-entry
    # protocol without host round-trips.  Config entries take effect on
    # append (the next latest_conf derivation sees them): the leader
    # counts the very commit that seals a joint entry under BOTH sets.
    full_bits = jnp.asarray((1 << P) - 1, I32)
    hv = host.conf_voters & full_bits
    hl = host.conf_learners & full_bits & ~hv
    joint1 = vnew1 != 0
    pending1 = cidx1 > commit
    space = log.last - log.base < L
    may_append = active & (role == LEADER) & ~pending1 & space
    # One pack covers both request kinds: a learner-only change (target
    # voters == current) packs voters_new = 0 (simple entry).
    enter_word = conf_pack(voters1, jnp.where(hv == voters1, 0, hv), hl)
    want_enter = (may_append & ~joint1 & ~fenced & (hv != 0)
                  & (enter_word != w1))
    want_leave = may_append & joint1
    leave_word = conf_pack(vnew1, 0, lrn1)
    conf_app = want_enter | want_leave
    app_word = jnp.where(want_leave, leave_word, enter_word)
    nidx = log.last + 1
    log = log.replace(
        term=ring_write_batch(log.term, nidx[:, None], term[:, None],
                              conf_app[:, None]),
        conf=ring_write_batch(log.conf, nidx[:, None], app_word[:, None],
                              conf_app[:, None]),
        last=log.last + conf_app.astype(I32))
    conf_app_idx = jnp.where(conf_app, nidx, 0)
    conf_app_term = jnp.where(conf_app, term, 0)
    conf_app_word = jnp.where(conf_app, app_word, 0)
    app_from = jnp.where(conf_app & (app_from == 0), nidx, app_from)
    app_to = jnp.where(conf_app, log.last, app_to)

    # Membership view C2: the end-of-tick active config — replication
    # fan-out, readiness, vote-solicitation targets and the commit quorum
    # all run against it.
    cidx2 = jnp.where(conf_app, nidx, cidx1)
    w2 = jnp.where(conf_app, app_word, w1)
    voters2 = conf_voters_of(w2)
    vnew2 = conf_new_of(w2)
    lrn2 = conf_learners_of(w2)
    member2 = mask_bits(voters2 | vnew2 | lrn2, P)              # [G, P]

    # ---- 9. replication fan-out -------------------------------------------
    # (reference Leader.replicateLog:142-245 — the hot loop, now a dense
    # (group x peer) batch build straight from the HBM ring, pipelined up to
    # `inflight_limit` un-acked batches per peer, Leadership.java:10-11.)
    # Fan-out only to MEMBER slots (voters, incoming voters, learners):
    # removed/never-added slots get no AEs, no heartbeats, no snapshot
    # offers — the membership masks gate the replication plane itself.
    lead_peer = (active & (role == LEADER))[:, None] & ~self_hot & member2
    # RPC timeout: the window has been un-acked too long.  Failure evidence
    # for the health stats (reference statFailure on unreachable,
    # Leadership.java:65-73) + window reset so replication restarts from the
    # ack base (reference AsyncFuture timeout, Async.java:177-256).
    # RPC timeout — the ONLY failure-evidence source, anchored to OUR OWN
    # last send on OUR OWN tick clock (reference: per-request Async
    # timeout feeding statFailure, Async.java:177-256, Leadership.java:
    # 65-73).  Occupying heartbeats (below) keep this armed on idle
    # leaders: a dead peer accumulates un-acked heartbeats and times out
    # exactly like a lost data window.  No reply-staleness heuristics —
    # they false-positive under free-running tick drift and wedge the
    # readiness gate shut via the recovery cool-down.
    timed_out = lead_peer & (inflight + hb_inflight > 0) & \
        (now - sent_at >= cfg.rpc_timeout_ticks)
    fail_streak = jnp.where(timed_out, fail_streak + 1, fail_streak)
    fail_at = jnp.where(timed_out, now, fail_at)
    send_next = jnp.where(timed_out, next_idx, send_next)
    inflight = jnp.where(timed_out, 0, inflight)
    hb_inflight = jnp.where(timed_out, 0, hb_inflight)

    heartbeat = (role == LEADER) & ((now >= hb_due) | read_kick)
    has_data = (log.last[:, None] >= send_next) & ~need_snap
    n_avail = jnp.clip(log.last[:, None] - send_next + 1, 0, B)  # [G, P]
    # Data flows whenever the window has room; empty heartbeat AEs keep
    # the follower's election timer fed on the normal cadence even while
    # acks are in flight.  Their prev = send_next - 1 assumes the in-flight
    # batches arrive first — guaranteed by the transport's per-source
    # in-order delivery (transport/inbox.py); under loss the follower
    # rejects and the window resets, same as any failed AE.
    can_send = (inflight + hb_inflight) < cfg.inflight_limit
    send_data = lead_peer & ~need_snap & has_data & can_send
    # Heartbeat capacity reservation (reference: the in-flight budget is
    # divided for heartbeats so they keep flowing, Leader.java:162,
    # Leadership.java:10-11): an empty AE goes out on the heartbeat cadence
    # on every leader lane not shipping data this tick — INCLUDING lanes
    # whose window is full of lost batches, so a wedged window can never
    # starve the followers' election timers into a spurious election (any
    # valid AE at the leader's term resets the timer, phase 4).  While the
    # window has room the heartbeat OCCUPIES a slot (in the dedicated
    # hb_inflight lane, released by its aer_empty-echoed reply), which is
    # what arms the RPC-timeout failure detector on idle leaders; when the
    # window is full it goes out slot-exempt, keeping followers fed while
    # the stuck batches carry the timeout evidence.
    send_hb = lead_peer & ~need_snap & heartbeat[:, None] & ~send_data
    hb_occupy = send_hb & can_send
    send_ae = send_data | send_hb                                # [G, P]
    n_send = jnp.where(send_data, n_avail, 0)
    prev = send_next - 1
    # One fused gather for all peers' batches: [G, P*B] -> [P, G, B].
    flat_idx = (send_next[:, :, None] + col[None, :, :]).reshape(G, P * B)
    ents_all = ring_terms_batch(log, flat_idx).reshape(G, P, B)
    cents_all = ring_conf_batch(log, flat_idx).reshape(G, P, B)
    prev_terms = ring_terms_batch(log, prev).T                   # [P, G]
    out_ae_valid = send_ae.T
    out_ae_term = jnp.broadcast_to(term[None, :], (P, G))
    out_ae_prev_idx = prev.T
    out_ae_prev_term = prev_terms
    out_ae_commit = jnp.broadcast_to(commit[None, :], (P, G))
    out_ae_n = n_send.T
    out_ae_ents = jnp.swapaxes(ents_all, 0, 1)                   # [P, G, B]
    out_ae_cents = jnp.swapaxes(cents_all, 0, 1)                 # [P, G, B]
    out_ae_occ = hb_occupy.T
    # Send tick, echoed back as aer_tick (read-barrier evidence, 6b).
    out_ae_tick = jnp.broadcast_to(now, (P, G)).astype(I32)
    # Snapshot offer for laggards (reference Leader.java:168-190); occupies
    # the whole window (one offer at a time), re-offered on the heartbeat
    # cadence while un-acked — the re-offer is window-exempt like a
    # heartbeat (reference: the heartbeat replicateLog pass re-enters the
    # install branch, Leader.java:162-190), so the follower's election
    # timer stays fed through a long download even if offer acks are lost.
    send_is_win = lead_peer & need_snap & (inflight + hb_inflight == 0)
    send_is = send_is_win | (lead_peer & need_snap & heartbeat[:, None])
    out_is_valid = send_is.T
    out_is_term = jnp.broadcast_to(term[None, :], (P, G))
    out_is_idx = jnp.broadcast_to(log.base[None, :], (P, G))
    out_is_last_term = jnp.broadcast_to(log.base_term[None, :], (P, G))
    out_is_probe = (send_is & ~send_is_win).T
    # The offered milestone's config rides the offer: it becomes the
    # installer's base_conf (via the host snap_conf round trip).
    out_is_conf = jnp.broadcast_to(log.base_conf[None, :], (P, G))
    # Window accounting: data batches and the first snapshot offer occupy
    # data slots; in-window heartbeats occupy heartbeat slots; window-full
    # heartbeats and snapshot re-offers are slot-exempt (see above).  Any
    # occupying send refreshes the send clock.
    occupy = send_data | send_is_win
    send_next = jnp.where(send_data, send_next + n_send, send_next)
    inflight = jnp.where(occupy, inflight + 1, inflight)
    hb_inflight = jnp.where(hb_occupy, hb_inflight + 1, hb_inflight)
    sent_at = jnp.where(occupy | hb_occupy, now, sent_at)
    hb_due = jnp.where(heartbeat, now + cfg.heartbeat_ticks, hb_due)

    # Leader readiness (reference Leader.isReady, Leader.java:52-64 +
    # Leadership.isReady/isUnhealthy, Leadership.java:44-51): a follower
    # counts as healthy once it has replied this leadership (ok_at > 0), is
    # not mid-snapshot-install, its timeout streak is within the critical
    # point, and its last failure is outside the recovery cool-down.
    healthy = (ok_at > 0) & ~need_snap & ~self_hot
    if cfg.avail_crit > 0:
        healthy = healthy & (fail_streak <= cfg.avail_crit)
    if cfg.recovery_ticks > 0:
        healthy = healthy & ((fail_at == 0) |
                             (now - fail_at >= cfg.recovery_ticks))
    # Readiness is a masked quorum over the ACTIVE config (joint: both
    # sets); self counts iff self is a voter.  A pending leadership
    # transfer reports not-ready — intake is fenced anyway, and the
    # host's refusal gate should say so before the queue does.
    ready = (active & (role == LEADER) & ~fenced &
             dual_quorum((healthy & lead_peer) | self_hot, voters2, vnew2))

    # TimeoutNow dispatch (7b intake): once the target's match covers our
    # whole log, tell it to campaign.  Re-sent every tick while the
    # condition holds — duplicates are fenced by the receiver's term
    # check, and loss costs one tick, not the transfer.
    tgt_match = jnp.take_along_axis(
        match_idx, jnp.clip(xfer_to, 0, P - 1)[:, None], axis=1)[:, 0]
    xfer_fire = (active & (role == LEADER) & (xfer_to != NIL)
                 & (tgt_match >= log.last))
    out_tn_valid = (peer_ids[:, None] == xfer_to[None, :]) & xfer_fire[None, :]
    out_tn_term = jnp.broadcast_to(term[None, :], (P, G))

    # Election broadcasts (PreVote at speculative term+1 carrying our log
    # position, reference Follower.prepareElection:223-279; RequestVote at
    # the new term, Candidate.startElection:90-143).
    bcast = (became_cand | start_pre) & active
    # Solicit only VOTER slots (both sets while joint): learner grants
    # would never count, so they are not asked.
    out_rv_valid = bcast[None, :] & not_me_col \
        & mask_bits(voters2 | vnew2, P).T
    out_rv_term = jnp.broadcast_to(
        jnp.where(start_pre, term + 1, term)[None, :], (P, G))
    out_rv_last_idx = jnp.broadcast_to(log.last[None, :], (P, G))
    out_rv_last_term = jnp.broadcast_to(last_term_v[None, :], (P, G))
    out_rv_prevote = jnp.broadcast_to(start_pre[None, :], (P, G))

    # ---- 10. commit advance ------------------------------------------------
    # Quorum median over the match matrix with self = last (reference
    # Leadership.majorIndices:116-130), gated by the commit-only-own-term
    # rule (reference Leader.tryCommit:256-261, Raft §5.4.2).  Runs as the
    # Pallas scan when cfg.use_pallas (ops/quorum.py), else inline jnp —
    # identical semantics either way.
    from ..ops.quorum import quorum_commit
    # Own-match durability gate (HostInbox.durable_tail): with the
    # pipelined runtime, this scan may be executing while the PREVIOUS
    # tick's WAL fsync is still in flight — so the self column counts only
    # the fsynced prefix, never the raw device tail.  An entry therefore
    # needs (majority - 1) durable FOLLOWER acks plus OUR durable copy
    # before it can commit — the ack-after-fsync contract, enforced
    # in-kernel rather than by host-phase ordering alone.  None (every
    # fused-scan path, and the serial runtime's default) keeps the
    # classic self = log.last.
    self_match = log.last if host.durable_tail is None \
        else jnp.minimum(log.last, host.durable_tail)
    match_full = jnp.where(self_hot, self_match[:, None], match_idx)
    commit = quorum_commit(cfg, match_full, log, commit, own_from,
                           active & (role == LEADER), voters2, vnew2)
    match_idx = match_full

    # §6 epilogue: a leader whose committed SIMPLE config no longer
    # includes it steps down (it managed the cluster through the joint
    # phase and just committed the C_new that removes it — this tick's
    # AEs already carry that commit to the survivors).
    resigned = (active & (role == LEADER) & (vnew2 == 0)
                & (cidx2 <= commit)
                & ((jnp.right_shift(voters2, me) & 1) == 0))
    role = jnp.where(resigned, FOLLOWER, role)
    leader_id = jnp.where(resigned, NIL, leader_id)
    elect_dl = jnp.where(resigned, now + rand_to, elect_dl)

    # ---- flight recorder ---------------------------------------------------
    # Branchless per-group event-ring writes (cfg.trace_depth; zero cost
    # when 0 — the whole block is a trace-time branch like debug_checks).
    # Emission order within a tick is canonical and mirrors phase order:
    # the scalar oracle (testkit/oracle.py) emits the identical stream, so
    # decoded device timelines are parity-checked tick-for-tick.  All
    # records carry the END-of-tick term; TR_CRASH_RESTART is written by
    # types.crash_restart before the step runs.
    trace = s.trace
    if cfg.trace_depth:
        from .types import (
            TR_BECAME_CANDIDATE, TR_BECAME_LEADER, TR_BECAME_PRE_CANDIDATE,
            TR_COMMIT_ADVANCE, TR_CONF_CHANGE_COMMIT, TR_CONF_CHANGE_ENTER,
            TR_LEADER_TRANSFER, TR_READ_RELEASE, TR_SNAPSHOT_INSTALL,
            TR_STEPPED_DOWN, TR_TERM_BUMP,
        )
        D = cfg.trace_depth
        NE = 11
        # All of one tick's events land in ONE batched scatter per lane:
        # event e's ring slot is n + (#events of this tick that fired
        # before it), so intra-tick order IS the canonical order above.
        # Slots stay distinct within a group because at most NE events
        # fire per tick and trace_depth >= NE + 1 (EngineConfig
        # post-init).
        ev_masks = jnp.stack([                               # [G, NE]
            term != s.term,
            (s.role == LEADER) & (role != LEADER),
            start_pre,
            became_cand,
            vote_win,
            sd,
            commit > s.commit,
            n_rel > 0,
            # Membership plane: active-config change (enter/leave/learner/
            # adoption/rollback), config-entry commit, TimeoutNow sent.
            (w2 != w0) | (cidx2 != cidx0),
            (cidx2 > 0) & (s.commit < cidx2) & (commit >= cidx2),
            xfer_fire,
        ], axis=1) & active[:, None]
        ev_kinds = jnp.asarray([
            TR_TERM_BUMP, TR_STEPPED_DOWN, TR_BECAME_PRE_CANDIDATE,
            TR_BECAME_CANDIDATE, TR_BECAME_LEADER, TR_SNAPSHOT_INSTALL,
            TR_COMMIT_ADVANCE, TR_READ_RELEASE,
            TR_CONF_CHANGE_ENTER, TR_CONF_CHANGE_COMMIT,
            TR_LEADER_TRANSFER,
        ], I32)
        ev_aux = jnp.stack([                                 # [G, NE]
            s.term, leader_id, jnp.zeros((G,), I32),
            # Candidacy cause: 0 prevote majority / 1 timer / 2 TimeoutNow
            # (tn_cand implies timer_cand, so the sum is exactly 2).
            timer_cand.astype(I32) + tn_cand.astype(I32),
            noop_idx, host.snap_idx,
            commit, n_served,
            w2, cidx2, xfer_to,
        ], axis=1)
        ev_i32 = ev_masks.astype(I32)
        prior = jnp.cumsum(ev_i32, axis=1) - ev_i32          # fired before e
        n_new = ev_i32.sum(axis=1)                           # [G]
        # Ring write WITHOUT a scatter: a vmapped scatter inside the
        # fused scan lowers ~17x slower on CPU (measured; the one-hot-
        # over-D select ~3-6x).  Instead the fired events compact into a
        # dense NE-wide window ([G, NE, NE] one-hot, D-independent), and
        # the ring blends it in with one take_along_axis per varying lane
        # — the same gather idiom as ring_terms_batch.  Ring position d
        # takes window offset (d - n) mod D when that offset < n_new;
        # tick/term are uniform across a tick's events, so those two
        # lanes need only the write mask.
        off_hit = (prior[:, :, None] ==
                   jnp.arange(NE, dtype=I32)[None, None, :]) \
            & ev_masks[:, :, None]                           # [G, NE, NE]
        win = lambda vals: jnp.where(
            off_hit, vals[:, :, None], 0).sum(axis=1)        # [G, NE]
        rel = jnp.remainder(jnp.arange(D, dtype=I32)[None, :]
                            - jnp.remainder(trace.n, D)[:, None], D)
        write = rel < n_new[:, None]                         # [G, D]
        rel_idx = jnp.minimum(rel, NE - 1)

        def put(ring, vals):                                 # vals [G, NE]
            return jnp.where(
                write, jnp.take_along_axis(win(vals), rel_idx, axis=1),
                ring)

        trace = trace.replace(
            tick=jnp.where(write, now, trace.tick),
            kind=put(trace.kind,
                     jnp.broadcast_to(ev_kinds[None, :], (G, NE))),
            term=jnp.where(write, term[:, None], trace.term),
            aux=put(trace.aux, ev_aux),
            n=trace.n + n_new,
        )

    # ---- heat lanes (cfg.heat) --------------------------------------------
    # Cumulative per-group activity counters for the host-side heat
    # registry: entries appended, RPCs emitted (all 7 kinds), commit
    # advance, reads served.  Branchless masked adds over lanes already
    # live at this point — the tick's outbox valid planes and the
    # append/commit/read results — so the extra work is a handful of [G]
    # sums; when off the subtree is None and nothing here traces.
    heat = s.heat
    if cfg.heat:
        sent_n = (out_ae_valid.astype(I32) + out_aer_valid.astype(I32)
                  + out_rv_valid.astype(I32) + out_rvr_valid.astype(I32)
                  + out_is_valid.astype(I32) + out_isr_valid.astype(I32)
                  + out_tn_valid.astype(I32)).sum(axis=0)
        appended_n = jnp.where(app_to > 0, app_to - app_from + 1, 0)
        heat = heat.replace(
            appended=heat.appended + appended_n,
            sent=heat.sent + sent_n,
            commits=heat.commits + (commit - s.commit),
            reads=heat.reads + n_served,
        )

    dirty = (term != old_term) | (voted != old_voted) | (log.last != old_last) \
        | (app_to > 0)

    # In-kernel invariant checks (cfg.debug_checks; zero cost when off —
    # the branch is resolved at trace time).  The vectorized analog of the
    # reference's hot-path AssertionErrors (ring/log continuity
    # RocksLog.java:175-187, monotonic matchIndex Leadership.java:76-81,
    # role/ballot sanity Follower.java:48-50): a violation pinpoints the
    # faulting phase by code instead of surfacing as downstream
    # divergence.  Codes in DEBUG_CODES; the host raises on any nonzero.
    debug_viol = jnp.zeros((G,), I32)
    if cfg.debug_checks:
        def flag(viol, cond, code):
            return jnp.where(active & cond & (viol == 0),
                             jnp.asarray(code, I32), viol)
        # 1: live window exceeds ring capacity (entries would alias).
        debug_viol = flag(debug_viol, log.last - log.base > L, 1)
        # 2: commit passed the log end.
        debug_viol = flag(debug_viol, commit > jnp.maximum(log.last, log.base), 2)
        # 3: term regressed within one step.
        debug_viol = flag(debug_viol, term < s.term, 3)
        # 4: a continuing leader's matchIndex moved backwards.
        debug_viol = flag(
            debug_viol,
            (s.role == LEADER) & (role == LEADER)
            & (match_idx < s.match_idx).any(axis=1), 4)
        # 5: candidate whose ballot is not itself.
        debug_viol = flag(debug_viol, (role == CANDIDATE) & (voted != me), 5)
        # 6: commit regressed.
        debug_viol = flag(debug_viol, commit < s.commit, 6)
        # 7: pipeline head behind the ack base.
        debug_viol = flag(debug_viol, (send_next < next_idx).any(axis=1), 7)
        # 8: read FIFO length out of range.
        debug_viol = flag(debug_viol, (rq_len < 0) | (rq_len > K), 8)
        # 9: active config with an empty voter set (a config entry can
        # never be built that way; seeing one means ring corruption).
        debug_viol = flag(debug_viol, voters2 == 0, 9)

    new_state = RaftState(
        node_id=s.node_id, now=now, rng=rng, active=active,
        term=term, role=role, voted_for=voted, leader_id=leader_id,
        commit=commit, applied=s.applied, log=log,
        next_idx=next_idx, match_idx=match_idx, send_next=send_next,
        own_from=own_from,
        inflight=inflight, hb_inflight=hb_inflight, sent_at=sent_at,
        need_snap=need_snap,
        ok_at=ok_at, fail_at=fail_at, fail_streak=fail_streak,
        votes=votes, prevotes=prevotes,
        elect_deadline=elect_dl, hb_due=hb_due,
        read_evid=read_evid,
        rq_idx=rq_idx, rq_stamp=rq_stamp, rq_n=rq_n,
        rq_head=rq_head, rq_len=rq_len,
        conf_idx=cidx2, conf_word=w2,
        xfer_to=xfer_to, xfer_dl=xfer_dl,
        trace=trace,
        heat=heat,
        qc=qc,
    )
    outbox = Messages(
        ae_valid=out_ae_valid, ae_term=out_ae_term,
        ae_prev_idx=out_ae_prev_idx, ae_prev_term=out_ae_prev_term,
        ae_commit=out_ae_commit, ae_n=out_ae_n, ae_ents=out_ae_ents,
        ae_cents=out_ae_cents, ae_occ=out_ae_occ, ae_tick=out_ae_tick,
        aer_valid=out_aer_valid, aer_term=out_aer_term,
        aer_success=out_aer_success, aer_match=out_aer_match,
        aer_empty=out_aer_empty, aer_occ=out_aer_occ,
        aer_tick=out_aer_tick,
        rv_valid=out_rv_valid, rv_term=out_rv_term,
        rv_last_idx=out_rv_last_idx, rv_last_term=out_rv_last_term,
        rv_prevote=out_rv_prevote,
        rvr_valid=out_rvr_valid, rvr_term=out_rvr_term,
        rvr_granted=out_rvr_granted, rvr_prevote=out_rvr_prevote,
        rvr_echo=out_rvr_echo,
        is_valid=out_is_valid, is_term=out_is_term, is_idx=out_is_idx,
        is_last_term=out_is_last_term, is_probe=out_is_probe,
        is_conf=out_is_conf,
        isr_valid=out_isr_valid, isr_term=out_isr_term,
        isr_success=out_isr_success, isr_probe=out_isr_probe,
        tn_valid=out_tn_valid, tn_term=out_tn_term,
    )
    info = StepInfo(
        submit_start=sub_start, submit_acc=n_acc, dirty=dirty,
        appended_from=app_from, appended_to=app_to, log_tail=log.last,
        commit=commit, leader=leader_id, ready=ready, snap_req=snap_req,
        snap_req_from=snap_from, snap_req_idx=snap_idx_o,
        snap_req_term=snap_term_o, snap_req_conf=snap_conf_o,
        noop_idx=noop_idx, noop_term=noop_term,
        read_acc=n_read, read_index=read_index_out,
        read_rel=n_rel, read_served=n_served,
        read_lease=read_lease_hit, read_abort=read_abort,
        conf_app_idx=conf_app_idx, conf_app_term=conf_app_term,
        conf_app_word=conf_app_word,
        conf_word=w2, conf_idx=cidx2, conf_pending=cidx2 > commit,
        xfer_fired=xfer_fire, xfer_abort=xfer_abort,
        debug_viol=debug_viol,
        cq_stepdown=cq_down, cq_veto=cq_veto,
    )
    return new_state, outbox, info
