"""Whole-cluster execution: N vectorized nodes in one SPMD program.

The reference runs one JVM per node and moves RPCs over per-peer TCP
connections (transport/EventBus.java, transport/EventNode.java).  Here an
entire N-node cluster is ``vmap(node_step)`` over a leading node axis, and
message routing is a pure array permutation: ``inbox[dst, src] =
outbox[src, dst]`` — a transpose of the first two axes.  Under a
``Mesh('node', 'group')`` sharding, that transpose lowers to an XLA
all-to-all over ICI, which is exactly the multi-chip deployment story: one
Raft node per device, consensus traffic riding the interconnect.

Fault injection (network partitions, message drops) is a boolean
connectivity matrix ANDed into every ``*_valid`` mask — the vectorized
analog of killing TCP links in the reference's manual chaos procedure
(README.md:28-33).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .step import node_step, ring_term_at
from .types import (
    LEADER, EngineConfig, FaultSchedule, HostInbox, Messages, RaftState,
    StepInfo, crash_restart, init_state,
)

_VALID_FIELDS = tuple(f.name for f in dataclasses.fields(Messages)
                      if f.name.endswith("_valid"))
# Message kinds (ae/aer/rv/rvr/is/isr) -> all fields of that RPC.  The
# leading underscore token of a field name is its kind; the nemesis
# duplicate-delivery merge replaces whole RPCs, so it must move every
# field of a kind together (a dup'd AE with a fresh reply's term lanes
# would be a frankenmessage).
_KIND_FIELDS = {}
for _f in dataclasses.fields(Messages):
    _KIND_FIELDS.setdefault(_f.name.split("_", 1)[0], []).append(_f.name)


def route(outboxes: Messages, conn: Optional[jax.Array] = None) -> Messages:
    """Deliver every node's outbox as next tick's inboxes.

    ``outboxes`` arrays are [N, P, G, ...] with axis 0 = sender, axis 1 =
    destination; the delivered inboxes are [N, P, G, ...] with axis 0 =
    destination, axis 1 = sender — a pure transpose.  ``conn[s, d]`` masks
    link s->d (False = partitioned / dropped).
    """
    swapped = jax.tree.map(lambda a: jnp.swapaxes(a, 0, 1), outboxes)
    if conn is None:
        return swapped
    # After the swap an element at [d, s] traveled s->d: mask with conn.T.
    mask = jnp.swapaxes(conn, 0, 1)
    reps = {}
    for name in _VALID_FIELDS:
        arr = getattr(swapped, name)
        reps[name] = arr & mask[..., None]
    return swapped.replace(**reps)


@partial(jax.jit, static_argnums=0, donate_argnums=(1, 2))
def cluster_step(cfg: EngineConfig, states: RaftState, inflight: Messages,
                 host: HostInbox, conn: jax.Array
                 ) -> Tuple[RaftState, Messages, StepInfo]:
    """One lockstep tick of the whole cluster.

    ``states``/``host``/returned ``StepInfo`` carry a leading node axis [N];
    ``inflight`` is the messages currently traveling (delivered this tick).
    """
    inboxes = route(inflight, conn)
    new_states, outboxes, infos = jax.vmap(partial(node_step, cfg))(
        states, inboxes, host)
    return new_states, outboxes, infos


def _node_bcast(mask: jax.Array, like: jax.Array) -> jax.Array:
    """Broadcast a [N] node mask against a leading-node-axis array."""
    return mask.reshape(mask.shape + (1,) * (like.ndim - 1))


def _select_nodes(mask: jax.Array, on_true, on_false):
    """Per-node pytree select: leaf[n] <- on_true[n] where mask[n]."""
    return jax.tree.map(
        lambda a, b: jnp.where(_node_bcast(mask, a), a, b), on_true, on_false)


def cluster_step_nemesis(cfg: EngineConfig, states: RaftState,
                         inflight: Messages, host: HostInbox,
                         prev_info: StepInfo, fault: FaultSchedule
                         ) -> Tuple[RaftState, Messages, StepInfo]:
    """One lockstep tick under one tick-slice of a :class:`FaultSchedule`.

    ``fault`` holds the per-tick arrays (``link_up`` [N, N], ``crash`` [N],
    ``stall`` [N], ``dup`` [N, N] — a scanned slice of the [T, ...]
    schedule).  Order of operations (the fault model of
    ``types.FaultSchedule``):

    1. crashed nodes reset volatile state to the durable frontier
       (:func:`crash_restart`) BEFORE delivery;
    2. in-flight messages deliver through ``link_up``; anything addressed
       to a crashed or stalled node is lost (it was down on arrival);
    3. live nodes step; stalled nodes are frozen wholesale — state, clock,
       timers, StepInfo — and send nothing;
    4. messages delivered over a ``dup`` link this tick are queued again
       for next tick unless the sender wrote a fresh RPC of the same kind
       over the lane (at-least-once delivery, exercising stale/duplicate
       RPC idempotency).

    Not jitted standalone: the nemesis path is always driven through the
    fused scan (core/sim.py ``run_cluster_ticks_nemesis``).
    """
    down = fault.crash | fault.stall                               # [N]

    # 1. crash-restart.  crash_restart splits each node's PRNG key; the
    # select keeps un-crashed nodes' streams bit-exact (types.py).
    restarted = jax.vmap(partial(crash_restart, cfg))(states)
    states = _select_nodes(fault.crash, restarted, states)

    # 2. delivery: link masks AND down-destination loss.  After route()'s
    # transpose conn[s, d] gates the s->d lane, so down destinations are
    # a column mask.
    inboxes = route(inflight, fault.link_up & ~down[None, :])

    # 3. step, then freeze stalled nodes (their pre-step state INCLUDES a
    # same-tick crash reset: a node both crashed and stalled restarts but
    # does not run).  StepInfo freezes too, so the self-driving host inbox
    # (auto_host_inbox snapshot echo) does not act for a stalled node.
    stepped, outboxes, infos = jax.vmap(partial(node_step, cfg))(
        states, inboxes, host)
    new_states = _select_nodes(fault.stall, states, stepped)
    infos = _select_nodes(fault.stall, prev_info, infos)
    sender_up = ~fault.stall
    outboxes = outboxes.replace(**{
        name: getattr(outboxes, name) & _node_bcast(
            sender_up, getattr(outboxes, name))
        for name in _VALID_FIELDS})

    # 4. duplicate delivery: re-queue this tick's DELIVERED messages on
    # dup'd links, whole-RPC, wherever the fresh outbox left the lane
    # empty.  The copy rides ``inflight`` and is subject to next tick's
    # masks like any message.
    delivered = fault.link_up & ~down[None, :]                     # [N, N]
    dup_lane = (fault.dup & delivered)[:, :, None]                 # [N, N, 1]
    reps = {}
    for kind, names in _KIND_FIELDS.items():
        vname = f"{kind}_valid"
        keep = dup_lane & getattr(inflight, vname) \
            & ~getattr(outboxes, vname)                            # [N, P, G]
        for name in names:
            old = getattr(inflight, name)
            new = getattr(outboxes, name)
            k = keep if old.ndim == keep.ndim else keep[..., None]
            reps[name] = jnp.where(k, old, new)
        reps[vname] = getattr(outboxes, vname) | keep
    outboxes = outboxes.replace(**reps)
    return new_states, outboxes, infos


@partial(jax.jit, static_argnums=(0, 3, 6))
def auto_host_inbox(cfg: EngineConfig, states: RaftState, submit_n: jax.Array,
                    compact, prev_info: StepInfo,
                    read_n: Optional[jax.Array] = None,
                    durable_lag: bool = False) -> HostInbox:
    """Build a HostInbox batch [N, ...] for the self-driving harness.

    Policy (the steady-state behavior of a host runtime whose state machines
    keep pace — reference MaintainAgreement, command/MaintainAgreement.java):

    * offer ``submit_n`` client commands per group (leaders accept);
    * compact with slack: raise the log floor only up to ``commit - L/4``,
      keeping a tail of committed entries so briefly-lagging followers catch
      up from the log instead of tripping snapshot installation;
    * service snapshot downloads instantly: last tick's ``snap_req`` comes
      back as this tick's ``snap_done`` (the payload-less analog of the
      reference's out-of-band snapshot channel, EventNode.java:122-267).

    ``read_n`` ([N, G] int32, optional): linearizable reads offered per
    group per tick (the read-plane analog of ``submit_n``; only leaders
    with a free ReadIndex slot stamp them — unstamped offers are simply
    re-offered next tick by this self-driving policy).

    ``durable_lag``: feed each node's PREVIOUS-tick log tail
    (``prev_info.log_tail``) as ``HostInbox.durable_tail`` — the fused-scan
    model of the pipelined runtime's one-tick durability barrier (a tick's
    appends fsync while the next scan runs, so own-match counts only the
    prior tick's tail).  Default False: writes are durable instantly, the
    classic simulation assumption.

    ``compact``: False = never; True = every tick (the bench steady state);
    int K > 1 = every K ticks.  The cadence matters for laggard catch-up
    under SUSTAINED load: an every-tick floor advances continuously and
    outruns any snapshot install (each installed milestone is already
    below the floor by adoption time — a pursuit that never converges),
    whereas real compaction is gated on discrete checkpoints with minimum
    intervals (snapshot/policy.py, reference MaintainAgreement.java:
    85-130), giving laggards a stable window to install and then drain
    the live log.  Use a cadence when simulating catch-up scenarios.
    """
    G = cfg.n_groups
    slack = cfg.log_slots // 4
    if read_n is None:
        read_n = jnp.zeros(submit_n.shape, jnp.int32)

    def one(st, sub, rd, info):
        hi = HostInbox.empty(cfg)
        if compact is True:
            ct = jnp.maximum(st.commit - slack, 0)
        elif compact:
            ct = jnp.where(st.now % int(compact) == 0,
                           jnp.maximum(st.commit - slack, 0),
                           jnp.zeros((G,), jnp.int32))
        else:
            ct = jnp.zeros((G,), jnp.int32)
        return hi.replace(
            submit_n=sub,
            read_n=rd,
            compact_to=ct,
            snap_done=info.snap_req,
            snap_idx=info.snap_req_idx,
            snap_term=info.snap_req_term,
            snap_conf=info.snap_req_conf,
            durable_tail=info.log_tail if durable_lag else None,
        )
    return jax.vmap(one)(states, submit_n, read_n, prev_info)


def cluster_snapshot(states: RaftState) -> dict:
    """Host snapshot dict from a stacked [N, ...] RaftState — the ONE
    definition of the audit currency, shared by ``DeviceCluster.snapshot``
    and the fused-scan audit paths (testkit/invariants.py ClusterChecker,
    testkit/nemesis.py), so raw scan outputs audit without a DeviceCluster
    wrapper and the two paths cannot drift."""
    return {
        "term": np.asarray(states.term),
        "role": np.asarray(states.role),
        "voted_for": np.asarray(states.voted_for),
        "leader_id": np.asarray(states.leader_id),
        "commit": np.asarray(states.commit),
        "last": np.asarray(states.log.last),
        "base": np.asarray(states.log.base),
        "log_term": np.asarray(states.log.term),
        "now": np.asarray(states.now),
    }


class DeviceCluster:
    """Host-side driver for an all-on-device N-node Multi-Raft cluster.

    The in-process many-node harness — the generalization of the reference's
    loopback test trick (transport/EventClusterTest.java:81-83) — used by the
    test suite, the chaos/parity oracle and the benchmark.
    """

    def __init__(self, cfg: EngineConfig, seed: int = 0,
                 n_active: int | None = None, n_voters: int | None = None):
        self.cfg = cfg
        # Compaction policy for the self-driving inbox (see
        # auto_host_inbox): True = every tick, int K = every K ticks,
        # False = never.  Set a cadence when simulating laggard catch-up.
        self.compact = True
        N = cfg.n_peers
        states = [init_state(cfg, i, seed=seed, n_active=n_active,
                             n_voters=n_voters)
                  for i in range(N)]
        self.states: RaftState = jax.tree.map(
            lambda *xs: jnp.stack(xs), *states)
        self.inflight: Messages = jax.tree.map(
            lambda a: jnp.broadcast_to(a, (N,) + a.shape).copy(),
            Messages.empty(cfg))
        self.conn = jnp.ones((N, N), jnp.bool_)
        self.last_info: StepInfo = jax.tree.map(
            lambda a: jnp.broadcast_to(a, (N,) + a.shape).copy(),
            StepInfo.empty(cfg))

    # -- fault injection ----------------------------------------------------
    def set_partition(self, groups_of_nodes) -> None:
        """Partition the cluster: nodes can only reach their own side."""
        N = self.cfg.n_peers
        conn = np.zeros((N, N), bool)
        for side in groups_of_nodes:
            for a in side:
                for b in side:
                    conn[a, b] = True
        self.conn = jnp.asarray(conn)

    def heal(self) -> None:
        self.conn = jnp.ones((self.cfg.n_peers,) * 2, jnp.bool_)

    def isolate(self, node: int) -> None:
        N = self.cfg.n_peers
        self.set_partition([[n for n in range(N) if n != node], [node]])

    # -- stepping -----------------------------------------------------------
    def tick(self, submit_n=None, host: Optional[HostInbox] = None,
             read_n=None) -> StepInfo:
        N, G = self.cfg.n_peers, self.cfg.n_groups
        if host is None:
            def dense(v):
                if v is None:
                    return jnp.zeros((N, G), jnp.int32)
                v = jnp.asarray(v, jnp.int32)
                return jnp.broadcast_to(v, (N, G)) if v.ndim == 0 else v
            host = auto_host_inbox(self.cfg, self.states, dense(submit_n),
                                   self.compact, self.last_info,
                                   dense(read_n))
        self.states, self.inflight, info = cluster_step(
            self.cfg, self.states, self.inflight, host, self.conn)
        self.last_info = info
        if self.cfg.debug_checks:
            self._debug_check(info)
        return info

    def _debug_check(self, info: StepInfo) -> None:
        """cfg.debug_checks: surface in-kernel violations (per-node lanes)
        plus the one cross-node invariant a single node cannot see —
        at most one leader per (group, term), the election-safety assert
        of the reference (Follower.java:48-50, Leader.java:79-81)."""
        from .step import raise_debug_violations
        raise_debug_violations(info, "cluster tick")
        role = np.asarray(self.states.role)
        term = np.asarray(self.states.term)
        N = role.shape[0]
        for i in range(N):
            for j in range(i + 1, N):
                both = ((role[i] == LEADER) & (role[j] == LEADER)
                        & (term[i] == term[j]))
                if both.any():
                    g = int(np.nonzero(both)[0][0])
                    raise AssertionError(
                        f"election safety violated: nodes {i} and {j} both "
                        f"lead group {g} at term {int(term[i, g])}")

    def run(self, n_ticks: int, submit_n=None) -> None:
        for _ in range(n_ticks):
            self.tick(submit_n)

    # -- membership ---------------------------------------------------------
    def request_membership(self, voters: int, learners: int = 0,
                           groups=None, submit_n=None) -> StepInfo:
        """One tick with a membership-change request offered to EVERY node
        for the selected groups (only the leader's intake takes it; §6,
        core/step.py phase 8c).  ``voters``/``learners`` are peer
        bitmasks; ``groups`` (None = all) selects lanes.  The request is
        a single-tick offer — drive further ticks until
        ``StepInfo.conf_pending`` clears and the active ``conf_word``
        matches (the joint walk's leave entry auto-appends)."""
        import jax.numpy as jnp

        N, G = self.cfg.n_peers, self.cfg.n_groups
        sel = np.zeros(G, bool)
        sel[np.asarray(list(range(G)) if groups is None else groups)] = True
        hv = jnp.asarray(np.where(sel, voters, 0).astype(np.int32))
        hl = jnp.asarray(np.where(sel, learners, 0).astype(np.int32))
        return self._tick_with(conf_voters=hv, conf_learners=hl,
                               submit_n=submit_n)

    def request_transfer(self, target, groups=None) -> StepInfo:
        """One tick with a leadership-transfer request (TimeoutNow walk,
        core/step.py phase 7b/9) offered to every node for the selected
        groups.  ``target`` is a peer id (or [G] vector)."""
        import jax.numpy as jnp

        G = self.cfg.n_groups
        sel = np.zeros(G, bool)
        sel[np.asarray(list(range(G)) if groups is None else groups)] = True
        tgt = np.broadcast_to(np.asarray(target, np.int32), (G,))
        tgt = np.where(sel, tgt, -1).astype(np.int32)
        return self._tick_with(xfer_target=jnp.asarray(tgt))

    def _tick_with(self, submit_n=None, **host_lanes) -> StepInfo:
        """Tick once with extra per-group HostInbox lanes broadcast to
        every node on top of the self-driving policy."""
        import jax.numpy as jnp

        N, G = self.cfg.n_peers, self.cfg.n_groups
        sub = jnp.zeros((N, G), jnp.int32) if submit_n is None else \
            jnp.broadcast_to(jnp.asarray(submit_n, jnp.int32), (N, G))
        host = auto_host_inbox(self.cfg, self.states, sub, self.compact,
                               self.last_info)
        host = host.replace(**{
            k: jnp.broadcast_to(v, (N,) + v.shape) for k, v in
            host_lanes.items()})
        return self.tick(host=host)

    def membership(self, group: int, node: int = 0) -> dict:
        """Decoded active config of one group as one node sees it (the
        state's conf_word cache; configs converge with the log)."""
        from .types import conf_learners_of, conf_new_of, conf_voters_of

        w = int(self.states.conf_word[node, group])
        return {"voters": int(conf_voters_of(w)),
                "voters_new": int(conf_new_of(w)),
                "learners": int(conf_learners_of(w)),
                "joint": bool(conf_new_of(w))}

    # -- inspection ---------------------------------------------------------
    def snapshot(self) -> dict:
        """Pull the whole cluster state to host numpy for assertions."""
        return cluster_snapshot(self.states)

    def leaders(self, group: int = 0) -> list[int]:
        role = np.asarray(self.states.role[:, group])
        return [int(n) for n in np.nonzero(role == LEADER)[0]]

    def log_terms(self, node: int, group: int, lo: int, hi: int) -> list[int]:
        """Entry terms for indices [lo, hi] on one node (host-side read)."""
        L = self.cfg.log_slots
        ring = np.asarray(self.states.log.term[node, group])
        base = int(self.states.log.base[node, group])
        last = int(self.states.log.last[node, group])
        out = []
        for i in range(lo, hi + 1):
            if i <= base or i > last:
                out.append(None)
            else:
                out.append(int(ring[i % L]))
        return out
