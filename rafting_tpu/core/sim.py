"""Fused multi-tick cluster simulation: a `lax.scan` over whole-cluster steps.

One compiled program advances an N-node, G-group Multi-Raft cluster by many
ticks without touching the host — the measurement core for the benchmark and
the fast path for large-scale tests.  The host-policy loop (submissions,
slack compaction, instant snapshot service) is folded into the scan body via
``auto_host_inbox``.

``run_cluster_ticks_blocked`` tiles the group axis: groups are independent
(no cross-group dataflow anywhere in the step), so a ``lax.map`` over blocks
of <= ``group_block`` groups — each block running the WHOLE tick scan — is
semantically exact while keeping every compiled program inside the working
envelope the TPU has been proven to handle (r1: the single fused program ran
at 32k groups and faulted at >= 65k).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from .cluster import auto_host_inbox, cluster_step, cluster_step_nemesis
from .shard import info_pspecs, messages_pspecs, state_pspecs, SUBMIT_PSPEC
from .types import EngineConfig, FaultSchedule, Messages, RaftState, StepInfo


def _scan_ticks(cfg: EngineConfig, n_ticks: int, states: RaftState,
                inflight: Messages, prev_info: StepInfo, conn: jax.Array,
                submit_n: jax.Array, read_n=None, durable_lag: bool = False
                ) -> Tuple[RaftState, Messages, StepInfo]:
    def body(carry, _):
        states, inflight, info = carry
        host = auto_host_inbox(cfg, states, submit_n, True, info, read_n,
                               durable_lag)
        states, inflight, info = cluster_step(cfg, states, inflight, host,
                                              conn)
        return (states, inflight, info), ()

    (states, inflight, info), _ = jax.lax.scan(
        body, (states, inflight, prev_info), None, length=n_ticks)
    return states, inflight, info


@partial(jax.jit, static_argnums=(0, 1, 8), donate_argnums=(2, 3, 4))
def run_cluster_ticks(cfg: EngineConfig, n_ticks: int, states: RaftState,
                      inflight: Messages, prev_info: StepInfo,
                      conn: jax.Array, submit_n: jax.Array,
                      read_n=None, durable_lag: bool = False
                      ) -> Tuple[RaftState, Messages, StepInfo]:
    """Advance the cluster `n_ticks` ticks under a constant offered load.

    ``submit_n`` is [N, G]: commands offered to every node each tick (only
    leaders accept).  ``read_n`` (optional, [N, G]) additionally offers
    linearizable read batches each tick (read plane, core/step.py phase
    8b; reads never touch the log).  ``durable_lag`` (static) feeds each
    tick's ``HostInbox.durable_tail`` from the previous tick's log tail —
    the in-scan model of the pipelined runtime's one-tick durability
    barrier (see ``auto_host_inbox``).  Returns the final carry; per-tick
    outputs are not materialized (the benchmark reads commit deltas from
    the state — for read-plane accounting use
    :func:`run_cluster_ticks_reads`).
    """
    return _scan_ticks(cfg, n_ticks, states, inflight, prev_info, conn,
                       submit_n, read_n, durable_lag)


@partial(jax.jit, static_argnums=(0, 1), donate_argnums=(2, 3, 4))
def run_cluster_ticks_reads(cfg: EngineConfig, n_ticks: int,
                            states: RaftState, inflight: Messages,
                            prev_info: StepInfo, conn: jax.Array,
                            submit_n: jax.Array, read_n: jax.Array
                            ) -> Tuple[RaftState, Messages, StepInfo,
                                       jax.Array, jax.Array, jax.Array]:
    """`run_cluster_ticks` with read-plane accounting in the carry.

    Offers ``read_n`` [N, G] linearizable read batches per node per tick on
    top of ``submit_n`` writes and accumulates, across the whole fused
    scan: total individual reads served, total batches released by the
    same-tick lease fast path, and total log entries appended (the bench's
    zero-log-growth / mixed-load evidence).  Returns ``(states, inflight,
    info, reads_served, lease_hits, appended)``.  The counters are i32
    scalars like every engine lane (core/types.py I32 design): one scan
    must keep ``n_ticks * N * G * reads_per_batch`` under ~2^31 — the
    bench drives bounded chunks, so chunk totals never approach it (the
    host sums chunks in Python ints).
    """
    from .types import I32

    def body(carry, _):
        states, inflight, info, served, lease, appended = carry
        host = auto_host_inbox(cfg, states, submit_n, True, info, read_n)
        states, inflight, info = cluster_step(cfg, states, inflight, host,
                                              conn)
        served = served + info.read_served.sum()
        lease = lease + info.read_lease.astype(I32).sum()
        appended = appended + jnp.where(
            info.appended_to > 0,
            info.appended_to - info.appended_from + 1, 0).sum()
        return (states, inflight, info, served, lease, appended), ()

    zero = jnp.zeros((), I32)
    (states, inflight, info, served, lease, appended), _ = jax.lax.scan(
        body, (states, inflight, prev_info, zero, zero, zero), None,
        length=n_ticks)
    return states, inflight, info, served, lease, appended


@partial(jax.jit, static_argnums=0, donate_argnums=(1, 2, 3))
def run_cluster_ticks_nemesis(cfg: EngineConfig, states: RaftState,
                              inflight: Messages, prev_info: StepInfo,
                              sched: FaultSchedule, submit_n: jax.Array,
                              read_n=None
                              ) -> Tuple[RaftState, Messages, StepInfo]:
    """Advance the cluster ``sched.n_ticks`` ticks under a fault schedule.

    The device-side nemesis: the whole chaos scenario — per-tick directed
    link masks, crash-restarts, clock stalls, duplicate deliveries — is
    data riding ``lax.scan`` as scan inputs, so the run executes inside
    ONE compiled program with zero per-tick host round-trips (the
    requirement that lets chaos run at the benchmark's 10k-100k-group
    scale instead of `DeviceCluster.tick`'s host-loop pace).  Tick count
    comes from the schedule's leading axis.  Fully deterministic: same
    seed + same schedule replays bit-identically (every lane is integer /
    counter-mode PRNG — there is no order-dependent float math to drift).

    ``submit_n`` is [N, G] constant offered load, as in
    :func:`run_cluster_ticks`; ``read_n`` (optional, [N, G]) offers
    linearizable read batches under the same faults — the adversary run
    the read plane's lease safety argument is tested against.  The
    self-driving host policy (``auto_host_inbox``: slack compaction +
    instant snapshot service) is folded into the scan body, with a
    stalled node's StepInfo frozen so its host half stalls with it.
    """
    def body(carry, fault):
        states, inflight, info = carry
        host = auto_host_inbox(cfg, states, submit_n, True, info, read_n)
        states, inflight, info = cluster_step_nemesis(
            cfg, states, inflight, host, info, fault)
        return (states, inflight, info), ()

    (states, inflight, info), _ = jax.lax.scan(
        body, (states, inflight, prev_info), sched)
    return states, inflight, info


def _group_axis(spec) -> int | None:
    entries = tuple(spec)
    return entries.index("group") if "group" in entries else None


def _to_blocks(tree, specs, nb: int, gb: int):
    """Split every group axis into [nb, gb] and move the block axis front.
    Leaves without a group axis are broadcast (shared by every block)."""
    def f(a, spec):
        ax = _group_axis(spec)
        if ax is None:
            return jnp.broadcast_to(a, (nb,) + a.shape)
        pad = nb * gb - a.shape[ax]
        if pad:
            width = [(0, 0)] * a.ndim
            width[ax] = (0, pad)
            a = jnp.pad(a, width)  # zero pad == inactive lanes (active=False)
        a = a.reshape(a.shape[:ax] + (nb, gb) + a.shape[ax + 1:])
        return jnp.moveaxis(a, ax, 0)
    return jax.tree.map(f, tree, specs)


def _from_blocks(tree, specs, G: int):
    """Invert ``_to_blocks``: merge [nb, gb] back into the group axis and
    strip padding.  Block-invariant leaves take block 0's value."""
    def f(a, spec):
        ax = _group_axis(spec)
        if ax is None:
            return a[0]
        a = jnp.moveaxis(a, 0, ax)
        a = a.reshape(a.shape[:ax] + (-1,) + a.shape[ax + 2:])
        return jax.lax.slice_in_dim(a, 0, G, axis=ax)
    return jax.tree.map(f, tree, specs)


@partial(jax.jit, static_argnums=(0, 1, 7), static_argnames=("group_block",),
         donate_argnums=(2, 3, 4))
def run_cluster_ticks_blocked(cfg: EngineConfig, n_ticks: int,
                              states: RaftState, inflight: Messages,
                              prev_info: StepInfo, conn: jax.Array,
                              submit_n: jax.Array, group_block: int
                              ) -> Tuple[RaftState, Messages, StepInfo]:
    """`run_cluster_ticks`, tiled over the group axis.

    Groups never interact, so each block of <= ``group_block`` groups runs
    the whole ``n_ticks`` scan as its own program under ``lax.map``; the
    group count is padded up to a block multiple with inert lanes
    (``active=False`` — zero-padded lanes never elect, accept, or send).
    Per-block PRNG keys are folded with the block index so election jitter
    stays decorrelated across blocks.  Not bit-identical to the unblocked
    run (randomized timeouts are drawn per-block), but protocol-equivalent;
    use the unblocked path when exact parity matters.  The returned state's
    ``rng`` is block 0's folded key (block-invariant leaves collapse to
    block 0), so chaining blocked and unblocked runs changes the
    randomized-timeout stream — fine for throughput runs, not for
    reproducibility-sensitive callers.
    """
    G = cfg.n_groups
    if group_block >= G:
        return _scan_ticks(cfg, n_ticks, states, inflight, prev_info, conn,
                           submit_n)
    nb = -(-G // group_block)
    gb = group_block
    cfg_blk = dataclasses.replace(cfg, n_groups=gb)

    st_specs, msg_specs, inf_specs = (
        state_pspecs(trace=states.trace is not None,
                     heat=states.heat is not None,
                     qc=states.qc is not None), messages_pspecs(),
        info_pspecs(qc=prev_info.cq_stepdown is not None))
    states_b = _to_blocks(states, st_specs, nb, gb)
    inflight_b = _to_blocks(inflight, msg_specs, nb, gb)
    info_b = _to_blocks(prev_info, inf_specs, nb, gb)
    submit_b = _to_blocks(submit_n, SUBMIT_PSPEC, nb, gb)
    # Decorrelate the per-node keys across blocks.
    rng_b = jax.vmap(lambda b: jax.vmap(
        lambda k: jax.random.fold_in(k, b))(states.rng))(
            jnp.arange(nb, dtype=jnp.uint32))
    states_b = states_b.replace(rng=rng_b)

    def one_block(blk):
        st, infl, inf, sub = blk
        return _scan_ticks(cfg_blk, n_ticks, st, infl, inf, conn, sub)

    states_o, inflight_o, info_o = jax.lax.map(
        one_block, (states_b, inflight_b, info_b, submit_b))
    return (_from_blocks(states_o, st_specs, G),
            _from_blocks(inflight_o, msg_specs, G),
            _from_blocks(info_o, inf_specs, G))


def committed_entries(states: RaftState) -> jax.Array:
    """Total entries committed across all groups (scalar int64-ish).

    Each group's commit point is counted once, at the furthest node (commit
    indices are identical across nodes once converged)."""
    return jnp.sum(states.commit.max(axis=0).astype(jnp.int64))
