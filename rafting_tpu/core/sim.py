"""Fused multi-tick cluster simulation: a `lax.scan` over whole-cluster steps.

One compiled program advances an N-node, G-group Multi-Raft cluster by many
ticks without touching the host — the measurement core for the benchmark and
the fast path for large-scale tests.  The host-policy loop (submissions,
slack compaction, instant snapshot service) is folded into the scan body via
``auto_host_inbox``.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from .cluster import auto_host_inbox, cluster_step
from .types import EngineConfig, Messages, RaftState, StepInfo


@partial(jax.jit, static_argnums=(0, 1), donate_argnums=(2, 3, 4))
def run_cluster_ticks(cfg: EngineConfig, n_ticks: int, states: RaftState,
                      inflight: Messages, prev_info: StepInfo,
                      conn: jax.Array, submit_n: jax.Array
                      ) -> Tuple[RaftState, Messages, StepInfo]:
    """Advance the cluster `n_ticks` ticks under a constant offered load.

    ``submit_n`` is [N, G]: commands offered to every node each tick (only
    leaders accept).  Returns the final carry; per-tick outputs are not
    materialized (the benchmark reads commit deltas from the state).
    """

    def body(carry, _):
        states, inflight, info = carry
        host = auto_host_inbox(cfg, states, submit_n, True, info)
        states, inflight, info = cluster_step(cfg, states, inflight, host,
                                              conn)
        return (states, inflight, info), ()

    (states, inflight, info), _ = jax.lax.scan(
        body, (states, inflight, prev_info), None, length=n_ticks)
    return states, inflight, info


def committed_entries(states: RaftState) -> jax.Array:
    """Total entries committed across all groups (scalar int64-ish).

    Each group's commit point is counted once, at the furthest node (commit
    indices are identical across nodes once converged)."""
    return jnp.sum(states.commit.max(axis=0).astype(jnp.int64))
