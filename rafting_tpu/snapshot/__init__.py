"""Snapshot subsystem: durable archive + maintain policy.

* :class:`SnapshotArchive` — per-group on-disk snapshot store with atomic
  installs, bounded retention and pending-download tracking (reference
  command/SnapshotArchive.java:15-244).
* :class:`MaintainAgreement` — the *when* policy: thresholds and cadences
  deciding when to checkpoint the machine and when to compact the log
  (reference command/MaintainAgreement.java:12-145).
"""

from .archive import PendingSnapshot, Snapshot, SnapshotArchive  # noqa: F401
from .policy import MaintainAgreement  # noqa: F401
