"""MaintainAgreement: when to checkpoint machines and compact logs.

Port of the reference's policy semantics (command/MaintainAgreement.java):
a checkpoint is triggered when enough state changes accumulated
(``state_change_threshold``), the dirty log is long enough
(``dirty_log_tolerance``) and minimum intervals elapsed
(MaintainAgreement.java:85-103); log compaction runs on its own cadence
gated on an existing snapshot (118-130).  Times here are node ticks, not
wall-clock — the policy is driven once per runtime tick.

One instance tracks ALL groups in numpy lanes (the policy itself is
vectorized; only the actual checkpoint work is per-group host code).
"""

from __future__ import annotations

import numpy as np


class MaintainAgreement:
    def __init__(self, n_groups: int, *,
                 state_change_threshold: int = 64,
                 dirty_log_tolerance: int = 16,
                 snap_min_interval: int = 20,
                 compact_min_interval: int = 10,
                 compact_slack: int = 8):
        G = n_groups
        self.state_change_threshold = state_change_threshold
        self.dirty_log_tolerance = dirty_log_tolerance
        self.snap_min_interval = snap_min_interval
        self.compact_min_interval = compact_min_interval
        self.compact_slack = compact_slack
        # Phase-stagger the cadences across groups: groups booted together
        # would otherwise cross their thresholds TOGETHER, turning
        # maintenance into a synchronized storm (thousands of checkpoint
        # file copies in one tick — a multi-second stall at 8k+ groups)
        # instead of a steady trickle.
        self.last_snap_tick = -(np.arange(G, dtype=np.int64)
                                % max(snap_min_interval, 1))
        self.last_compact_tick = -(np.arange(G, dtype=np.int64)
                                   % max(compact_min_interval, 1))
        self.snap_index = np.zeros(G, np.int64)     # newest archived snapshot
        self.applied_at_snap = np.zeros(G, np.int64)

    def need_checkpoint(self, now: int, applied: np.ndarray,
                        log_base: np.ndarray) -> np.ndarray:
        """[G] bool: machines whose state moved enough to checkpoint
        (MaintainAgreement.needMaintain, 85-103)."""
        changed = applied - self.applied_at_snap
        dirty = applied - log_base
        due = now - self.last_snap_tick >= self.snap_min_interval
        return ((changed >= self.state_change_threshold)
                & (dirty >= self.dirty_log_tolerance) & due)

    def note_checkpoint(self, g: int, now: int, index: int) -> None:
        self.last_snap_tick[g] = now
        self.snap_index[g] = index
        self.applied_at_snap[g] = index

    def compact_targets(self, now: int, commit: np.ndarray,
                        log_base: np.ndarray) -> np.ndarray:
        """[G] int: compact-to index per group (0 = keep).  Compaction never
        passes the newest snapshot (the reference gates flush on the
        snapshot milestone, RaftRoutine.compactLog:365-400) and keeps
        ``compact_slack`` committed entries for briefly-lagging followers."""
        due = now - self.last_compact_tick >= self.compact_min_interval
        target = np.minimum(self.snap_index,
                            np.maximum(commit - self.compact_slack, 0))
        target = np.where(due & (target > log_base), target, 0)
        if target.any():
            self.last_compact_tick = np.where(
                target > 0, now, self.last_compact_tick)
        return target.astype(np.int64)
