"""SnapshotArchive: durable per-group snapshot store.

Disk layout mirrors the reference (command/SnapshotArchive.java:110-242):
one directory per group holding files named ``snapshot_<index:016x>_<term:016x>``,
installed by atomic rename, retaining the last N (reference keeps 5,
context/ContextManager.java:72).  Temp files from interrupted transfers
are swept at open (SnapshotArchive.java:127-132).  A PendingSnapshot
tracks at most one in-flight remote download per group
(SnapshotArchive.java:197-211).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import re
import shutil
import threading
from typing import Dict, List, Optional

from ..utils import iofault
from ..utils.crc32c import crc32c_file

log = logging.getLogger(__name__)

_NAME = re.compile(r"^snapshot_([0-9a-f]{16})_([0-9a-f]{16})$")

# Integrity sidecar: every archived snapshot file gets a small JSON
# companion at `<path>.crc` recording its CRC-32C and byte length, written
# atomically AFTER the payload is fsynced.  The payload file itself stays
# pristine bytes — state machines read checkpoint paths raw, and the
# remote-install path streams them verbatim — so integrity metadata must
# live beside the data, not inside it.  The receiving node recomputes the
# CRC over the bytes it actually landed on disk, making the check
# end-to-end across the transfer at the storage layer.  Snapshots from
# before this scheme have no sidecar and verify as "legacy".
_CRC_SUFFIX = ".crc"
_CORRUPT_SUFFIX = ".corrupt"  # quarantined files keep their bytes for
                              # post-mortem; no scan pattern matches them


@dataclasses.dataclass(frozen=True)
class Snapshot:
    path: str
    index: int
    term: int


@dataclasses.dataclass
class PendingSnapshot:
    """One in-flight snapshot download/install for a group."""
    index: int
    term: int
    from_peer: int
    failed: bool = False

    def expired_by(self, index: int, term: int) -> bool:
        """A newer offer supersedes this one (reference PendingSnapshot
        ordering, SnapshotArchive.java:30-76)."""
        return (term, index) > (self.term, self.index)


class SnapshotArchive:
    def __init__(self, root: str, retain: int = 5):
        self.root = root
        self.retain = retain
        os.makedirs(root, exist_ok=True)
        self._pending: Dict[int, PendingSnapshot] = {}
        # Hot-path caches: group dirs already created, and the FULL sorted
        # snapshot manifest per group.  The manifest makes checkpoint
        # rotation O(1): save_checkpoint appends to it and prunes from its
        # head instead of re-listing and re-stat'ing the directory on
        # every call (the listdir+stat retention storm was 16k+ posix.stat
        # calls per durable bench run).  A group's manifest is warmed once
        # (first touch after open) and maintained by every mutation; the
        # directory is re-read only on that first touch.
        self._dirs: set = set()
        self._manifest: Dict[int, List[Snapshot]] = {}
        # Per-group incarnation counter bumped by destroy(), plus the lock
        # that makes check-gen-then-cache atomic against it: a cache-miss
        # read that overlapped a destroy must not write its (now dead)
        # listing back into the manifest — see last_snapshot.  The same
        # lock orders manifest mutations from the checkpoint worker pool
        # (runtime/node.py off-thread saves) against the tick thread's
        # installs/serves; manifest critical sections are a few list ops —
        # file I/O (copy, unlink) always happens outside it.
        self._gen: Dict[int, int] = {}
        self._gen_lock = threading.Lock()
        # Sweep temp droppings from interrupted installs.
        for name in os.listdir(root):
            if name.endswith(".tmp"):
                try:
                    os.unlink(os.path.join(root, name))
                except OSError:
                    pass
            elif os.path.isdir(os.path.join(root, name)):
                gdir = os.path.join(root, name)
                for f in os.listdir(gdir):
                    if f.endswith(".tmp"):
                        try:
                            os.unlink(os.path.join(gdir, f))
                        except OSError:
                            pass

    def _gdir(self, g: int) -> str:
        d = os.path.join(self.root, f"g{g}")
        if g not in self._dirs:
            os.makedirs(d, exist_ok=True)
            self._dirs.add(g)
        return d

    def groups_with_snapshots(self, n_groups: Optional[int] = None
                              ) -> List[int]:
        """Group ids that have an on-disk snapshot directory — ONE listdir
        of the archive root, no per-group mkdir/stat.  Boot recovery
        iterates this instead of range(n_groups): a 100k-group cold start
        with a handful of snapshotted groups costs one directory read,
        not 100k makedirs (each _gdir call CREATES the directory).  The
        listing may include groups whose directories exist but hold no
        snapshot files; callers filter via last_snapshot."""
        out: List[int] = []
        for name in os.listdir(self.root):
            if not name.startswith("g"):
                continue
            try:
                g = int(name[1:])
            except ValueError:
                continue
            if n_groups is not None and g >= n_groups:
                continue
            out.append(g)
        out.sort()
        return out

    # -- local snapshots -----------------------------------------------------

    def save_checkpoint(self, g: int, src_path: str, index: int,
                        term: int) -> Snapshot:
        """Archive a machine checkpoint as the group's newest snapshot
        (atomic move; ordering asserted like SnapshotArchive.java:138-182).

        Safe off the tick thread: the node runtime runs local checkpoint
        saves on its worker pool (group-sharded, so one group's saves
        stay ordered); the manifest keeps rotation O(1) — no listdir or
        per-file stat on this path, ever."""
        last = self.last_snapshot(g)
        if last is not None:
            assert (term, index) >= (last.term, last.index), \
                f"snapshot ordering violated: ({index},{term}) after " \
                f"({last.index},{last.term})"
            if (index, term) == (last.index, last.term):
                return last
        dst = os.path.join(self._gdir(g), f"snapshot_{index:016x}_{term:016x}")
        # Writer-unique temp name (still *.tmp so the open() sweep catches
        # droppings): a tick-thread install and a pool worker's save must
        # never collide on one temp path.
        tmp = f"{dst}.{threading.get_ident()}.tmp"
        iofault.check("archive.write", dst)
        shutil.copyfile(src_path, tmp)
        # Durability + integrity before the atomic publish: fsync the
        # payload, then record its CRC-32C — computed from the bytes ON
        # DISK, so a copy/transfer corruption is caught here or by the
        # scrubber, never served onward as good.
        crc, size = self._fsync_and_crc(tmp)
        os.replace(tmp, dst)
        self._write_sidecar(dst, crc, size)
        snap = Snapshot(dst, index, term)
        with self._gen_lock:
            m = self._manifest.setdefault(g, [])
            if not m or (snap.term, snap.index) > (m[-1].term, m[-1].index):
                m.append(snap)
            drop, self._manifest[g] = m[:-self.retain], m[-self.retain:]
        for s in drop:
            for p in (s.path, s.path + _CRC_SUFFIX):
                try:
                    os.unlink(p)
                except OSError:
                    pass
        return snap

    @staticmethod
    def _fsync_and_crc(path: str):
        with open(path, "rb") as f:
            iofault.check("archive.fsync", path)
            os.fsync(f.fileno())
        return crc32c_file(path), os.path.getsize(path)

    @staticmethod
    def _write_sidecar(path: str, crc: int, size: int) -> None:
        tmp = path + _CRC_SUFFIX + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"algo": "crc32c", "crc": int(crc), "len": int(size)},
                      f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path + _CRC_SUFFIX)

    # -- integrity: verify / quarantine / scrub ------------------------------

    @staticmethod
    def verify_snapshot(path: str) -> str:
        """Check one archived snapshot against its sidecar.  Returns
        ``"ok"`` (checksum matches), ``"legacy"`` (no sidecar — predates
        the scheme, accepted), ``"corrupt"`` (length or CRC mismatch, or
        unreadable payload with a sidecar present), or ``"missing"`` (the
        payload file is gone)."""
        if not os.path.exists(path):
            return "missing"
        try:
            with open(path + _CRC_SUFFIX) as f:
                meta = json.load(f)
            want_crc = int(meta["crc"])
            want_len = int(meta["len"])
        except (OSError, ValueError, KeyError):
            return "legacy"
        try:
            if os.path.getsize(path) != want_len:
                return "corrupt"
            return "ok" if crc32c_file(path) == want_crc else "corrupt"
        except OSError:
            return "corrupt"

    def quarantine(self, g: int, snap: Snapshot) -> None:
        """Fail-stop a corrupt archived snapshot: move the bytes aside
        (kept for post-mortem under ``*.corrupt``) and drop it from the
        manifest so no reader — recovery, serve, retention — ever hands
        it out again."""
        log.error("snapshot archive: quarantining corrupt %s", snap.path)
        try:
            os.replace(snap.path, snap.path + _CORRUPT_SUFFIX)
        except OSError:
            pass
        try:
            os.unlink(snap.path + _CRC_SUFFIX)
        except OSError:
            pass
        with self._gen_lock:
            m = self._manifest.get(g)
            if m is not None:
                self._manifest[g] = [s for s in m if s.path != snap.path]

    def verified_last_snapshot(self, g: int) -> Optional[Snapshot]:
        """Newest snapshot that passes verification, quarantining any
        corrupt newer ones on the way down — the verify-on-recovery walk:
        a corrupt newest milestone falls back to the previous one (WAL
        replay above it restores the rest)."""
        while True:
            snap = self.last_snapshot(g)
            if snap is None:
                return None
            v = self.verify_snapshot(snap.path)
            if v in ("ok", "legacy"):
                return snap
            self.quarantine(g, snap)

    def scrub(self, g: int, limit: int = 0):
        """Verify up to ``limit`` (0 = all) of a group's archived
        snapshots, newest first; corrupt ones are quarantined.  Returns
        ``(ok, corrupt)`` counts — the background scrubber's unit of
        work."""
        ok = corrupt = 0
        for snap in reversed(self.list_snapshots(g)):
            if limit and ok + corrupt >= limit:
                break
            v = self.verify_snapshot(snap.path)
            if v == "corrupt":
                self.quarantine(g, snap)
                corrupt += 1
            elif v in ("ok", "legacy"):
                ok += 1
        return ok, corrupt

    def last_snapshot(self, g: int) -> Optional[Snapshot]:
        with self._gen_lock:
            m = self._manifest.get(g)
            if m is not None:
                return m[-1] if m else None
            gen = self._gen.get(g, 0)
        snaps = self._scan_dir(g)
        # The gen check and the write-back must be ONE atomic step (under
        # _gen_lock, paired with destroy's pop+bump): a bare
        # check-then-setdefault leaves a preemption window in which
        # destroy() completes between the two and the dead listing gets
        # cached anyway — handing out a deleted path and pinning a stale
        # manifest that a recreated group's save_checkpoint would trip
        # its ordering assert on.
        with self._gen_lock:
            if self._gen.get(g, 0) != gen:
                # destroy() completed while this miss was listing: the
                # listing belongs to the dead incarnation.
                return None
            # setdefault, not assignment: if another thread archived a
            # NEWER snapshot while this (possibly transport-thread) miss
            # was listing the directory, its manifest must win — a stale
            # write-back here would pin an old/empty view until the
            # group's next checkpoint.
            m = self._manifest.setdefault(g, snaps)
            return m[-1] if m else None

    def list_snapshots(self, g: int) -> List[Snapshot]:
        with self._gen_lock:
            m = self._manifest.get(g)
            if m is not None:
                return list(m)
            gen = self._gen.get(g, 0)
        snaps = self._scan_dir(g)
        with self._gen_lock:
            if self._gen.get(g, 0) != gen:
                return []
            return list(self._manifest.setdefault(g, snaps))

    def _scan_dir(self, g: int) -> List[Snapshot]:
        """Cold read of a group directory (manifest warm-up only)."""
        d = self._gdir(g)
        out = []
        try:
            names = os.listdir(d)
        except OSError:
            # destroy()'s rmtree raced this listing (the _dirs cache said
            # the dir existed): the group is gone — an empty listing, not
            # a crash in the snapshot-serving thread.
            return []
        for name in names:
            m = _NAME.match(name)
            if m:
                out.append(Snapshot(os.path.join(d, name),
                                    int(m.group(1), 16), int(m.group(2), 16)))
        out.sort(key=lambda s: (s.term, s.index))
        return out

    # -- remote installs -----------------------------------------------------

    def pend_snapshot(self, g: int, index: int, term: int,
                      from_peer: int) -> Optional[PendingSnapshot]:
        """Register an in-flight download unless one is already pending for
        an equal-or-newer milestone.  Returns the new pending record, or
        None if the existing one stands (SnapshotArchive.java:197-211)."""
        cur = self._pending.get(g)
        if cur is not None and not cur.failed and \
                not cur.expired_by(index, term):
            return None
        p = PendingSnapshot(index=index, term=term, from_peer=from_peer)
        self._pending[g] = p
        return p

    def pending(self, g: int) -> Optional[PendingSnapshot]:
        return self._pending.get(g)

    def install_pending(self, g: int, data_path: str,
                        index: Optional[int] = None,
                        term: Optional[int] = None) -> Snapshot:
        """Download finished: atomically archive the received snapshot.

        ``index``/``term`` are the milestone the serving peer ACTUALLY
        returned (it may serve a newer snapshot than requested); they default
        to the pending request's milestone.  If a newer snapshot was archived
        locally while the download was in flight (local checkpoint racing the
        transfer), the download is discarded and the newer local snapshot is
        returned instead — the caller recovers from whichever is returned."""
        p = self._pending.get(g)
        assert p is not None, "no pending snapshot"
        index = p.index if index is None else index
        term = p.term if term is None else term
        try:
            last = self.last_snapshot(g)
            if last is not None and (last.term, last.index) > (term, index):
                return last
            return self.save_checkpoint(g, data_path, index, term)
        finally:
            del self._pending[g]

    def fail_pending(self, g: int) -> None:
        p = self._pending.get(g)
        if p is not None:
            p.failed = True

    def clear_pending(self, g: int) -> None:
        self._pending.pop(g, None)

    def destroy(self, g: int) -> None:
        shutil.rmtree(self._gdir(g), ignore_errors=True)
        self._pending.pop(g, None)
        self._dirs.discard(g)
        # Pop and bump under the lock, AFTER the rmtree: a concurrent
        # last_snapshot miss either wins the lock first (its possibly
        # pre-rmtree cache entry is popped right here) or enters after
        # and sees the bumped gen, discarding its dead listing.  A miss
        # that starts after the bump lists the (empty) new-incarnation
        # directory — caching that is correct.
        with self._gen_lock:
            self._manifest.pop(g, None)
            self._gen[g] = self._gen.get(g, 0) + 1
