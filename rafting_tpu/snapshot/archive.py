"""SnapshotArchive: durable per-group snapshot store.

Disk layout mirrors the reference (command/SnapshotArchive.java:110-242):
one directory per group holding files named ``snapshot_<index:016x>_<term:016x>``,
installed by atomic rename, retaining the last N (reference keeps 5,
context/ContextManager.java:72).  Temp files from interrupted transfers
are swept at open (SnapshotArchive.java:127-132).  A PendingSnapshot
tracks at most one in-flight remote download per group
(SnapshotArchive.java:197-211).
"""

from __future__ import annotations

import dataclasses
import os
import re
import shutil
import threading
from typing import Dict, List, Optional

_NAME = re.compile(r"^snapshot_([0-9a-f]{16})_([0-9a-f]{16})$")


@dataclasses.dataclass(frozen=True)
class Snapshot:
    path: str
    index: int
    term: int


@dataclasses.dataclass
class PendingSnapshot:
    """One in-flight snapshot download/install for a group."""
    index: int
    term: int
    from_peer: int
    failed: bool = False

    def expired_by(self, index: int, term: int) -> bool:
        """A newer offer supersedes this one (reference PendingSnapshot
        ordering, SnapshotArchive.java:30-76)."""
        return (term, index) > (self.term, self.index)


class SnapshotArchive:
    def __init__(self, root: str, retain: int = 5):
        self.root = root
        self.retain = retain
        os.makedirs(root, exist_ok=True)
        self._pending: Dict[int, PendingSnapshot] = {}
        # Hot-path caches: group dirs already created, and the newest
        # snapshot per group.  Without them every checkpoint/serve does a
        # makedirs + listdir + sort per call — at a 100k-group maintain
        # cadence that is hundreds of redundant file ops per tick.
        self._dirs: set = set()
        self._newest: Dict[int, Optional[Snapshot]] = {}
        # Per-group incarnation counter bumped by destroy(), plus the lock
        # that makes check-gen-then-cache atomic against it: a cache-miss
        # read that overlapped a destroy must not write its (now dead)
        # listing back into _newest — see last_snapshot.  The lock guards
        # ONLY the miss write-back and destroy's pop+bump (cache hits stay
        # lock-free; a hit racing destroy is the pre-existing bounded
        # hand-out-then-check-exists race every caller already handles).
        self._gen: Dict[int, int] = {}
        self._gen_lock = threading.Lock()
        # Sweep temp droppings from interrupted installs.
        for name in os.listdir(root):
            if name.endswith(".tmp"):
                try:
                    os.unlink(os.path.join(root, name))
                except OSError:
                    pass
            elif os.path.isdir(os.path.join(root, name)):
                gdir = os.path.join(root, name)
                for f in os.listdir(gdir):
                    if f.endswith(".tmp"):
                        try:
                            os.unlink(os.path.join(gdir, f))
                        except OSError:
                            pass

    def _gdir(self, g: int) -> str:
        d = os.path.join(self.root, f"g{g}")
        if g not in self._dirs:
            os.makedirs(d, exist_ok=True)
            self._dirs.add(g)
        return d

    # -- local snapshots -----------------------------------------------------

    def save_checkpoint(self, g: int, src_path: str, index: int,
                        term: int) -> Snapshot:
        """Archive a machine checkpoint as the group's newest snapshot
        (atomic move; ordering asserted like SnapshotArchive.java:138-182)."""
        last = self.last_snapshot(g)
        if last is not None:
            assert (term, index) >= (last.term, last.index), \
                f"snapshot ordering violated: ({index},{term}) after " \
                f"({last.index},{last.term})"
            if (index, term) == (last.index, last.term):
                return last
        dst = os.path.join(self._gdir(g), f"snapshot_{index:016x}_{term:016x}")
        tmp = dst + ".tmp"
        shutil.copyfile(src_path, tmp)
        os.replace(tmp, dst)
        self._prune(g)
        snap = Snapshot(dst, index, term)
        self._newest[g] = snap
        return snap

    _MISS = object()

    def last_snapshot(self, g: int) -> Optional[Snapshot]:
        # Single .get read: the snapshot-serving transport thread calls
        # this concurrently with the tick thread's destroy(), so a
        # check-then-index pair could land between the two and KeyError.
        snap = self._newest.get(g, self._MISS)
        if snap is not self._MISS:
            return snap
        gen = self._gen.get(g, 0)
        snaps = self.list_snapshots(g)
        snap = snaps[-1] if snaps else None
        # The gen check and the write-back must be ONE atomic step (under
        # _gen_lock, paired with destroy's pop+bump): a bare
        # check-then-setdefault leaves a preemption window in which
        # destroy() completes between the two and the dead listing gets
        # cached anyway — handing out a deleted path and pinning a stale
        # Snapshot that a recreated group's save_checkpoint would trip
        # its ordering assert on.
        with self._gen_lock:
            if self._gen.get(g, 0) != gen:
                # destroy() completed while this miss was listing: the
                # listing belongs to the dead incarnation.
                return None
            # setdefault, not assignment: if the tick thread archived a
            # NEWER snapshot while this (possibly transport-thread) miss
            # was listing the directory, its cache entry must win — a
            # stale write-back here would pin an old/None value until
            # the group's next checkpoint.
            return self._newest.setdefault(g, snap)

    def list_snapshots(self, g: int) -> List[Snapshot]:
        d = self._gdir(g)
        out = []
        try:
            names = os.listdir(d)
        except OSError:
            # destroy()'s rmtree raced this listing (the _dirs cache said
            # the dir existed): the group is gone — an empty listing, not
            # a crash in the snapshot-serving thread.
            return []
        for name in names:
            m = _NAME.match(name)
            if m:
                out.append(Snapshot(os.path.join(d, name),
                                    int(m.group(1), 16), int(m.group(2), 16)))
        out.sort(key=lambda s: (s.term, s.index))
        return out

    def _prune(self, g: int) -> None:
        snaps = self.list_snapshots(g)
        for s in snaps[:-self.retain]:
            try:
                os.unlink(s.path)
            except OSError:
                pass

    # -- remote installs -----------------------------------------------------

    def pend_snapshot(self, g: int, index: int, term: int,
                      from_peer: int) -> Optional[PendingSnapshot]:
        """Register an in-flight download unless one is already pending for
        an equal-or-newer milestone.  Returns the new pending record, or
        None if the existing one stands (SnapshotArchive.java:197-211)."""
        cur = self._pending.get(g)
        if cur is not None and not cur.failed and \
                not cur.expired_by(index, term):
            return None
        p = PendingSnapshot(index=index, term=term, from_peer=from_peer)
        self._pending[g] = p
        return p

    def pending(self, g: int) -> Optional[PendingSnapshot]:
        return self._pending.get(g)

    def install_pending(self, g: int, data_path: str,
                        index: Optional[int] = None,
                        term: Optional[int] = None) -> Snapshot:
        """Download finished: atomically archive the received snapshot.

        ``index``/``term`` are the milestone the serving peer ACTUALLY
        returned (it may serve a newer snapshot than requested); they default
        to the pending request's milestone.  If a newer snapshot was archived
        locally while the download was in flight (local checkpoint racing the
        transfer), the download is discarded and the newer local snapshot is
        returned instead — the caller recovers from whichever is returned."""
        p = self._pending.get(g)
        assert p is not None, "no pending snapshot"
        index = p.index if index is None else index
        term = p.term if term is None else term
        try:
            last = self.last_snapshot(g)
            if last is not None and (last.term, last.index) > (term, index):
                return last
            return self.save_checkpoint(g, data_path, index, term)
        finally:
            del self._pending[g]

    def fail_pending(self, g: int) -> None:
        p = self._pending.get(g)
        if p is not None:
            p.failed = True

    def clear_pending(self, g: int) -> None:
        self._pending.pop(g, None)

    def destroy(self, g: int) -> None:
        shutil.rmtree(self._gdir(g), ignore_errors=True)
        self._pending.pop(g, None)
        self._dirs.discard(g)
        # Pop and bump under the lock, AFTER the rmtree: a concurrent
        # last_snapshot miss either wins the lock first (its possibly
        # pre-rmtree cache entry is popped right here) or enters after
        # and sees the bumped gen, discarding its dead listing.  A miss
        # that starts after the bump lists the (empty) new-incarnation
        # directory — caching that is correct.
        with self._gen_lock:
            self._newest.pop(g, None)
            self._gen[g] = self._gen.get(g, 0) + 1
