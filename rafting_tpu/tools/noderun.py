"""Standalone cluster node + built-in load client: the deployment-shaped
process the reference tests with (cluster/TestNode1.java:16-56 — one JVM per
node, each submitting a command every ~10ms forever, operator kills and
restarts processes, correctness = byte-identical output files).

Run one per node::

    python -m rafting_tpu.tools.noderun node1.xml

The process:
  * loads the XML config (reference-shaped schema, api/config.load_xml_config),
  * creates a full production container (TCP transport, replicated admin
    lifecycle, WAL durability, live tick loop),
  * opens the shared group ``root`` (idempotent across nodes),
  * submits a uniquely-tagged command every ``--period`` seconds through its
    own stub (redirected to the leader automatically), recording every
    ACKNOWLEDGED payload to ``<data_dir>/acked.txt`` — the survivors an
    operator (or the system test) must find exactly once in the final state,
  * reports liveness to ``<data_dir>/status.json`` so an external harness
    can pick the current leader to kill,
  * on SIGTERM: stops the load, keeps ticking ~3s so replicas drain, then
    closes cleanly.  SIGKILL is the crash case — the WAL recovers.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("config", help="XML config path")
    ap.add_argument("--group", default="root")
    ap.add_argument("--period", type=float, default=0.01,
                    help="seconds between submissions (reference: 10ms)")
    ap.add_argument("--platform", default="cpu",
                    help="jax platform to pin ('' = default backend)")
    ap.add_argument("--drain", type=float, default=3.0,
                    help="seconds to keep ticking after SIGTERM")
    args = ap.parse_args()

    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)

    from rafting_tpu.api import RaftContainer, load_xml_config

    cfg = load_xml_config(args.config)
    container = RaftContainer(cfg).create()
    stop = {"flag": False}
    signal.signal(signal.SIGTERM, lambda *a: stop.update(flag=True))
    signal.signal(signal.SIGINT, lambda *a: stop.update(flag=True))

    # Open (or join) the shared group; every node may race to open it —
    # the admin group's replicated OCC transaction makes this idempotent.
    lane = None
    deadline = time.time() + 120
    while lane is None and time.time() < deadline and not stop["flag"]:
        try:
            lane = container.open_context(args.group, timeout=30)
        except Exception as e:  # not elected yet / racing another opener
            print(f"open_context retry: {e}", flush=True)
            time.sleep(0.5)
    if lane is None:
        print("FATAL could not open group", flush=True)
        container.destroy()
        return 2
    print(f"READY lane={lane} node={cfg.node_id}", flush=True)

    acked_path = os.path.join(cfg.data_dir, "acked.txt")
    status_path = os.path.join(cfg.data_dir, "status.json")
    acked_f = open(acked_path, "a", buffering=1)
    stub = container.get_stub(args.group)
    n_acked = 0
    k = 0
    # Per-incarnation nonce: a restarted process must never re-submit a
    # payload string its pre-crash incarnation may already have committed
    # (the reference randomizes payloads for the same reason,
    # cluster/TestNode1.java:52).
    nonce = os.urandom(4).hex()
    last_status = 0.0
    while not stop["flag"]:
        payload = f"n{cfg.node_id}-{nonce}-{k}"
        k += 1
        try:
            stub.execute(payload, timeout=5)
            acked_f.write(payload + "\n")
            n_acked += 1
        except Exception:
            time.sleep(0.02)
        now = time.time()
        if now - last_status >= 0.5:
            last_status = now
            tmp = status_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"leader": container.node.is_leader(lane),
                           "acked": n_acked, "pid": os.getpid()}, f)
            os.replace(tmp, status_path)
        time.sleep(args.period)

    # Drain: the tick loop keeps running so in-flight commits replicate and
    # apply everywhere before the files are compared.
    print(f"DRAIN acked={n_acked}", flush=True)
    time.sleep(args.drain)
    acked_f.close()
    container.destroy()
    print("CLOSED", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
