"""Operational CLI tools: standalone node runner, offline log checker."""
