"""Test kit: scalar oracle, cluster invariant checkers, chaos harness.

The reference's test strategy (SURVEY.md §4) relies on (a) runtime
AssertionError invariants saturating the main code, (b) a 3-node
kill/restart procedure whose oracle is byte-identical output files.  Here
those become first-class, automated components:

* :mod:`oracle` — a scalar, loop-based re-derivation of the Raft tick
  semantics, compared lane-for-lane against the vectorized kernel
  (election-safety parity requirement, BASELINE.md).
* :mod:`invariants` — cluster-level protocol invariants (election safety,
  log matching, commit stability) checked over live histories.
"""

from .oracle import oracle_step  # noqa: F401
from .invariants import ClusterChecker, cluster_snapshot  # noqa: F401
from . import nemesis  # noqa: F401
from . import faultfs  # noqa: F401
from . import openloop  # noqa: F401
