"""Shared trivial state machines for benchmarks and tests.

``NullMachine`` counts applies with no per-entry I/O (so a harness
measures the framework, not fixture work); ``NullProvider`` hands one per
group.  The checkpoint is a one-line temp file so the snapshot/compaction
lifecycle still runs end to end (the reference's test machines are
likewise minimal file fixtures, cluster/cmd/FileMachine.java).
"""

from __future__ import annotations

import os
import tempfile

from ..machine.spi import Checkpoint, MachineProvider, RaftMachine


class NullMachine(RaftMachine):
    applies_empty = True   # counts no-ops like any apply

    def __init__(self):
        self._applied = 0

    def last_applied(self) -> int:
        return self._applied

    def apply(self, index: int, payload: bytes):
        self._applied = index
        return index

    def apply_batch(self, start_index: int, payloads) -> list:
        self._applied = start_index + len(payloads) - 1
        return list(range(start_index, start_index + len(payloads)))

    def checkpoint(self, must_include: int) -> Checkpoint:
        fd, path = tempfile.mkstemp()
        os.write(fd, str(self._applied).encode())
        os.close(fd)
        return Checkpoint(path=path, index=self._applied)

    def recover(self, ckpt) -> None:
        with open(ckpt.path) as f:
            self._applied = int(f.read() or 0)

    def close(self) -> None:
        pass

    def destroy(self) -> None:
        pass


class ArenaNullMachine(NullMachine):
    """NullMachine plus the arena apply fast path.  Deliberately a LEAF
    class, not part of NullMachine itself: a test subclass overriding
    ``apply`` on NullMachine must not have an inherited ``apply_run``
    silently bypass its override (the hazard machine/spi.py documents for
    apply_batch applies doubly here)."""

    def apply_run(self, start_index: int, pieces, lens) -> list:
        """Arena fast path (machine/spi.py): the null machine never reads
        payload bytes, so a whole committed run applies in O(1)."""
        n = len(lens)
        self._applied = start_index + n - 1
        return list(range(start_index, start_index + n))


class NullProvider(MachineProvider):
    def __init__(self, _root=None):
        pass

    def bootstrap(self, group: int) -> RaftMachine:
        return ArenaNullMachine()
