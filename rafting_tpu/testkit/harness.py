"""LocalCluster: N full RaftNodes in one process.

The system-test harness — the generalization of the reference's test
topology (three JVMs on localhost driven by TestNode1-3,
test cluster/TestNode1.java:16-56, README.md:28-33) collapsed into one
process: real node runtimes (device engine + WAL + machines + snapshots)
wired over the loopback transport, with deterministic lockstep ticking,
node kill/restart (crash = close without flushing anything extra; restart
= rebuild from the WAL) and link-level fault injection.
"""

from __future__ import annotations

import os
import shutil
from typing import Callable, Dict, List, Optional

import numpy as np

from ..core.types import EngineConfig, LEADER
from ..machine.file_machine import FileMachineProvider
from ..runtime.node import RaftNode
from ..transport import LinkFaults, LoopbackNetwork, LoopbackTransport


def scaled_election_mul(tick_ms: int, base: float = 3.0,
                        floor_ms: float = 150.0) -> float:
    """Election multiplier with a wall-clock floor for starved hosts.

    On a multi-core host a vote round trip over localhost TCP completes
    well inside one tick, so ``base`` ticks of election timeout are
    plenty.  On a 1-vCPU runner, N node processes/threads time-share one
    core: the leader's heartbeat can sit unscheduled past base*tick_ms,
    followers start elections they would never start on real hardware,
    and the test flakes on election churn (the known
    test_replicated_group_lifecycle_tcp flake, ROADMAP).  Scale the
    multiplier so the election timeout is at least ``floor_ms`` of wall
    clock when cores are scarce; on >=4 cores the base wins unchanged.
    """
    cores = os.cpu_count() or 1
    if cores >= 4:
        return base
    need = floor_ms / max(1.0, float(tick_ms)) * (2.0 / max(2, cores))
    return max(base, need)


def free_ports(n: int) -> List[int]:
    """Reserve n distinct free localhost TCP ports (close-then-reuse; the
    usual bind(0) probe, shared by every TCP-based test)."""
    import socket
    socks = [socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


class LocalCluster:
    def __init__(self, cfg: EngineConfig, root: str,
                 provider_factory: Optional[Callable[[int], object]] = None,
                 seed: int = 0,
                 maintain_factory: Optional[Callable[[], object]] = None,
                 store_factory: Optional[Callable[[int], object]] = None,
                 serializer_factory: Optional[Callable[[], object]] = None,
                 transport: str = "loopback",
                 pipeline: Optional[bool] = None,
                 wal_shards: Optional[int] = None,
                 host_workers: Optional[int] = None):
        """``provider_factory(node_id)`` returns a MachineProvider; defaults
        to FileMachine per group under ``root/node<i>/machines`` (the
        reference's file-append oracle, cluster/cmd/FileMachine.java).
        ``maintain_factory()`` builds a per-node MaintainAgreement (e.g. the
        reference test configs' aggressive all-thresholds-1 snapshot cadence,
        test/resources/raft1.xml:22-28).
        ``store_factory(node_id)`` builds a LogStoreSPI product per node
        (log/spi.py; default: the durable WAL under the node's data dir).
        ``serializer_factory()`` builds a per-node CmdSerializer
        (api/serial.py; default JSON).
        ``transport``: ``"loopback"`` (in-process, default) or ``"tcp"`` —
        real localhost sockets per node, so the framing / sender-queue /
        reader-thread / accumulator plane is exercised under the same
        manual-tick control (the reference's system test runs real TCP,
        test/resources/raft1.xml:3-7).
        ``pipeline`` / ``wal_shards`` / ``host_workers``: forwarded to
        every RaftNode (see RaftNode.__init__; None = the node's
        env-driven defaults)."""
        self.cfg = cfg
        self.root = root
        self.seed = seed
        self.transport = transport
        self.pipeline = pipeline
        self.wal_shards = wal_shards
        self.host_workers = host_workers
        self.net = LoopbackNetwork(cfg.n_peers)
        # Shared per-directed-link fault table (transport/faults.py):
        # one instance across every node's transport, so the chaos
        # conductor mutates a single source of truth for both backends.
        self.faults = LinkFaults(cfg.n_peers, seed=seed)
        self.net.faults = self.faults
        self._ports = free_ports(cfg.n_peers) if transport == "tcp" else None
        self.provider_factory = provider_factory or (
            lambda i: FileMachineProvider(
                os.path.join(root, f"node{i}", "machines")))
        self.maintain_factory = maintain_factory
        self.store_factory = store_factory
        self.serializer_factory = serializer_factory
        self.nodes: Dict[int, RaftNode] = {}
        for i in range(cfg.n_peers):
            self.start_node(i)

    # -- lifecycle -----------------------------------------------------------

    def _factory(self, node_id: int):
        def build(node, on_slice, snapshot_provider):
            if self.transport == "tcp":
                from ..transport.tcp import TcpTransport
                peers = {i: ("127.0.0.1", p)
                         for i, p in enumerate(self._ports)}
                return TcpTransport(node_id, peers, self.cfg,
                                    node.template, on_slice,
                                    snapshot_provider,
                                    submit_handler=node.submit,
                                    result_encoder=node.serializer
                                    .encode_result,
                                    read_handler=node.read,
                                    conf_node=node,
                                    faults=self.faults)
            return LoopbackTransport(self.net, node_id, self.cfg,
                                     node.template, on_slice,
                                     snapshot_provider,
                                     submit_handler=node.submit,
                                     result_encoder=node.serializer
                                     .encode_result,
                                     read_handler=node.read,
                                     conf_node=node)
        return build

    def start_node(self, i: int) -> RaftNode:
        assert i not in self.nodes
        node = RaftNode(
            self.cfg, i, os.path.join(self.root, f"node{i}"),
            self.provider_factory(i), self._factory(i), seed=self.seed,
            maintain=(self.maintain_factory()
                      if self.maintain_factory else None),
            store=(self.store_factory(i) if self.store_factory else None),
            serializer=(self.serializer_factory()
                        if self.serializer_factory else None),
            pipeline=self.pipeline,
            wal_shards=self.wal_shards,
            host_workers=self.host_workers)
        node.transport.start()
        self.nodes[i] = node
        return node

    def kill_node(self, i: int) -> None:
        """Simulated crash: drop off the network and release files.  No
        graceful flush beyond what each tick already made durable (close
        joins in-flight snapshot workers so the native WAL handle is never
        used after free)."""
        node = self.nodes.pop(i)
        node.close()

    def restart_node(self, i: int) -> RaftNode:
        return self.start_node(i)

    def close(self) -> None:
        for i in list(self.nodes):
            self.kill_node(i)

    # -- stepping ------------------------------------------------------------

    def tick(self, rounds: int = 1) -> None:
        """Lockstep: every live node ticks once per round (node order fixed;
        loopback delivery is immediate, so intra-round ordering mirrors the
        reference's asynchronous delivery)."""
        for _ in range(rounds):
            for node in self.nodes.values():
                node.tick()

    def tick_until(self, pred: Callable[[], bool], max_rounds: int = 500,
                   what: str = "condition") -> None:
        for _ in range(max_rounds):
            if pred():
                return
            self.tick()
        raise AssertionError(f"{what} not reached in {max_rounds} rounds")

    def replay_schedule(self, sched, audit: Optional[Callable[[int], None]]
                        = None) -> None:
        """Host-path nemesis parity: drive the SAME FaultSchedule the
        fused device scan consumes (core/sim.py run_cluster_ticks_nemesis)
        against the full event-loop runtime — real RaftNodes, WAL, state
        machines, codec round-trips over the loopback network.  Tick t:

        * ``crash[t, n]``  -> kill_node + restart_node (rebuild from WAL:
          the host mirror of the engine's in-scan ``crash_restart``);
        * ``link_up[t]``   -> bulk connectivity matrix on the network;
        * ``dup[t]``       -> duplicate-delivery links on the network;
        * ``stall[t, n]``  -> node n simply does not tick (its engine
          clock, timers and sends all freeze, like the device stall).

        ``audit(t)`` runs after every tick (invariant checks, snapshots).
        Used for CPU/TPU cross-validation: the same seed's schedule must
        keep both the vectorized and the event-loop paths safe.
        """
        import numpy as np
        link = np.asarray(sched.link_up)
        crash = np.asarray(sched.crash)
        stall = np.asarray(sched.stall)
        dup = np.asarray(sched.dup)
        try:
            for t in range(link.shape[0]):
                for n in np.nonzero(crash[t])[0].tolist():
                    if n in self.nodes:
                        self.kill_node(int(n))
                        self.restart_node(int(n))
                self.net.set_conn(link[t])
                self.net.set_dup(dup[t])
                for i, node in list(self.nodes.items()):
                    if not stall[t, i]:
                        node.tick()
                if audit is not None:
                    audit(t)
        finally:
            self.net.heal()
            self.net.set_dup(np.zeros((self.cfg.n_peers,) * 2, bool))

    # -- queries -------------------------------------------------------------

    def leader_of(self, group: int) -> Optional[int]:
        """Current leader (highest term if a stale minority leader is still
        deposed-but-unaware).  The election-safety invariant is at most one
        leader per (group, TERM) — two leaders at the SAME term is split
        brain (reference one-leader-per-term asserts, Follower.java:48-50,
        Leader.java:79-81); a stale lower-term claimant is legal Raft."""
        leaders = [(i, int(n.h_term[group])) for i, n in self.nodes.items()
                   if n.h_role[group] == LEADER]
        terms = [t for _, t in leaders]
        assert len(terms) == len(set(terms)), \
            f"split brain in group {group}: same-term leaders {leaders}"
        if not leaders:
            return None
        return max(leaders, key=lambda it: it[1])[0]

    def wait_leader(self, group: int, max_rounds: int = 500) -> int:
        self.tick_until(lambda: self.leader_of(group) is not None,
                        max_rounds, f"leader for group {group}")
        return self.leader_of(group)

    def submit_via_leader(self, group: int, payload: bytes,
                          max_rounds: int = 500):
        """Submit to whoever currently leads, retrying through elections.

        A retry happens ONLY after the previous attempt failed (NotLeader /
        aborted); a still-pending future is never abandoned and resubmitted,
        which could commit the command twice."""
        for _ in range(max_rounds):
            lead = self.leader_of(group)
            if lead is None:
                self.tick()
                continue
            fut = self.nodes[lead].submit(group, payload)
            for _ in range(max_rounds):
                if fut.done():
                    break
                self.tick()
            if not fut.done():
                raise AssertionError(
                    f"submission stuck pending in group {group}")
            if fut.exception() is None:
                return fut.result()
            self.tick()  # leadership moved: drive on, then retry
        raise AssertionError("submission never committed")

    def machine_file(self, node: int, group: int) -> str:
        return os.path.join(self.root, f"node{node}", "machines",
                            f"group_{group}.txt")

    def machine_lines(self, node: int, group: int) -> List[str]:
        path = self.machine_file(node, group)
        if not os.path.exists(path):
            return []
        with open(path) as f:
            return f.readlines()

    def command_lines(self, node: int, group: int) -> List[str]:
        """machine_lines MINUS election no-ops (empty payloads — Raft §8,
        core/step.py phase 3): what client commands actually applied, for
        tests that assert content without depending on how many elections
        the run happened to need."""
        return [l for l in self.machine_lines(node, group)
                if l.split(":", 1)[1].strip()]

    def command_payloads(self, node: int, group: int) -> List[str]:
        return [l.split(":", 1)[1].strip()
                for l in self.command_lines(node, group)]

    def assert_file_parity(self, group: int, require_progress: bool = True
                           ) -> None:
        """The reference's whole-system oracle: replica output files must
        agree on their common prefix, and live nodes that applied everything
        must be byte-identical (README.md:28-33)."""
        files = {i: self.machine_lines(i, group) for i in self.nodes}
        lens = {i: len(ls) for i, ls in files.items()}
        if require_progress:
            assert max(lens.values()) > 0, "no entries applied anywhere"
        base = max(files.values(), key=len)
        for i, ls in files.items():
            assert ls == base[:len(ls)], \
                f"node {i} file diverges from longest replica in group {group}"
