"""LogChecker: offline replica-log differ.

Re-creation of the reference's verification tool (test
cluster/LogChecker.java:9-37: opens two nodes' RocksDB logs offline and
diffs epoch/last/batch entries).  Opens N nodes' WAL directories read-only
and checks the Raft log-matching property over every group: on the index
range where logs overlap (above both compaction floors, up to the shorter
tail) the (term, payload) pairs must be identical.

Usable as a library (the system tests) or a CLI::

    python -m rafting_tpu.testkit.logcheck DIR1 DIR2 [DIR3 ...]
"""

from __future__ import annotations

import dataclasses
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from ..log.store import LogStore


@dataclasses.dataclass
class Divergence:
    group: int
    index: int
    kind: str          # "term" | "payload"
    a: object
    b: object
    node_a: int
    node_b: int

    def __str__(self):
        return (f"group {self.group} index {self.index}: {self.kind} "
                f"mismatch node{self.node_a}={self.a!r} "
                f"node{self.node_b}={self.b!r}")


def check_logs(wal_dirs: Sequence[str], groups: Optional[Sequence[int]] = None,
               max_groups: int = 1 << 20) -> List[Divergence]:
    """Diff N WAL directories; returns all divergences (empty = consistent).

    ``groups`` limits the check; by default every group id seen in any
    store (up to ``max_groups``) is probed via its tail."""
    stores = [LogStore(d) for d in wal_dirs]
    try:
        if groups is None:
            gset = set()
            for st in stores:
                g = 0
                # probe group ids until a long run of empties
                empty_run = 0
                while g < max_groups and empty_run < 64:
                    if st.tail(g) > 0 or st.floor(g) > 0:
                        gset.add(g)
                        empty_run = 0
                    else:
                        empty_run += 1
                    g += 1
            groups = sorted(gset)
        out: List[Divergence] = []
        for g in groups:
            for ai in range(len(stores)):
                for bi in range(ai + 1, len(stores)):
                    out.extend(_diff_pair(stores[ai], stores[bi], g, ai, bi))
        return out
    finally:
        for st in stores:
            st.close()


def _diff_pair(a: LogStore, b: LogStore, g: int, ai: int,
               bi: int) -> List[Divergence]:
    lo = max(a.floor(g), b.floor(g)) + 1
    hi = min(a.tail(g), b.tail(g))
    out = []
    for idx in range(lo, hi + 1):
        ta, tb = a.entry_term(g, idx), b.entry_term(g, idx)
        if ta != tb:
            out.append(Divergence(g, idx, "term", ta, tb, ai, bi))
            continue
        pa, pb = a.payload(g, idx), b.payload(g, idx)
        if pa != pb:
            out.append(Divergence(g, idx, "payload", pa, pb, ai, bi))
    return out


def main(argv: Sequence[str]) -> int:
    if len(argv) < 2:
        print(__doc__)
        return 2
    divs = check_logs(argv)
    if not divs:
        print(f"OK: {len(argv)} logs consistent")
        return 0
    for d in divs:
        print(d)
    return 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
