"""Open-loop traffic harness: offered load that does NOT wait for you.

The closed-loop drivers everywhere else in this repo (bench.py,
LocalCluster tests) submit, wait, submit — so offered load automatically
tracks capacity and latency collapse is INVISIBLE: the system can't be
overloaded by a driver that politely blocks (ROADMAP item 5: "the
current closed-loop burst bench can't see latency collapse").  Real
clients are open-loop: arrivals come from the outside world at their own
rate, and when the system falls behind, queues — not the driver — absorb
the difference.  This module generates that traffic:

* seeded **Poisson** arrivals (exponential inter-arrival at ``rate``)
  and bursty **MMPP** (2-state Markov-modulated Poisson: a quiet rate
  and a burst rate with exponentially-distributed dwells — the classic
  model for flash-crowd traffic);
* **multi-tenant Zipf skew**: tenant identity and target group are both
  drawn Zipf-distributed, so one hot tenant / hot group dominates the
  offered mix exactly the way production keyspaces do;
* **per-request deadlines**: a completion after its deadline is NOT
  goodput — it's work the system wasted on an answer nobody is waiting
  for anymore.

The harness fires each arrival at its scheduled instant (spinning the
caller-supplied ``step`` — usually one cluster tick — while waiting),
registers a done-callback, and moves on WITHOUT awaiting the future.
Results classify every arrival: completed-in-deadline (goodput), late,
shed (typed refusal taxonomy: admission shed / queue-full busy /
routing / unavailable), errored, or still pending at drain end; latency
percentiles (p50/p99/p999) are reported over ADMITTED completions —
the no-collapse property is "goodput plateaus AND admitted p999 stays
bounded", never "nothing is refused".

Everything is deterministic given ``seed`` (arrival times, tenant/group
draws) — completions of course depend on the system under test.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "OpenLoopSpec", "OpenLoopResult", "Transfer", "gen_schedule",
    "gen_transfers", "run_open_loop", "zipf_weights",
]


def zipf_weights(n: int, s: float) -> np.ndarray:
    """Zipf pmf over ranks 1..n with exponent ``s`` (s=0 -> uniform)."""
    w = 1.0 / np.power(np.arange(1, n + 1, dtype=np.float64), s)
    return w / w.sum()


@dataclass
class OpenLoopSpec:
    """One open-loop run's traffic law.

    ``rate``: mean arrivals/second (Poisson), or the QUIET rate when
    ``mmpp`` is set.  ``mmpp``: (burst_rate, mean_quiet_s, mean_burst_s)
    — a 2-state MMPP alternating exponential dwells between ``rate`` and
    ``burst_rate``.  ``deadline_s``: per-request SLO; completions later
    than this are not goodput.  ``tenant_zipf``/``group_zipf``: skew
    exponents (0 = uniform).  ``hot_tenant_share`` (optional override):
    when set, tenant 0 is drawn with exactly this probability and the
    rest uniformly — the one-hot-tenant fairness scenario without
    needing an extreme exponent."""
    rate: float = 200.0
    duration_s: float = 2.0
    n_tenants: int = 4
    n_groups: int = 4
    tenant_zipf: float = 1.1
    group_zipf: float = 0.8
    deadline_s: float = 1.0
    mmpp: Optional[Tuple[float, float, float]] = None
    hot_tenant_share: Optional[float] = None
    seed: int = 0


# One scheduled arrival: (t_offset_s, tenant_name, group_rank).
Arrival = Tuple[float, str, int]


def gen_schedule(spec: OpenLoopSpec) -> List[Arrival]:
    """Materialize the arrival schedule — deterministic in ``spec.seed``.
    Group ranks are 0..n_groups-1 by hotness; the caller maps rank to
    actual group ids (identity is the common case)."""
    rng = random.Random(spec.seed ^ 0x09E37)
    tw = zipf_weights(spec.n_tenants, spec.tenant_zipf)
    if spec.hot_tenant_share is not None and spec.n_tenants > 1:
        rest = (1.0 - spec.hot_tenant_share) / (spec.n_tenants - 1)
        tw = np.array([spec.hot_tenant_share]
                      + [rest] * (spec.n_tenants - 1))
    gw = zipf_weights(spec.n_groups, spec.group_zipf)
    t_cum = np.cumsum(tw)
    g_cum = np.cumsum(gw)

    out: List[Arrival] = []
    t = 0.0
    if spec.mmpp is None:
        lam = spec.rate
        while t < spec.duration_s:
            t += rng.expovariate(lam)
            if t >= spec.duration_s:
                break
            ten = int(np.searchsorted(t_cum, rng.random()))
            grp = int(np.searchsorted(g_cum, rng.random()))
            out.append((t, f"tenant-{ten}", grp))
    else:
        burst_rate, mean_quiet, mean_burst = spec.mmpp
        bursting = False
        # Next modulation switch; dwells are exponential.
        t_switch = rng.expovariate(1.0 / mean_quiet)
        while t < spec.duration_s:
            lam = burst_rate if bursting else spec.rate
            t += rng.expovariate(lam)
            while t >= t_switch:
                bursting = not bursting
                t_switch += rng.expovariate(
                    1.0 / (mean_burst if bursting else mean_quiet))
            if t >= spec.duration_s:
                break
            ten = int(np.searchsorted(t_cum, rng.random()))
            grp = int(np.searchsorted(g_cum, rng.random()))
            out.append((t, f"tenant-{ten}", grp))
    return out


# One scheduled bank transfer: (t_offset_s, tenant, src_group_rank,
# dst_group_rank, src_key, dst_key, amount) — the 2-key txn workload
# for the cross-group transaction plane (runtime/txn.py).
Transfer = Tuple[float, str, int, int, str, str, int]


def gen_transfers(spec: OpenLoopSpec, n_accounts: int = 64,
                  account_zipf: float = 1.0,
                  max_amount: int = 5) -> List[Transfer]:
    """Materialize a seeded transfers-between-accounts schedule on top
    of :func:`gen_schedule`'s arrival law: each arrival becomes a 2-key
    transfer debiting ``src_key`` on the arrival's (Zipf-hot) group and
    crediting ``dst_key`` on a different group, with BOTH account keys
    drawn Zipf over ``n_accounts`` — hot accounts contend, which is
    what gives the 2PC plane real lock conflicts to abort on.  Amounts
    are uniform in [1, max_amount].  Deterministic in ``spec.seed``;
    the sum of all balances is invariant under any subset of these
    transfers applied atomically (testkit/invariants.py judges that)."""
    sched = gen_schedule(spec)
    rng = random.Random(spec.seed ^ 0x72A45)
    a_cum = np.cumsum(zipf_weights(n_accounts, account_zipf))
    out: List[Transfer] = []
    for t, tenant, src in sched:
        if spec.n_groups > 1:
            dst = rng.randrange(spec.n_groups - 1)
            if dst >= src:
                dst += 1
        else:
            dst = src
        a = int(np.searchsorted(a_cum, rng.random()))
        b = int(np.searchsorted(a_cum, rng.random()))
        out.append((t, tenant, src, dst, f"acct{a}", f"acct{b}",
                    1 + rng.randrange(max_amount)))
    return out


@dataclass
class _TenantStat:
    offered: int = 0
    ok: int = 0
    shed: int = 0


@dataclass
class OpenLoopResult:
    """Outcome of one open-loop run.  ``goodput`` counts completions
    within their deadline; ``admitted_lat`` percentiles cover every
    ADMITTED completion (in- or out-of-deadline) — the tail the
    no-collapse property bounds."""
    offered: int = 0
    ok: int = 0                 # completed within deadline (goodput)
    late: int = 0               # completed past deadline
    shed_overload: int = 0      # OverloadError (admission shed)
    shed_busy: int = 0          # BusyLoopError (hard queue bound)
    shed_routing: int = 0       # NotLeader / NotReady
    shed_unavailable: int = 0   # Unavailable / StorageFault
    errors: int = 0             # anything else
    pending: int = 0            # unresolved at drain end
    duration_s: float = 0.0
    p50_s: float = 0.0
    p99_s: float = 0.0
    p999_s: float = 0.0
    per_tenant: Dict[str, _TenantStat] = field(default_factory=dict)

    @property
    def shed(self) -> int:
        return (self.shed_overload + self.shed_busy
                + self.shed_routing + self.shed_unavailable)

    @property
    def goodput(self) -> float:
        return self.ok / self.duration_s if self.duration_s > 0 else 0.0

    @property
    def offered_rate(self) -> float:
        return self.offered / self.duration_s if self.duration_s > 0 \
            else 0.0

    @property
    def shed_rate(self) -> float:
        return self.shed / self.offered if self.offered else 0.0

    def to_dict(self) -> dict:
        return {
            "offered": self.offered, "ok": self.ok, "late": self.late,
            "shed_overload": self.shed_overload,
            "shed_busy": self.shed_busy,
            "shed_routing": self.shed_routing,
            "shed_unavailable": self.shed_unavailable,
            "errors": self.errors, "pending": self.pending,
            "duration_s": round(self.duration_s, 3),
            "offered_rate": round(self.offered_rate, 1),
            "goodput": round(self.goodput, 1),
            "shed_rate": round(self.shed_rate, 4),
            "admitted_p50_s": round(self.p50_s, 6),
            "admitted_p99_s": round(self.p99_s, 6),
            "admitted_p999_s": round(self.p999_s, 6),
        }


def _classify(res: OpenLoopResult, name: str) -> None:
    """Fold one failure outcome (by exception-type NAME — callbacks
    record names, not live objects) into the refusal taxonomy."""
    if name == "OverloadError":
        res.shed_overload += 1
    elif name == "BusyLoopError":
        res.shed_busy += 1
    elif name in ("NotLeaderError", "NotReadyError"):
        res.shed_routing += 1
    elif name in ("UnavailableError", "StorageFaultError"):
        res.shed_unavailable += 1
    else:
        res.errors += 1


def run_open_loop(spec: OpenLoopSpec,
                  submit: Callable[[int, str, int], "object"],
                  step: Optional[Callable[[], None]] = None,
                  drain_s: float = 2.0,
                  schedule: Optional[List[Arrival]] = None
                  ) -> OpenLoopResult:
    """Fire ``spec``'s arrivals open-loop against ``submit(group_rank,
    tenant, seq) -> Future`` and classify every outcome.

    ``step``: called while waiting for the next arrival instant and
    during the drain — pass one cluster tick for lockstep tests (the
    harness then IS the tick driver), or None to sleep (free-running
    cluster / real transport).  The loop never blocks on a future:
    completions land via done-callbacks on whatever thread resolves
    them, so the offered schedule is honored regardless of how far the
    system falls behind — the whole point of open loop.

    ``drain_s``: after the last arrival, keep stepping this long for
    stragglers; whatever is still unresolved is counted ``pending``
    (pending at drain end is latency-collapse evidence, not noise)."""
    sched = gen_schedule(spec) if schedule is None else schedule
    res = OpenLoopResult(duration_s=spec.duration_s)
    for t_arr, tenant, _g in sched:
        res.per_tenant.setdefault(tenant, _TenantStat())

    # Completion records appended from resolver threads: plain list
    # appends are GIL-atomic; the harness only reads after the drain.
    done: List[Tuple[str, float, Optional[str]]] = []

    def fire(tenant: str, grp: int, seq: int) -> None:
        t_sub = time.monotonic()
        st = res.per_tenant[tenant]
        st.offered += 1
        try:
            fut = submit(grp, tenant, seq)
        except Exception as e:   # refusal raised synchronously
            done.append((tenant, 0.0, type(e).__name__))
            return
        if fut is None:          # fire-and-forget submit path
            return

        def _done(f, tenant=tenant, t_sub=t_sub):
            exc = f.exception()
            if exc is None:
                done.append((tenant, time.monotonic() - t_sub, None))
            else:
                done.append((tenant, 0.0, type(exc).__name__))
        fut.add_done_callback(_done)

    t0 = time.monotonic()
    for seq, (t_arr, tenant, grp) in enumerate(sched):
        # Honor the schedule: step (or sleep) until the arrival instant,
        # then fire without waiting.  If we're BEHIND schedule (step took
        # too long), fire immediately — arrivals never queue in the
        # harness itself.
        while time.monotonic() - t0 < t_arr:
            if step is not None:
                step()
            else:
                time.sleep(min(0.001, t_arr - (time.monotonic() - t0)))
        fire(tenant, grp, seq)
    res.offered = len(sched)

    # Drain: give stragglers a bounded chance to resolve.
    t_end = time.monotonic() + drain_s
    while time.monotonic() < t_end and len(done) < res.offered:
        if step is not None:
            step()
        else:
            time.sleep(0.005)

    lats: List[float] = []
    for tenant, lat, kind in done:
        st = res.per_tenant[tenant]
        if kind is None:
            lats.append(lat)
            if lat <= spec.deadline_s:
                res.ok += 1
                st.ok += 1
            else:
                res.late += 1
        else:
            _classify(res, kind)
            st.shed += 1
    res.pending = res.offered - len(done)
    if lats:
        arr = np.asarray(lats)
        res.p50_s = float(np.percentile(arr, 50))
        res.p99_s = float(np.percentile(arr, 99))
        res.p999_s = float(np.percentile(arr, 99.9))
    return res


def no_collapse_check(results: List[OpenLoopResult],
                      slo_s: float,
                      goodput_floor: float = 0.85
                      ) -> Tuple[bool, str]:
    """The acceptance predicate over a rate sweep: past-peak goodput must
    stay >= ``goodput_floor`` x peak, and every sweep point's admitted
    p999 must sit within the SLO.  Returns (ok, human-readable why)."""
    if not results:
        return False, "empty sweep"
    peaks = [r.goodput for r in results]
    peak = max(peaks)
    if peak <= 0:
        return False, "no goodput anywhere in the sweep"
    i_peak = peaks.index(peak)
    for i, r in enumerate(results):
        if i > i_peak and r.goodput < goodput_floor * peak:
            return False, (f"goodput collapsed past peak: point {i} "
                           f"{r.goodput:.1f}/s < {goodput_floor:.0%} of "
                           f"peak {peak:.1f}/s")
        if r.p999_s > slo_s and r.ok:
            return False, (f"admitted p999 {r.p999_s * 1e3:.1f}ms out of "
                           f"SLO {slo_s * 1e3:.1f}ms at point {i}")
    return True, f"peak {peak:.1f}/s, floor held, p999 within SLO"
