"""Seeded chaos conductor: every nemesis under one replayable timeline.

The Jepsen control plane for this repo.  One seeded, audited timeline
composes every fault family the codebase owns against the REAL runtime
(RaftNode + WAL + machines + transport), while recording client
histories (testkit/history.py) for the linearizability checker
(testkit/linz.py):

* network  — asymmetric cuts, full partitions, flaky links
  (drop/dup/delay/reorder) through the shared LinkFaults table
  (transport/faults.py) — both loopback and TCP backends;
* process  — crash (node close, nothing flushed beyond what ticks made
  durable) + restart (WAL/snapshot rebuild) via LocalCluster, and REAL
  ``kill -9`` of separate OS processes via :class:`ProcCluster`;
* storage  — engine-level I/O faults (slow fsync, fail-stop EIO)
  through ``LogStore.set_fault`` (the testkit/faultfs.py plane);
* clock    — stall windows: a node simply does not tick, freezing its
  engine clock, timers and lease receipts;
* control  — membership churn (demote-to-learner / promote-back) and
  leadership transfers through the §6 joint-consensus plane.

Determinism: :func:`plan_chaos` is a pure function of (shape, seed) —
the same seed yields the byte-identical timeline
(:func:`timeline_json`), and the conductor applies events at fixed tick
boundaries over the lockstep harness, so a failing soak replays.  The
conductor records every applied event in ``.applied`` — the audit an
artifact embeds next to the history and the checker verdict
(tools/chaos_run.py).
"""

from __future__ import annotations

import errno
import json
import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass
from random import Random
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api.anomaly import UnavailableError, as_refusal, is_refusal
from .harness import LocalCluster, free_ports
from .history import History

__all__ = [
    "ChaosEvent", "plan_chaos", "plan_leader_isolate", "timeline_json",
    "ChaosConductor", "StubHost", "make_recording_stub", "KVWorkload",
    "TransferWorkload", "ProcCluster",
]


# ---------------------------------------------------------------- timeline --

@dataclass(frozen=True)
class ChaosEvent:
    """One nemesis action at one tick.  ``a``/``b`` are node ids (or a
    group id where noted), ``args`` carries kind-specific payload."""
    tick: int
    kind: str
    a: int = -1
    b: int = -1
    args: tuple = ()

    def to_dict(self) -> dict:
        return {"tick": self.tick, "kind": self.kind, "a": self.a,
                "b": self.b, "args": list(self.args)}


def timeline_json(events: Sequence[ChaosEvent]) -> str:
    """Canonical JSON for a timeline — byte-for-byte reproducible from
    the same (shape, seed), which is what the replay test pins."""
    return json.dumps([e.to_dict() for e in events],
                      sort_keys=True, separators=(",", ":"))


def plan_chaos(n_peers: int, n_ticks: int, seed: int = 0, *,
               period: int = 12,
               mix: Optional[Dict[str, float]] = None,
               max_dur: int = 10,
               storage_fsync_victim: Optional[int] = None,
               churn_group: int = 1) -> Tuple[ChaosEvent, ...]:
    """Compile a seeded mixed-nemesis scenario.

    Every ``period`` ticks one nemesis is drawn from ``mix`` (relative
    weights over: ``asym`` — one-directional cut, ``part`` — full
    partition, ``flaky`` — probabilistic drop/dup/delay/reorder on all
    links, ``kill`` — crash+restart, ``stall`` — clock freeze,
    ``storage`` — slow-I/O window, ``churn`` — leadership transfer or
    demote/promote membership cycle).  Each destructive event schedules
    its own undo (heal / restart / promote) ``dur`` ticks later, and at
    most one node is dead at a time, so a majority can always re-form.

    ``storage_fsync_victim``: additionally arm ONE fail-stop fsync EIO
    on that node mid-run (the quarantine path — its stripe goes silent
    for the rest of the run, so keep it off nodes you will assert final
    parity on).  Pure function of its arguments.
    """
    if mix is None:
        mix = {"asym": 2.0, "part": 2.0, "flaky": 1.5, "kill": 2.0,
               "stall": 1.0, "storage": 1.0, "churn": 1.0}
    kinds = sorted(mix)
    weights = np.array([mix[k] for k in kinds], dtype=float)
    weights = weights / weights.sum()
    rng = np.random.default_rng(seed)
    events: List[ChaosEvent] = []
    node_busy_until = -1   # one crashed node at a time
    net_busy_until = -1    # one network regime at a time (heals reset all)
    for t in range(period, n_ticks - max_dur, period):
        kind = kinds[int(rng.choice(len(kinds), p=weights))]
        dur = int(rng.integers(2, max_dur + 1))
        a = int(rng.integers(0, n_peers))
        b = int(rng.integers(0, n_peers - 1))
        b = b if b < a else b + 1   # a distinct peer
        if kind == "asym":
            if t <= net_busy_until:
                continue
            events.append(ChaosEvent(t, "asym_cut", a, b))
            events.append(ChaosEvent(t + dur, "heal"))
            net_busy_until = t + dur
        elif kind == "part":
            if t <= net_busy_until:
                continue
            side = sorted({a})
            rest = sorted(set(range(n_peers)) - set(side))
            events.append(ChaosEvent(t, "part", args=(tuple(side),
                                                      tuple(rest))))
            events.append(ChaosEvent(t + dur, "heal"))
            net_busy_until = t + dur
        elif kind == "flaky":
            if t <= net_busy_until:
                continue
            drop = round(float(rng.uniform(0.05, 0.3)), 3)
            dup = round(float(rng.uniform(0.0, 0.2)), 3)
            reorder = round(float(rng.uniform(0.0, 0.2)), 3)
            events.append(ChaosEvent(t, "flaky",
                                     args=(drop, dup, reorder)))
            events.append(ChaosEvent(t + dur, "heal"))
            net_busy_until = t + dur
        elif kind == "kill":
            if t <= node_busy_until:
                continue
            events.append(ChaosEvent(t, "kill", a))
            events.append(ChaosEvent(t + dur, "restart", a))
            node_busy_until = t + dur
        elif kind == "stall":
            if t <= node_busy_until:
                continue
            events.append(ChaosEvent(t, "stall", a, args=(dur,)))
            node_busy_until = t + dur
        elif kind == "storage":
            events.append(ChaosEvent(t, "storage_delay", a,
                                     args=(2000,)))
        elif kind == "churn":
            if int(rng.integers(0, 2)):
                events.append(ChaosEvent(t, "churn_transfer", a,
                                         args=(churn_group,)))
            else:
                events.append(ChaosEvent(t, "churn_demote", a,
                                         args=(churn_group,)))
                events.append(ChaosEvent(t + dur, "churn_promote", a,
                                         args=(churn_group,)))
    if storage_fsync_victim is not None:
        events.append(ChaosEvent(n_ticks // 2, "storage_fsync",
                                 int(storage_fsync_victim)))
    events.sort(key=lambda e: (e.tick, e.kind, e.a, e.b))
    return tuple(events)


def plan_leader_isolate(n_ticks: int, seed: int = 0, *,
                        group: int = 0, period: int = 40,
                        dur: int = 25) -> Tuple[ChaosEvent, ...]:
    """Compile the GRAY-FAILURE nemesis: periodically cut every link
    INTO ``group``'s current leader while its outbound links stay up.

    This is the asymmetric fault CheckQuorum exists for (tests/
    test_checkquorum.py): the victim's heartbeats still reach — and
    keep suppressing — every follower's election timer, but it hears no
    acks and no higher term, so neither phase-1 step-down nor a normal
    election can ever fire.  Without ``cfg.check_quorum`` the group is
    hostage for the whole window; with it the leader steps itself down
    within an election timeout and the healthy majority re-elects.

    The victim is resolved AT APPLY TIME (the conductor's
    ``_leader_node``), not at plan time — after the first step-down a
    later period isolates whoever leads NOW, so the nemesis keeps
    biting across re-elections.  Each cut schedules its heal ``dur``
    ticks later.  Pure function of its arguments (the timeline is
    replayable; only the victim binding is runtime state, and the
    conductor's ``applied`` audit records who it hit)."""
    events: List[ChaosEvent] = []
    rng = Random(seed)
    for t in range(period, n_ticks - dur, period):
        jitter = rng.randrange(0, max(period // 4, 1))
        events.append(ChaosEvent(t + jitter, "leader_isolate",
                                 args=(group,)))
        events.append(ChaosEvent(t + jitter + dur, "heal"))
    events.sort(key=lambda e: (e.tick, e.kind, e.a, e.b))
    return tuple(events)


# --------------------------------------------------------------- conductor --

class ChaosConductor:
    """Apply a timeline over a LocalCluster, tick by tick, while client
    threads drive load concurrently.  Audited: ``applied`` records every
    event actually applied, in order, for the artifact."""

    def __init__(self, cluster: LocalCluster, events: Sequence[ChaosEvent]):
        self.cluster = cluster
        self.events = list(events)
        self._by_tick: Dict[int, List[ChaosEvent]] = {}
        for ev in self.events:
            self._by_tick.setdefault(ev.tick, []).append(ev)
        self.horizon = max((e.tick for e in self.events), default=0)
        self.t = 0
        self.applied: List[dict] = []
        self._stalled_until: Dict[int, int] = {}

    # -- event application ---------------------------------------------------

    def _leader_node(self, group: int):
        try:
            lead = self.cluster.leader_of(group)
        except AssertionError:
            raise
        return None if lead is None else self.cluster.nodes.get(lead)

    def _apply(self, ev: ChaosEvent) -> None:
        c, f = self.cluster, self.cluster.faults
        extra: dict = {}
        try:
            if ev.kind == "asym_cut":
                f.set_link(ev.a, ev.b, False)
            elif ev.kind == "leader_isolate":
                # Gray failure: inbound-only cut of the group's CURRENT
                # leader — its outbound heartbeats keep flowing (that is
                # the whole point; LinkFaults.isolate cuts both ways and
                # would let ordinary elections handle it).  Victim is
                # resolved now and recorded in the audit.
                g = int(ev.args[0])
                node = self._leader_node(g)
                if node is None:
                    raise RuntimeError(f"group {g} has no leader to "
                                       "isolate")
                lead = node.node_id
                for o in range(c.cfg.n_peers):
                    if o != lead:
                        f.set_link(o, lead, False)
                extra["victim"] = int(lead)
            elif ev.kind == "part":
                f.partition([list(s) for s in ev.args])
            elif ev.kind == "flaky":
                drop, dup, reorder = ev.args[:3]
                f.set_all_flaky(drop_p=drop, dup_p=dup, reorder_p=reorder,
                                delay_p=0.0)
            elif ev.kind == "heal":
                f.heal()
                c.net.flush_held()
            elif ev.kind == "kill":
                if ev.a in c.nodes:
                    c.kill_node(ev.a)
            elif ev.kind == "restart":
                if ev.a not in c.nodes:
                    c.restart_node(ev.a)
            elif ev.kind == "stall":
                self._stalled_until[ev.a] = self.t + int(ev.args[0])
            elif ev.kind == "storage_delay":
                node = c.nodes.get(ev.a)
                if node is not None:
                    node.store.set_fault("delay", value=int(ev.args[0]))
            elif ev.kind == "storage_fsync":
                node = c.nodes.get(ev.a)
                if node is not None:
                    node.store.set_fault("fsync", value=errno.EIO)
            elif ev.kind == "churn_transfer":
                g = int(ev.args[0])
                node = self._leader_node(g)
                if node is not None and ev.a in c.nodes:
                    node.transfer_leadership(g, ev.a)   # fire and forget
            elif ev.kind == "churn_demote":
                g = int(ev.args[0])
                node = self._leader_node(g)
                full = (1 << c.cfg.n_peers) - 1
                if node is not None and node.node_id != ev.a:
                    node.change_membership(g, full & ~(1 << ev.a),
                                           1 << ev.a)
            elif ev.kind == "churn_promote":
                g = int(ev.args[0])
                node = self._leader_node(g)
                full = (1 << c.cfg.n_peers) - 1
                if node is not None:
                    node.change_membership(g, full, 0)
            self.applied.append({"t": self.t, **ev.to_dict(), **extra})
        except AssertionError:
            raise            # split-brain oracle must fail loudly
        except Exception as e:
            # Nemesis application is best-effort (the leader may be mid-
            # election, the membership plane busy) — record the miss.
            self.applied.append({"t": self.t, **ev.to_dict(),
                                 "error": type(e).__name__})

    # -- stepping ------------------------------------------------------------

    def step(self) -> None:
        for ev in self._by_tick.pop(self.t, []):
            self._apply(ev)
        for i, node in list(self.cluster.nodes.items()):
            if self._stalled_until.get(i, -1) > self.t:
                continue   # clock stall: the node's world freezes
            node.tick()
        self.t += 1

    def run(self, extra_ticks: int = 0, tick_sleep: float = 0.0) -> None:
        """Drive the whole timeline (plus ``extra_ticks``).  A small
        ``tick_sleep`` yields the GIL to client threads on starved
        hosts."""
        end = self.horizon + 1 + extra_ticks
        while self.t < end:
            self.step()
            if tick_sleep:
                time.sleep(tick_sleep)

    def finish(self, settle_rounds: int = 800) -> None:
        """Heal the world and drive to convergence: all faults cleared,
        dead nodes restarted (WAL/snapshot recovery), stalls released,
        full voter sets restored, every group led again."""
        c = self.cluster
        c.faults.heal()
        c.net.flush_held()
        self._stalled_until.clear()
        for i in range(c.cfg.n_peers):
            if i not in c.nodes:
                c.restart_node(i)
        for node in c.nodes.values():
            try:
                node.store.set_fault("delay", value=0)  # delay is sticky
                node.store.clear_faults()
            except Exception:
                pass
        c.tick(5)
        full = (1 << c.cfg.n_peers) - 1
        for g in range(c.cfg.n_groups):
            c.wait_leader(g, max_rounds=settle_rounds)
            node = self._leader_node(g)
            if node is None:
                continue
            m = node.membership(g)
            if m["voters"] != full or m["learners"] or m["joint"]:
                try:
                    node.change_membership(g, full, 0)
                except Exception:
                    pass
        c.tick(30)
        for g in range(c.cfg.n_groups):
            c.wait_leader(g, max_rounds=settle_rounds)


# ------------------------------------------------------------ client plane --

class StubHost:
    """Adapter giving RaftStub a container-shaped view of one LocalCluster
    node.  ``_node`` re-resolves per use, so a stub transparently follows
    its node through kill/restart cycles; while the node is down every
    call fails with a MARKED UnavailableError (the op provably never
    started — recorded ``fail``, the history stays sound)."""

    def __init__(self, cluster: LocalCluster, node_id: int):
        self.cluster = cluster
        self.node_id = node_id

    @property
    def _node(self):
        n = self.cluster.nodes.get(self.node_id)
        if n is None:
            raise as_refusal(UnavailableError(
                f"node {self.node_id} is down (chaos)"))
        return n

    def _lookup(self, name: str) -> Optional[int]:
        return int(name)        # the stub name IS the lane number here

    def _release_stub(self, name: str) -> int:
        return 0


def make_recording_stub(cluster: LocalCluster, node_id: int, group: int,
                        history: History, proc: str, *,
                        forward_budget: float = 6.0):
    """A RaftStub over ``cluster.nodes[node_id]`` for ``group``, with
    history recording attached as client process ``proc``."""
    from ..api.stub import RaftStub

    stub = RaftStub(StubHost(cluster, node_id), name=str(group),
                    lane=group, forward=True,
                    forward_budget=forward_budget)
    return stub.attach_history(history, proc)


class KVWorkload:
    """N client threads driving seeded set/add/get traffic at one group
    through recording stubs, while the conductor ticks concurrently.

    Register keys (``r*``) take unique writes (``{proc}-{seq}``), list
    keys (``l*``) take unique appends — so every read is unambiguously
    explained (or not) by the checker, and a duplicate apply of any
    append is observable."""

    def __init__(self, cluster: LocalCluster, history: History, *,
                 group: int = 1, clients: int = 3, seed: int = 0,
                 regs: int = 3, lists: int = 1, read_ratio: float = 0.4,
                 op_timeout: float = 6.0):
        self.cluster = cluster
        self.history = history
        self.group = group
        self.seed = seed
        self.regs = regs
        self.lists = lists
        self.read_ratio = read_ratio
        self.op_timeout = op_timeout
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(target=self._client, args=(c,),
                             name=f"chaos-client-{c}", daemon=True)
            for c in range(clients)]
        self.ops_attempted = 0

    def start(self) -> None:
        for t in self._threads:
            t.start()

    def stop(self) -> None:
        self._stop.set()

    def join(self, tick_fn=None, timeout: float = 60.0) -> None:
        """Join the client threads; ``tick_fn`` keeps the cluster ticking
        while clients drain their in-flight (blocking) operations —
        without it a pending future never resolves and every client
        would ride out its full op timeout."""
        deadline = time.monotonic() + timeout
        while any(t.is_alive() for t in self._threads):
            if tick_fn is not None:
                tick_fn()
            time.sleep(0.01)
            if time.monotonic() > deadline:
                break
        for t in self._threads:
            t.join(timeout=max(0.1, deadline - time.monotonic()))

    def _client(self, c: int) -> None:
        rng = Random(self.seed * 9176 + c)
        n_peers = self.cluster.cfg.n_peers
        stub = make_recording_stub(self.cluster, c % n_peers, self.group,
                                   self.history, f"c{c}",
                                   forward_budget=self.op_timeout)
        seq = 0
        while not self._stop.is_set():
            r = rng.random()
            try:
                if r < self.read_ratio:
                    pool = self.regs + self.lists
                    j = rng.randrange(pool)
                    key = (f"r{j}" if j < self.regs
                           else f"l{j - self.regs}")
                    stub.execute_read(json.dumps({"op": "get", "k": key}),
                                      timeout=self.op_timeout)
                elif r < self.read_ratio + (1 - self.read_ratio) * 0.6:
                    key = f"r{rng.randrange(self.regs)}"
                    stub.execute(json.dumps(
                        {"op": "set", "k": key, "v": f"c{c}-{seq}"}),
                        timeout=self.op_timeout)
                else:
                    key = f"l{rng.randrange(self.lists)}"
                    stub.execute(json.dumps(
                        {"op": "add", "k": key, "v": f"c{c}-{seq}"}),
                        timeout=self.op_timeout)
            except Exception:
                pass    # outcome already classified into the history
            seq += 1
            self.ops_attempted += 1
            # Brief jittered pause: yields the GIL to the tick thread
            # (1-vCPU hosts) and decorrelates the clients.
            time.sleep(0.002 + rng.random() * 0.006)


class TransferWorkload:
    """N client threads driving seeded cross-group bank transfers through
    the 2PC plane (runtime/txn.py) while the conductor ticks concurrently.

    Each transfer moves ``amount`` between two accounts in two DIFFERENT
    Raft groups via ``stub.txn().transfer(...)``.  Outcomes are recorded
    as kind-``t`` ops in the history — linz.py refuses those by design;
    the judgment for this workload is check_transfer_atomicity over the
    converged machines, plus balance conservation (transfers are
    zero-sum, so the acct* total never moves).

    History classification mirrors StubRecorder: a returned decision
    (commit OR abort) is ``ok`` — both are definite outcomes; a MARKED
    refusal (admission txn-shed, node down) is ``fail`` — the plane
    proves no PREPARE was sent; anything else is ``info`` — the txn is
    in doubt and the deadline sweep owns its resolution."""

    def __init__(self, cluster: LocalCluster, history: History, *,
                 coord_group: int = 0, groups: Sequence[int] = (1, 2),
                 clients: int = 3, seed: int = 0, accounts: int = 8,
                 max_amount: int = 5, deadline_s: float = 4.0,
                 op_timeout: float = 8.0):
        assert len(groups) >= 2, "transfers need two distinct groups"
        assert coord_group not in groups, \
            "coordinator group must not double as a participant"
        self.cluster = cluster
        self.history = history
        self.coord_group = coord_group
        self.groups = list(groups)
        self.seed = seed
        self.accounts = accounts
        self.max_amount = max_amount
        self.deadline_s = deadline_s
        self.op_timeout = op_timeout
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(target=self._client, args=(c,),
                             name=f"xfer-client-{c}", daemon=True)
            for c in range(clients)]
        self.attempted = 0
        self.committed = 0
        self.aborted = 0
        self.refused = 0
        self.unknown = 0

    def start(self) -> None:
        for t in self._threads:
            t.start()

    def stop(self) -> None:
        self._stop.set()

    def join(self, tick_fn=None, timeout: float = 60.0) -> None:
        """Join the client threads while ``tick_fn`` keeps the cluster
        ticking (a blocked 2PC driver needs the coordinator and both
        participants to keep committing)."""
        deadline = time.monotonic() + timeout
        while any(t.is_alive() for t in self._threads):
            if tick_fn is not None:
                tick_fn()
            time.sleep(0.01)
            if time.monotonic() > deadline:
                break
        for t in self._threads:
            t.join(timeout=max(0.1, deadline - time.monotonic()))

    def counts(self) -> dict:
        return {"attempted": self.attempted, "committed": self.committed,
                "aborted": self.aborted, "refused": self.refused,
                "unknown": self.unknown}

    def _client(self, c: int) -> None:
        rng = Random(self.seed * 7841 + c)
        n_peers = self.cluster.cfg.n_peers
        host = StubHost(self.cluster, c % n_peers)
        from ..api.stub import RaftStub
        coord = RaftStub(host, name=str(self.coord_group),
                         lane=self.coord_group, forward=True,
                         forward_budget=self.op_timeout)
        parts = {g: RaftStub(host, name=str(g), lane=g, forward=True,
                             forward_budget=self.op_timeout)
                 for g in self.groups}
        while not self._stop.is_set():
            sg = self.groups[rng.randrange(len(self.groups))]
            dg = sg
            while dg == sg:
                dg = self.groups[rng.randrange(len(self.groups))]
            sk = f"acct{rng.randrange(self.accounts)}"
            dk = f"acct{rng.randrange(self.accounts)}"
            amt = 1 + rng.randrange(self.max_amount)
            op_id = self.history.invoke(
                f"x{c}", "t", f"{sg}/{sk}->{dg}/{dk}", amt)
            self.attempted += 1
            try:
                r = (coord.txn(deadline_s=self.deadline_s)
                     .transfer(parts[sg], sk, parts[dg], dk, amt)
                     .execute(timeout=self.op_timeout))
            except Exception as e:
                if is_refusal(e):
                    self.refused += 1
                    self.history.fail(op_id, type(e).__name__)
                else:
                    self.unknown += 1
                    self.history.info(op_id, type(e).__name__)
            else:
                if r.committed:
                    self.committed += 1
                else:
                    self.aborted += 1
                self.history.ok(op_id, {"txn": r.txn,
                                        "decision": r.decision})
            # Yield to the tick thread and decorrelate the clients.
            time.sleep(0.002 + rng.random() * 0.006)


# ------------------------------------------------------- real-process tier --

PROC_XML = """<raft>
  <cluster>
    <local>{local}</local>
    {remotes}
  </cluster>
  <timing tick="10" heartbeat="1" election="{election}" broadcast="0.5"
          pre-vote="true"/>
  <engine groups="{groups}" log-slots="64" batch="8" max-submit="8"/>
  <snapshot state-change-threshold="64" dirty-log-tolerance="16"
            snap-min-interval="20" compact-min-interval="10" slack="8"/>
  <storage dir="{data_dir}"/>
</raft>
"""


class ProcCluster:
    """Real OS processes on localhost TCP: the SIGKILL nemesis substrate
    (extracted from tests/test_system_procs.py so the chaos plane and
    the system test share one set of plumbing).  Each node runs
    ``rafting_tpu.tools.noderun`` in its own interpreter — separate
    address spaces, hard kills, crash recovery from disk alone."""

    def __init__(self, root, n: int = 3, groups: int = 4,
                 election_mul: float = 3.0):
        self.root = root
        self.n = n
        self.repo = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        ports = free_ports(n)
        self.uris = [f"raft://127.0.0.1:{p}" for p in ports]
        self.cfgs = []
        for i in range(n):
            remotes = "\n    ".join(f"<remote>{u}</remote>"
                                    for j, u in enumerate(self.uris)
                                    if j != i)
            p = os.path.join(str(root), f"node{i}.xml")
            with open(p, "w") as fh:
                fh.write(PROC_XML.format(
                    local=self.uris[i], remotes=remotes, groups=groups,
                    election=election_mul,
                    data_dir=os.path.join(str(root), f"node{i}")))
            self.cfgs.append(p)
        self.procs: Dict[int, subprocess.Popen] = {}

    # -- lifecycle -----------------------------------------------------------

    def start(self, i: int) -> subprocess.Popen:
        env = dict(os.environ)
        env["PYTHONPATH"] = self.repo
        env["JAX_PLATFORMS"] = "cpu"
        out = open(os.path.join(str(self.root), f"node{i}.out"), "ab")
        p = subprocess.Popen(
            [sys.executable, "-m", "rafting_tpu.tools.noderun",
             self.cfgs[i]],
            env=env, cwd=self.repo, stdout=out, stderr=out)
        self.procs[i] = p
        return p

    def start_all(self) -> None:
        for i in range(self.n):
            self.start(i)

    def sigkill(self, i: int) -> None:
        """The nemesis: ``kill -9``, no flush, no goodbye."""
        os.kill(self.procs[i].pid, signal.SIGKILL)
        self.procs[i].wait(timeout=10)

    def sigterm_all(self, timeout: float = 120.0) -> List[int]:
        for p in self.procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        return [p.wait(timeout=timeout) for p in self.procs.values()]

    def close(self) -> None:
        for p in self.procs.values():
            if p.poll() is None:
                p.kill()

    # -- observation ---------------------------------------------------------

    def out_path(self, i: int) -> str:
        return os.path.join(str(self.root), f"node{i}.out")

    def ready_count(self, i: int) -> int:
        p = self.out_path(i)
        if not os.path.exists(p):
            return 0
        with open(p, "rb") as f:
            return f.read().count(b"READY lane=")

    def ready_lanes(self, i: int) -> List[int]:
        p = self.out_path(i)
        if not os.path.exists(p):
            return []
        lanes = []
        with open(p, "rb") as f:
            for ln in f.read().splitlines():
                if ln.startswith(b"READY lane="):
                    lanes.append(int(ln.split(b"=")[1].split(b" ")[0]))
        return lanes

    def status(self, i: int) -> Optional[dict]:
        try:
            with open(os.path.join(str(self.root), f"node{i}",
                                   "status.json")) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def total_acked(self, alive=None) -> int:
        total = 0
        for i in (alive if alive is not None else range(self.n)):
            s = self.status(i)
            if s:
                total += s["acked"]
        return total

    def leader(self) -> Optional[int]:
        for i in range(self.n):
            s = self.status(i)
            if s and s.get("leader"):
                return i
        return None

    def machine_lines(self, i: int, lane: int) -> List[str]:
        p = os.path.join(str(self.root), f"node{i}", "machines",
                         f"group_{lane}.txt")
        if not os.path.exists(p):
            return []
        with open(p) as f:
            return f.read().splitlines()

    def acked_payloads(self, i: int) -> List[str]:
        """Payloads node i's load loop saw acknowledged (the runner's
        client-side oracle file)."""
        p = os.path.join(str(self.root), f"node{i}", "acked.txt")
        if not os.path.exists(p):
            return []
        with open(p) as f:
            return f.read().split()

    def wal_dirs(self) -> List[str]:
        return [os.path.join(str(self.root), f"node{i}", "wal")
                for i in range(self.n)]

    @staticmethod
    def wait(pred, what: str, timeout: float) -> None:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if pred():
                return
            time.sleep(0.25)
        raise AssertionError(f"{what} not reached in {timeout}s")

    # -- the seeded kill schedule -------------------------------------------

    def run_kill_schedule(self, events: Sequence[ChaosEvent], *,
                          step_s: float = 1.0,
                          progress_per_step: int = 0) -> List[dict]:
        """Interpret a timeline's kill/restart events in wall-clock time
        (``tick`` * ``step_s`` seconds from start).  Other kinds are
        ignored — real processes expose no mid-run fault controls.
        Returns the applied audit."""
        applied = []
        t0 = time.time()
        for ev in sorted(events, key=lambda e: e.tick):
            if ev.kind not in ("kill", "restart"):
                continue
            when = t0 + ev.tick * step_s
            while time.time() < when:
                time.sleep(0.1)
            if ev.kind == "kill" and self.procs[ev.a].poll() is None:
                self.sigkill(ev.a)
                applied.append({"t": ev.tick, **ev.to_dict()})
            elif ev.kind == "restart" and self.procs[ev.a].poll() is not None:
                self.start(ev.a)
                applied.append({"t": ev.tick, **ev.to_dict()})
        return applied
