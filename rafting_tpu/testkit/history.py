"""Client-history recording for linearizability checking (Jepsen's
invoke/ok/fail/info model).

A :class:`History` is a concurrent, append-only event log of client
operations as the CLIENTS saw them — the raw material testkit/linz.py
checks.  Each operation is an ``invoke`` event paired (maybe) with a
completion:

* ``ok``   — the operation returned a result; it MUST linearize.
* ``fail`` — the operation provably did NOT happen (a MARKED pre-log
  refusal, api/anomaly.py: the node guarantees the command never
  entered any log); the checker excludes it.
* ``info`` — outcome UNKNOWN: timeouts, crash windows, unmarked errors
  (an accept-then-abort ``NotLeaderError``, a bare ``StorageFaultError``
  after acceptance).  The operation MAY have taken effect at any point
  after its invocation — even after the "end" of the history — so the
  checker treats it as forever-concurrent: free to linearize anywhere
  after invoke, or never.

The classification rule is the repo's refusal-marking protocol
(api/anomaly.py as_refusal/is_refusal): marked = provably-not-executed =
``fail``; everything else that isn't a result is ``info``.  Getting this
wrong in the conservative direction (unknown recorded as ``fail``) makes
the checker UNSOUND — a retry of a command whose first attempt actually
committed then looks like a duplicate apply out of nowhere.
tests/test_linz.py pins both directions.

:class:`StubRecorder` is the RaftStub hook (``stub.attach_history``):
it wraps blocking ``execute``/``execute_read`` calls, parses the KV
command vocabulary (machine/kv_machine.py JSON ops) into typed ops, and
applies the classification rule.  When no recorder is attached the stub
pays exactly one is-None test (tests/test_hotpath_lint.py).
"""

from __future__ import annotations

import copy
import json
import math
import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from ..api.anomaly import is_refusal

__all__ = ["Op", "History", "StubRecorder"]

# Op kinds: "w" register write (KV set), "a" list append (KV add),
# "r" read (KV get), "t" cross-group transaction (runtime/txn.py — a
# multi-key op the per-key Wing & Gong checker must NOT judge; linz.py
# refuses "t" ops and routes callers to the transfer invariant,
# testkit/invariants.py check_transfer_atomicity).
_KINDS = ("w", "a", "r", "t")


@dataclass
class Op:
    """One paired client operation (the checker's unit of work)."""
    id: int
    proc: str
    kind: str            # "w" | "a" | "r"
    key: str
    value: Any = None    # written value (w/a); None for reads
    status: str = "info"  # "ok" | "fail" | "info"
    result: Any = None   # returned value (ok reads)
    error: str = ""      # exception type name (fail/info)
    invoke_seq: int = 0  # global total order of invocations/completions
    resp_seq: float = math.inf   # inf = never completed (info forever)

    def describe(self) -> str:
        what = {"w": f"w {self.key}={self.value!r}",
                "a": f"a {self.key}+={self.value!r}",
                "r": f"r {self.key}",
                "t": f"t {self.key} {self.value!r}"}[self.kind]
        end = (f"{self.status}@{int(self.resp_seq)}"
               if math.isfinite(self.resp_seq) else f"{self.status}@∞")
        got = f" -> {self.result!r}" if self.status == "ok" else \
              (f" ({self.error})" if self.error else "")
        return (f"op {self.id:<4} [{self.proc}] {what:<24} "
                f"invoke@{self.invoke_seq:<5} {end}{got}")


class History:
    """Thread-safe invoke/ok/fail/info event log.

    Events carry a single global sequence number, so the real-time
    precedence relation the checker needs (op A completed before op B
    was invoked) is exact regardless of which client thread recorded
    what."""

    def __init__(self):
        self._lock = threading.Lock()
        self._seq = 0
        self.events: List[dict] = []
        self._next_id = 0

    def _stamp(self, ev: dict) -> dict:
        with self._lock:
            ev["seq"] = self._seq
            self._seq += 1
            self.events.append(ev)
        return ev

    # -- recording -----------------------------------------------------------

    def invoke(self, proc: str, kind: str, key: str,
               value: Any = None) -> int:
        assert kind in _KINDS, kind
        with self._lock:
            op_id = self._next_id
            self._next_id += 1
        self._stamp({"e": "invoke", "id": op_id, "proc": proc,
                     "kind": kind, "key": key, "v": value})
        return op_id

    def ok(self, op_id: int, result: Any = None) -> None:
        # Deep-copy: a local read may return a LIVE machine object (the
        # KV machine hands out its actual list); recording a reference
        # would let later appends rewrite what this read "saw".
        self._stamp({"e": "ok", "id": op_id,
                     "result": copy.deepcopy(result)})

    def fail(self, op_id: int, error: str = "") -> None:
        """The operation provably never happened (marked refusal ONLY)."""
        self._stamp({"e": "fail", "id": op_id, "error": error})

    def info(self, op_id: int, error: str = "") -> None:
        """Outcome unknown: may have happened, now or later."""
        self._stamp({"e": "info", "id": op_id, "error": error})

    # -- views ---------------------------------------------------------------

    def ops(self) -> List[Op]:
        """Pair events into Ops.  Invokes with no completion (a client
        thread that died in a crash window) are info-forever."""
        with self._lock:
            events = list(self.events)
        out: Dict[int, Op] = {}
        for ev in events:
            if ev["e"] == "invoke":
                out[ev["id"]] = Op(id=ev["id"], proc=ev["proc"],
                                   kind=ev["kind"], key=ev["key"],
                                   value=ev["v"], invoke_seq=ev["seq"])
            else:
                op = out[ev["id"]]
                op.status = ev["e"]
                op.resp_seq = ev["seq"]
                op.result = ev.get("result")
                op.error = ev.get("error", "")
        for op in out.values():
            if op.status == "info" and op.resp_seq != math.inf:
                # Explicit info: completion time is known but meaningless
                # for ordering — the op may take effect later than it.
                op.resp_seq = math.inf
            elif op.status not in ("ok", "fail"):
                op.status = "info"   # unpaired invoke
        return [out[k] for k in sorted(out)]

    def by_key(self) -> Dict[str, List[Op]]:
        keys: Dict[str, List[Op]] = {}
        for op in self.ops():
            keys.setdefault(op.key, []).append(op)
        return keys

    def counts(self) -> Dict[str, int]:
        c = {"ok": 0, "fail": 0, "info": 0}
        for op in self.ops():
            c[op.status] += 1
        return c

    def to_json(self) -> list:
        """JSON-shaped event list (chaos artifacts embed it verbatim)."""
        with self._lock:
            return [dict(ev) for ev in self.events]


class StubRecorder:
    """The RaftStub history hook: one instance per client process
    identity, installed with ``stub.attach_history(history, proc)``.

    Wraps the blocking paths only (``execute`` / ``execute_read``) —
    they are where a client learns an outcome, which is what a history
    is made of.  Classification (the load-bearing part):

    * return value            -> ``ok``
    * MARKED refusal          -> ``fail``  (provably pre-log: NotLeader
      hint bounce, NotReady, admission shed, quarantined stripe — the
      node promises the command never entered any log)
    * anything else           -> ``info``  (WaitTimeout: still in
      flight; unmarked NotLeader: accept-then-abort, may still commit
      under the new leader; unmarked StorageFault: accepted entries on
      a faulted stripe; transport RaftError: the forward channel died
      mid-call).  A retry the caller issues after ``info`` can
      therefore double-apply — by design the HISTORY stays sound:
      either zero or one effect per recorded op, duplicates show up as
      two ops of which one was info (legal) or as a non-linearizable
      read (caught), never as silent acceptance.
    """

    def __init__(self, history: History, proc: str):
        self.history = history
        self.proc = proc

    @staticmethod
    def _parse(command) -> tuple:
        """Map the KV JSON vocabulary to (kind, key, value); unknown
        commands become whole-machine register writes so arbitrary
        traffic still yields a checkable (if coarse) history."""
        try:
            raw = command.decode() if isinstance(command, bytes) else command
            cmd = json.loads(raw)
            op = cmd.get("op")
            if op == "set":
                return "w", str(cmd.get("k")), cmd.get("v")
            if op == "add":
                return "a", str(cmd.get("k")), cmd.get("v")
            if op == "get":
                return "r", str(cmd.get("k")), None
        except (ValueError, AttributeError, TypeError):
            pass
        return "w", "__cmd__", str(command)

    def _classify(self, op_id: int, exc: BaseException) -> None:
        if is_refusal(exc):
            self.history.fail(op_id, type(exc).__name__)
        else:
            self.history.info(op_id, type(exc).__name__)

    def execute(self, stub, command, timeout: Optional[float]) -> Any:
        kind, key, value = self._parse(command)
        op_id = self.history.invoke(self.proc, kind, key, value)
        try:
            result = stub._execute(command, timeout)
        except BaseException as e:
            self._classify(op_id, e)
            raise
        self.history.ok(op_id, result)
        return result

    def execute_read(self, stub, query, timeout: Optional[float]) -> Any:
        kind, key, _ = self._parse(query)
        op_id = self.history.invoke(self.proc, "r", key)
        try:
            result = stub._execute_read(query, timeout)
        except BaseException as e:
            # Reads never mutate state; fail vs info only affects whether
            # the checker may discard them — it discards both, so the
            # same refusal-marking rule keeps the bookkeeping honest.
            self._classify(op_id, e)
            raise
        self.history.ok(op_id, result)
        return result
