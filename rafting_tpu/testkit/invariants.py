"""Cluster-level Raft protocol invariants, checked over live histories.

The reference encodes its invariants as ~30 runtime ``AssertionError``s
scattered through the hot path (e.g. one-leader-per-term,
Follower.java:48-50 / Leader.java:79-81; monotonic matchIndex,
Leadership.java:76-81; log continuity, RocksLog.java:175-187).  Here they
are lifted into an external checker that audits full cluster snapshots
between ticks — usable both in unit tests and in the chaos/fuzz harness
(BASELINE.md configs 2-5).

Checked invariants (Raft paper §5.2-§5.4 terminology):

* **Election safety** — at most one leader per (group, term), across the
  entire history.
* **Log matching** — if two nodes hold an entry with the same (index,
  term), their logs are identical up to that index.  Checked on the
  intersection of live windows (above both compaction floors).
* **Leader completeness / commit stability** — once an entry is committed
  at (index, term), no later state of any node commits a different term at
  that index; the committed frontier never regresses on any node.
* **Term monotonicity** — per (node, group), currentTerm never decreases.

Plus the TRANSACTION invariant (:func:`check_transfer_atomicity`): the
Jepsen bank-test judgment for the cross-group 2PC plane
(runtime/txn.py), audited over converged machine state instead of a
client history — total balance conserved, no lost or phantom
transfers, no half-applied decision, every in-doubt participant
resolved.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..core.cluster import cluster_snapshot  # noqa: F401 — audit currency
from ..core.types import LEADER


class InvariantViolation(AssertionError):
    pass


def check_transfer_atomicity(coordinator, participants: Dict[int, Any],
                             initial_total: Optional[int] = None,
                             key_prefix: str = "acct") -> dict:
    """The bank-transfer atomicity judgment over CONVERGED state.

    ``coordinator``: the coordinator group's machine (pass the current
    leader's — its ``txns`` dict is the replicated decision ledger).
    ``participants``: lane -> that participant group's (leader) machine.
    ``initial_total``: when given, the sum of numeric values under keys
    starting with ``key_prefix`` across all participants must equal it
    (balance conservation — a lost debit or phantom credit moves it).

    Raises :class:`InvariantViolation` on:

    * a LIVE intent on any participant — an in-doubt txn nobody
      resolved (call only after the deadline sweep had time to run);
    * a LOST transfer — the coordinator decided commit but a recorded
      participant has no commit in its done-ledger;
    * a HALF-APPLIED transfer — the coordinator decided abort (or never
      decided) yet some participant committed;
    * a PHANTOM — a participant applied a commit it never prepared
      (``commit-noop`` ledger entries, machine/kv_machine.py), or holds
      a commit for a txn the coordinator has no commit decision for;
    * a balance-sum mismatch when ``initial_total`` is given.

    Returns a report dict (committed/aborted/undecided counts, the
    balance sum) for artifacts."""
    for lane, m in participants.items():
        if m.intents:
            raise InvariantViolation(
                f"participant {lane}: {len(m.intents)} live intent(s) "
                f"{sorted(m.intents)} — in-doubt txns unresolved")
        for tid, done in m.txn_done.items():
            if done == "commit-noop":
                raise InvariantViolation(
                    f"participant {lane}: txn {tid} applied a commit it "
                    f"never prepared (phantom)")

    committed = aborted = undecided = 0
    decisions = coordinator.txns
    for tid, rec in decisions.items():
        d = rec["decision"]
        if d == "commit":
            committed += 1
            for lane in rec["parts"]:
                m = participants.get(lane)
                if m is not None and m.txn_done.get(tid) != "commit":
                    raise InvariantViolation(
                        f"LOST transfer {tid}: coordinator decided "
                        f"commit but participant {lane} recorded "
                        f"{m.txn_done.get(tid)!r}")
        elif d == "abort":
            aborted += 1
            for lane in rec["parts"]:
                m = participants.get(lane)
                if m is not None and m.txn_done.get(tid) == "commit":
                    raise InvariantViolation(
                        f"HALF-APPLIED transfer {tid}: decided abort "
                        f"but participant {lane} committed")
        else:
            undecided += 1

    for lane, m in participants.items():
        for tid, done in m.txn_done.items():
            if done == "commit":
                rec = decisions.get(tid)
                if rec is None or rec["decision"] != "commit":
                    raise InvariantViolation(
                        f"PHANTOM transfer {tid}: participant {lane} "
                        f"committed but the coordinator decided "
                        f"{rec['decision'] if rec else None!r}")

    total = 0
    for m in participants.values():
        for k, v in m.data.items():
            if k.startswith(key_prefix) and isinstance(v, (int, float)):
                total += v
    if initial_total is not None and total != initial_total:
        raise InvariantViolation(
            f"balance NOT conserved: sum over {key_prefix}* keys is "
            f"{total}, expected {initial_total}")
    return {"committed": committed, "aborted": aborted,
            "undecided": undecided, "balance_total": total,
            "participants": len(participants)}


class ClusterChecker:
    """Audits a sequence of cluster snapshots (as from DeviceCluster)."""

    def __init__(self, cfg):
        self.cfg = cfg
        # (group, term) -> node id of the leader observed at that term.
        self.leaders: Dict[Tuple[int, int], int] = {}
        # (group, index) -> term committed there (first observation wins;
        # any later disagreement is a safety violation).
        self.committed_terms: Dict[Tuple[int, int], int] = {}
        self.max_commit = None   # [N, G] per-node committed frontier
        self.max_term = None     # [N, G]

    def check(self, snap: dict, crashed=None) -> None:
        """snap: dict of numpy arrays from DeviceCluster.snapshot().

        ``crashed``: optional [N] bool — nodes that crash-restarted since
        the previous check (nemesis runs).  commitIndex is VOLATILE in
        Raft (rediscovered from leaderCommit; the engine restarts it at
        the compaction floor), so a crashed node's per-node frontier may
        legally regress — its monotonicity baseline resets.  Everything
        durable (term, log, the global committed-entry ledger) stays
        strict: a crash excuses no safety property.
        """
        role, term = snap["role"], snap["term"]
        commit, last = snap["commit"], snap["last"]
        base, log_term = snap["base"], snap["log_term"]
        N, G = role.shape
        L = log_term.shape[-1]

        # Ring-capacity invariant: the live window must fit the ring, or
        # appends would alias committed slots (the bounded-window partial
        # accept rule in the kernel's AppendEntries phase enforces this).
        window = last - base
        if (window > L).any():
            n, g = np.argwhere(window > L)[0]
            raise InvariantViolation(
                f"log window exceeds ring: node {n} group {g}: "
                f"({base[n, g]}, {last[n, g]}] > {L} slots")

        # Term monotonicity per node.
        if self.max_term is not None and (term < self.max_term).any():
            n, g = np.argwhere(term < self.max_term)[0]
            raise InvariantViolation(
                f"term regressed on node {n} group {g}: "
                f"{self.max_term[n, g]} -> {term[n, g]}")
        self.max_term = term.copy() if self.max_term is None \
            else np.maximum(self.max_term, term)

        # Election safety: one leader per (group, term) ever.
        for n, g in zip(*np.nonzero(role == LEADER)):
            key = (int(g), int(term[n, g]))
            prev = self.leaders.setdefault(key, int(n))
            if prev != int(n):
                raise InvariantViolation(
                    f"two leaders for group {g} term {term[n, g]}: "
                    f"nodes {prev} and {n}")

        # Commit stability: frontier never regresses — except on a node
        # that crash-restarted, whose volatile commit restarts at its
        # compaction floor.
        if self.max_commit is not None and crashed is not None:
            self.max_commit[np.asarray(crashed, bool)] = 0
        if self.max_commit is not None and (commit < self.max_commit).any():
            n, g = np.argwhere(commit < self.max_commit)[0]
            raise InvariantViolation(
                f"commit regressed on node {n} group {g}: "
                f"{self.max_commit[n, g]} -> {commit[n, g]}")
        self.max_commit = commit.copy() if self.max_commit is None \
            else np.maximum(self.max_commit, commit)

        # Committed-entry term stability + cross-node log matching over the
        # committed live window.
        for g in range(G):
            for n in range(N):
                lo = int(max(base[n, g] + 1, 1))
                hi = int(min(commit[n, g], last[n, g]))
                for idx in range(lo, hi + 1):
                    t = int(log_term[n, g, idx % L])
                    key = (g, idx)
                    prev = self.committed_terms.setdefault(key, t)
                    if prev != t:
                        raise InvariantViolation(
                            f"committed entry changed: group {g} index "
                            f"{idx}: term {prev} vs {t} (node {n})")

    def check_log_matching(self, snap: dict) -> None:
        """Pairwise log-matching audit (quadratic; call sparsely)."""
        last, base, log_term = snap["last"], snap["base"], snap["log_term"]
        N, G = last.shape
        L = log_term.shape[-1]
        for g in range(G):
            for a in range(N):
                for b in range(a + 1, N):
                    lo = int(max(base[a, g], base[b, g]) + 1)
                    hi = int(min(last[a, g], last[b, g]))
                    match_at = None
                    for idx in range(hi, lo - 1, -1):
                        if log_term[a, g, idx % L] == log_term[b, g, idx % L]:
                            match_at = idx
                            break
                    if match_at is None:
                        continue
                    for idx in range(lo, match_at):
                        ta = int(log_term[a, g, idx % L])
                        tb = int(log_term[b, g, idx % L])
                        if ta != tb:
                            raise InvariantViolation(
                                f"log matching violated: group {g} nodes "
                                f"{a}/{b} share ({match_at}) but differ at "
                                f"{idx}: {ta} vs {tb}")
