"""Storage-fault nemesis: seeded host-I/O fault plans + injection glue.

The device nemesis (testkit/nemesis.py) compiles network/crash scenarios
into dense per-tick schedules; this module is its *storage* twin for the
host durability tier.  The injection *plane* already lives inside the
engines — the per-engine fault tables in ``log/wal.py`` (Python tier)
and ``log/native/wal.cpp`` (native tier, exported as ``wal_fault_set``/
``wal_fault_clear``) plus the process-wide cold-path hook in
``utils/iofault.py`` — so this module is pure *policy*:

* :func:`plan_storage_faults` — a deterministic, seeded per-tick plan of
  engine-level faults (failed fsync, ENOSPC, torn/short write, slow
  I/O), a pure function of ``(shape, seed)`` exactly like the nemesis
  generators: the same seed replays the same storage scenario on either
  WAL tier.
* :class:`FaultInjector` — walks a plan alongside the node's tick loop,
  arming each event on the LogStore's fault table the tick before it is
  scheduled to fire.
* :class:`ColdFaults` — an installable ``utils.iofault`` hook for the
  cold paths (ConfMeta flush, snapshot-archive write/fsync) with
  one-shot arming and restore-on-exit.
* :func:`flip_bits` — deterministic at-rest corruption (the "cosmic
  ray"/firmware-lie stand-in) for checksum/scrub tests.

Faults are armed through public surfaces only (``LogStore.set_fault``,
``iofault.install``); nothing here monkeypatches os/file internals, so
the same plans drive the native engine byte-for-byte.
"""

from __future__ import annotations

import errno
import time
from collections import defaultdict
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import iofault

__all__ = [
    "FaultEvent", "plan_storage_faults", "FaultInjector", "ColdFaults",
    "flip_bits",
]

# Engine-level ops (log/wal.py _FAULT_OPS): value semantics per op are
#   fsync/write -> errno (0 = EIO), short -> bytes kept, delay -> usec.
ENGINE_OPS = ("fsync", "write", "short", "delay")


@dataclass(frozen=True)
class FaultEvent:
    """One armed fault: at ``tick``, arm ``op`` on WAL stripe ``shard``
    so that the ``(after + 1)``-th matching engine call fires with
    ``value`` (errno / bytes-kept / microseconds, per op)."""
    tick: int
    op: str
    shard: int = 0
    after: int = 0
    value: int = 0


def plan_storage_faults(n_ticks: int, n_shards: int = 1, *, seed: int = 0,
                        fsync_p: float = 0.0, enospc_p: float = 0.0,
                        short_p: float = 0.0, delay_p: float = 0.0,
                        delay_us: int = 2000,
                        max_events: Optional[int] = None
                        ) -> Tuple[FaultEvent, ...]:
    """Compile a seeded storage-fault scenario into a flat event plan.

    Each ``(tick, shard)`` cell independently draws at most ONE fault,
    tested in severity order (fail-stop fsync, then ENOSPC, then torn
    write, then slow I/O) — mirroring how the nemesis generators draw
    per-cell faults.  Pure function of the arguments: the same seed
    yields the identical plan, and the engines' fault tables are
    deterministic, so a failing storage scenario replays exactly.

    ``max_events`` caps the plan (earliest events win) so acceptance
    runs can bound how much of the cluster they poison.
    """
    rng = np.random.default_rng(seed)
    events: List[FaultEvent] = []
    for t in range(n_ticks):
        for s in range(n_shards):
            r = rng.random(4)
            keep = int(rng.integers(0, 48))  # drawn always: keeps the
            # stream position independent of which branch fires below.
            if r[0] < fsync_p:
                events.append(FaultEvent(t, "fsync", s, 0, errno.EIO))
            elif r[1] < enospc_p:
                events.append(FaultEvent(t, "write", s, 0, errno.ENOSPC))
            elif r[2] < short_p:
                events.append(FaultEvent(t, "short", s, 0, keep))
            elif r[3] < delay_p:
                events.append(FaultEvent(t, "delay", s, 0, int(delay_us)))
    if max_events is not None:
        events = events[:max_events]
    return tuple(events)


class FaultInjector:
    """Arm a plan's events on a LogStore in step with the tick loop.

    Call :meth:`advance` with the node's tick number BEFORE driving that
    tick; every event scheduled for it is armed on the store's fault
    table (``LogStore.set_fault``) and will fire from inside the engine
    when the host phase touches the faulted stripe.  Events for poisoned
    stripes still arm harmlessly — a fail-stop engine refuses all
    further I/O regardless.
    """

    def __init__(self, store, plan: Sequence[FaultEvent]):
        self.store = store
        self._by_tick: Dict[int, List[FaultEvent]] = defaultdict(list)
        for ev in plan:
            self._by_tick[ev.tick].append(ev)
        self.armed_total = 0

    def advance(self, tick: int) -> List[FaultEvent]:
        """Arm all events scheduled for ``tick``; returns them."""
        evs = self._by_tick.pop(tick, [])
        for ev in evs:
            self.store.set_fault(ev.op, after=ev.after, value=ev.value,
                                 shard=ev.shard)
            self.armed_total += 1
        return evs

    @property
    def pending(self) -> int:
        return sum(len(v) for v in self._by_tick.values())


class ColdFaults:
    """One-shot fault hook for the cold storage paths (``utils.iofault``
    ops: ``"conf.flush"``, ``"archive.write"``, ``"archive.fsync"``).

    Use as a context manager; arms are one-shot (consumed when they
    fire) and the previously installed hook — normally none — is
    restored on exit::

        with ColdFaults() as cf:
            cf.arm("archive.fsync", err=errno.EIO)
            ...  # next archive seal fails once
    """

    def __init__(self):
        # op -> [remaining-skips, thrower-or-delay]
        self._armed: Dict[str, list] = {}
        self._prev = None
        self.fired: List[Tuple[str, str]] = []

    def arm(self, op: str, *, err: Optional[int] = None,
            torn_keep: Optional[int] = None, delay_s: float = 0.0,
            after: int = 0) -> "ColdFaults":
        self._armed[op] = [after, (err, torn_keep, delay_s)]
        return self

    def __call__(self, op: str, path: str) -> None:
        ent = self._armed.get(op)
        if ent is None:
            return
        if ent[0] > 0:
            ent[0] -= 1
            return
        err, torn_keep, delay_s = ent[1]
        del self._armed[op]  # one-shot
        self.fired.append((op, path))
        if delay_s > 0:
            time.sleep(delay_s)
            return
        if torn_keep is not None:
            raise iofault.TornWrite(keep=torn_keep)
        e = errno.EIO if err is None else err
        raise OSError(e, f"injected {op} fault")

    def __enter__(self) -> "ColdFaults":
        self._prev = iofault.install(self)
        return self

    def __exit__(self, *exc) -> None:
        if self._prev is not None:
            iofault.install(self._prev)
        else:
            iofault.uninstall()


def flip_bits(path: str, seed: int = 0, n_flips: int = 1,
              skip: int = 0) -> List[Tuple[int, int]]:
    """Deterministically flip ``n_flips`` bits of the file at ``path``
    (offsets drawn past byte ``skip``), modeling silent at-rest
    corruption the CRC-32C sidecars must catch.  Returns the flipped
    ``(offset, bit)`` pairs so a test can assert the corruption landed.
    """
    with open(path, "r+b") as f:
        data = bytearray(f.read())
        if len(data) <= skip:
            raise ValueError(f"{path}: nothing to corrupt past {skip}")
        rng = np.random.default_rng(seed)
        out = []
        for _ in range(n_flips):
            off = int(rng.integers(skip, len(data)))
            bit = int(rng.integers(0, 8))
            data[off] ^= (1 << bit)
            out.append((off, bit))
        f.seek(0)
        f.write(bytes(data))
        f.truncate()
    return out
