"""Nemesis schedule generators + audited device chaos runs.

The fault-injection *plane* lives in the engine (core/types.py
``FaultSchedule``, core/sim.py ``run_cluster_ticks_nemesis``); this module
is the *policy* tier: seeded generators that compile whole Jepsen-style
scenarios — split brain, rolling partitions, crash-restart storms, lossy/
duplicating links, clock stalls — into the dense per-tick schedule arrays,
plus the audit harness that runs a schedule on device in fused windows and
checks every Raft safety invariant between windows (testkit/invariants.py
``ClusterChecker``).

Everything is a pure function of ``(shape, seed)``: the same seed produces
the same schedule, and the engine run itself is bit-deterministic (integer
lanes + counter-mode PRNG only), so a failing chaos run replays exactly —
``assert_nemesis_deterministic`` pins that property.  This is the
vectorized, reproducible analog of the reference's manual chaos procedure
(kill TCP links / kill -9 a JVM / restart, README.md:28-33).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..core.types import EngineConfig, FaultSchedule

__all__ = [
    "healthy", "split_brain", "rolling_partition", "crash_storm",
    "clock_stalls", "lossy_links", "compose", "concat", "chaos_mix",
    "run_nemesis_audited", "assert_nemesis_deterministic",
]


def _as_schedule(link_up, crash, stall, dup) -> FaultSchedule:
    import jax.numpy as jnp
    return FaultSchedule(
        link_up=jnp.asarray(link_up, jnp.bool_),
        crash=jnp.asarray(crash, jnp.bool_),
        stall=jnp.asarray(stall, jnp.bool_),
        dup=jnp.asarray(dup, jnp.bool_),
    )


def _blank(n_peers: int, n_ticks: int):
    """Host-side (numpy) all-healthy arrays for generators to mutate."""
    return (np.ones((n_ticks, n_peers, n_peers), bool),
            np.zeros((n_ticks, n_peers), bool),
            np.zeros((n_ticks, n_peers), bool),
            np.zeros((n_ticks, n_peers, n_peers), bool))


def healthy(n_peers: int, n_ticks: int) -> FaultSchedule:
    """All links up, nothing crashes (delegates to the engine's own
    FaultSchedule.healthy so the two can never drift)."""
    return FaultSchedule.healthy(n_peers, n_ticks)


def split_brain(n_peers: int, n_ticks: int, *, start: int = 0,
                stop: Optional[int] = None,
                sides: Optional[Sequence[Sequence[int]]] = None,
                seed: int = 0) -> FaultSchedule:
    """Partition the cluster into ``sides`` for ticks [start, stop).

    Default sides: a random near-half split drawn from ``seed`` (a
    majority side must exist for progress; a seeded permutation keeps the
    scenario reproducible).  Nodes can only reach their own side — the
    classic split-brain window, healed for the remaining ticks.
    """
    link_up, crash, stall, dup = _blank(n_peers, n_ticks)
    stop = n_ticks if stop is None else stop
    if sides is None:
        perm = np.random.default_rng(seed).permutation(n_peers)
        k = n_peers // 2
        sides = [perm[:k].tolist(), perm[k:].tolist()]
    conn = np.zeros((n_peers, n_peers), bool)
    for side in sides:
        for a in side:
            for b in side:
                conn[a, b] = True
    link_up[start:stop] = conn
    return _as_schedule(link_up, crash, stall, dup)


def rolling_partition(n_peers: int, n_ticks: int, *, period: int = 20,
                      heal_gap: int = 5) -> FaultSchedule:
    """Isolate each node in turn: node (w % N) is cut off for the first
    ``period - heal_gap`` ticks of window w, then the cluster heals for
    ``heal_gap`` ticks before the next victim — randomized-leader-churn
    pressure without ever losing a quorum (BASELINE config-4's
    "randomized leader churn" regime)."""
    link_up, crash, stall, dup = _blank(n_peers, n_ticks)
    for t in range(n_ticks):
        w, off = divmod(t, period)
        if off < period - heal_gap:
            victim = w % n_peers
            link_up[t, victim, :] = False
            link_up[t, :, victim] = False
            link_up[t, victim, victim] = True
    return _as_schedule(link_up, crash, stall, dup)


def crash_storm(n_peers: int, n_ticks: int, *, rate: float = 0.02,
                seed: int = 0, max_down: Optional[int] = None
                ) -> FaultSchedule:
    """Random crash-restarts: each (tick, node) crashes with probability
    ``rate``.  ``max_down`` caps simultaneous crashes per tick (default:
    keep a majority standing, so liveness assertions stay meaningful —
    safety must of course hold under ANY schedule)."""
    link_up, crash, stall, dup = _blank(n_peers, n_ticks)
    rng = np.random.default_rng(seed)
    cap = (n_peers - (n_peers // 2 + 1)) if max_down is None else max_down
    hits = rng.random((n_ticks, n_peers)) < rate
    for t in range(n_ticks):
        idx = np.nonzero(hits[t])[0]
        if cap >= 0 and len(idx) > cap:
            idx = rng.permutation(idx)[:cap]
        crash[t, idx] = True
    return _as_schedule(link_up, crash, stall, dup)


def clock_stalls(n_peers: int, n_ticks: int, *, rate: float = 0.01,
                 max_len: int = 8, seed: int = 0) -> FaultSchedule:
    """GC-pause regime: nodes freeze for random windows of 1..max_len
    ticks (clock, timers, sends and receives all stop — per-node clocks
    drift apart, by design)."""
    link_up, crash, stall, dup = _blank(n_peers, n_ticks)
    rng = np.random.default_rng(seed)
    for n in range(n_peers):
        t = 0
        while t < n_ticks:
            if rng.random() < rate:
                ln = int(rng.integers(1, max_len + 1))
                stall[t:t + ln, n] = True
                t += ln
            else:
                t += 1
    return _as_schedule(link_up, crash, stall, dup)


def lossy_links(n_peers: int, n_ticks: int, *, drop_p: float = 0.1,
                dup_p: float = 0.0, seed: int = 0) -> FaultSchedule:
    """Flaky network: every directed link independently drops each tick
    with ``drop_p`` (asymmetric by construction) and duplicates delivered
    traffic with ``dup_p``.  Self-links never drop."""
    link_up, crash, stall, dup = _blank(n_peers, n_ticks)
    rng = np.random.default_rng(seed)
    link_up &= rng.random(link_up.shape) >= drop_p
    if dup_p > 0:
        dup |= rng.random(dup.shape) < dup_p
    eye = np.eye(n_peers, dtype=bool)
    link_up |= eye[None]
    return _as_schedule(link_up, crash, stall, dup)


def compose(*scheds: FaultSchedule) -> FaultSchedule:
    """Overlay schedules of equal length: a link is up iff up in ALL
    (partitions stack with loss), a node crashes/stalls/dups if ANY says
    so."""
    assert scheds, "compose() needs at least one schedule"
    T = scheds[0].n_ticks
    assert all(s.n_ticks == T for s in scheds), "tick counts differ"
    out = scheds[0]
    for s in scheds[1:]:
        out = FaultSchedule(
            link_up=out.link_up & s.link_up,
            crash=out.crash | s.crash,
            stall=out.stall | s.stall,
            dup=out.dup | s.dup,
        )
    return out


def concat(*scheds: FaultSchedule) -> FaultSchedule:
    """Concatenate schedules along the tick axis (phased scenarios)."""
    import jax
    import jax.numpy as jnp

    assert scheds, "concat() needs at least one schedule"
    return jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=0), *scheds)


def chaos_mix(n_peers: int, n_ticks: int, *, seed: int = 0) -> FaultSchedule:
    """The standard three-regime acceptance scenario (ISSUE 1), phased
    over the run:

    1. first third  — a split-brain window plus rolling partitions;
    2. middle third — crash-restart storm plus clock stalls;
    3. last third   — lossy links with duplication.

    The division remainder (0-2 ticks, when 3 does not divide
    ``n_ticks``) is padded healthy so the schedule length is exactly
    ``n_ticks`` — that is NOT enough settle time for liveness.  Callers
    asserting liveness (one leader, commits advance) after the run must
    append real healthy time: ``run_nemesis_audited(...,
    settle_ticks=...)`` or ``concat(sched, healthy(...))``; the election
    lottery's slow tail needs more settle the more groups there are
    (~240 ticks at 4k groups).
    """
    t3 = n_ticks // 3
    tail = max(n_ticks - 3 * t3, 0)
    p1 = compose(
        split_brain(n_peers, t3, start=t3 // 4, stop=3 * t3 // 4, seed=seed),
        rolling_partition(n_peers, t3, period=max(8, t3 // 4), heal_gap=4),
    )
    p2 = compose(
        crash_storm(n_peers, t3, rate=0.03, seed=seed + 1),
        clock_stalls(n_peers, t3, rate=0.02, max_len=5, seed=seed + 2),
    )
    p3 = lossy_links(n_peers, t3, drop_p=0.15, dup_p=0.1, seed=seed + 3)
    parts = [p1, p2, p3]
    if tail:
        parts.append(healthy(n_peers, tail))
    return concat(*parts)


# --------------------------------------------------------------- audit ----

def _slice_schedule(sched: FaultSchedule, lo: int, hi: int) -> FaultSchedule:
    import jax
    return jax.tree.map(lambda a: a[lo:hi], sched)


def run_nemesis_audited(cfg: EngineConfig, sched: FaultSchedule, *,
                        seed: int = 0, submit: int = 2,
                        audit_every: int = 32, settle_ticks: int = 0,
                        checker=None):
    """Run a fault schedule on device, auditing safety between windows.

    The schedule executes as fused ``run_cluster_ticks_nemesis`` scans of
    ``audit_every`` ticks (no per-tick host loop — the host only touches
    the run at window boundaries to pull a snapshot for the
    ``ClusterChecker``).  ``settle_ticks`` appends an all-healthy tail so
    callers can assert liveness (single leader, commits) after the chaos.

    Returns ``(states, checker, snapshot)`` — the final stacked state, the
    (accumulating) checker, and the final host snapshot dict.
    """
    import jax.numpy as jnp

    from ..core.cluster import DeviceCluster
    from ..core.sim import run_cluster_ticks_nemesis
    from .invariants import ClusterChecker, cluster_snapshot

    if settle_ticks:
        sched = concat(sched, healthy(cfg.n_peers, settle_ticks))
    c = DeviceCluster(cfg, seed=seed)
    chk = checker if checker is not None else ClusterChecker(cfg)
    states, inflight, info = c.states, c.inflight, c.last_info
    sub = jnp.full((cfg.n_peers, cfg.n_groups), submit, jnp.int32)
    T = sched.n_ticks
    snap = cluster_snapshot(states)
    chk.check(snap)
    done = 0
    import numpy as _np
    crash_np = _np.asarray(sched.crash)
    while done < T:
        step = min(audit_every, T - done)
        states, inflight, info = run_cluster_ticks_nemesis(
            cfg, states, inflight, info,
            _slice_schedule(sched, done, done + step), sub)
        crashed = crash_np[done:done + step].any(axis=0)
        done += step
        snap = cluster_snapshot(states)
        chk.check(snap, crashed=crashed)
    chk.check_log_matching(snap)
    return states, chk, snap


def assert_nemesis_deterministic(cfg: EngineConfig, sched: FaultSchedule, *,
                                 seed: int = 0, submit: int = 2) -> None:
    """Same seed + same schedule ⇒ bit-identical final state.

    Runs the WHOLE schedule as one fused scan, twice, from two
    independently built clusters, and requires every leaf of the final
    RaftState (including PRNG keys and per-node clocks) to match exactly.
    This is the replayability guarantee chaos debugging rests on: a
    violating run can be re-executed under instrumentation and will take
    the identical path.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..core.cluster import DeviceCluster
    from ..core.sim import run_cluster_ticks_nemesis

    sub = jnp.full((cfg.n_peers, cfg.n_groups), submit, jnp.int32)

    def one_run():
        c = DeviceCluster(cfg, seed=seed)
        states, _, _ = run_cluster_ticks_nemesis(
            cfg, c.states, c.inflight, c.last_info, sched, sub)
        return states

    a, b = one_run(), one_run()
    flat_a, _ = jax.tree_util.tree_flatten_with_path(a)
    flat_b = jax.tree.leaves(b)
    for (path, la), lb in zip(flat_a, flat_b):
        np.testing.assert_array_equal(
            np.asarray(la), np.asarray(lb),
            err_msg=f"nemesis run not deterministic at {jax.tree_util.keystr(path)}")
