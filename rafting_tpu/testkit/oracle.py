"""Scalar oracle: a loop-based re-derivation of one Multi-Raft node tick.

This implements the SAME protocol semantics as
:func:`rafting_tpu.core.step.node_step`, but as explicit per-group /
per-peer Python loops following the reference implementation's scalar logic
(curioloop/rafting: context/member/Follower.java, Candidate.java,
Leader.java, Leadership.java, context/RaftRoutine.java) and the Raft paper
rules.  It is deliberately written WITHOUT vector tricks so that it can
serve as an independent check of the kernel's vectorization: the parity
test drives both with identical inputs and compares every state lane and
every outbound message bit-for-bit.

The only shared computation is the PRNG draw for randomized election
timeouts: the oracle consumes the same `jax.random` stream so that timer
outcomes are comparable (the reference re-randomizes the election window on
every read, support/RaftConfig.java:187-190; which lanes *consume* the draw
is part of the checked semantics).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict

import jax
import numpy as np

from ..core.types import (
    CANDIDATE, FOLLOWER, LEADER, NIL, PRE_CANDIDATE,
    TR_BECAME_CANDIDATE, TR_BECAME_LEADER, TR_BECAME_PRE_CANDIDATE,
    TR_COMMIT_ADVANCE, TR_CONF_CHANGE_COMMIT, TR_CONF_CHANGE_ENTER,
    TR_LEADER_TRANSFER, TR_READ_RELEASE, TR_SNAPSHOT_INSTALL,
    TR_STEPPED_DOWN, TR_TERM_BUMP,
    EngineConfig, HostInbox, Messages, RaftState,
    conf_learners_of, conf_new_of, conf_pack, conf_voters_of,
)


def _popcount(x: int) -> int:
    return bin(x & 0xFFFFFFFF).count("1")


def _dual_quorum(flags, voters: int, voters_new: int) -> bool:
    """Scalar mirror of core.step.dual_quorum: ``flags`` is a per-peer
    boolean sequence; a joint config needs a majority in BOTH sets."""
    cv = sum(1 for p, f in enumerate(flags) if f and (voters >> p) & 1)
    ok = cv >= _popcount(voters) // 2 + 1
    if voters_new:
        cn = sum(1 for p, f in enumerate(flags)
                 if f and (voters_new >> p) & 1)
        ok = ok and cn >= _popcount(voters_new) // 2 + 1
    return ok


def _np(tree) -> Dict[str, np.ndarray]:
    """Flatten a flax struct dataclass into {field: numpy array}.

    None subfields (e.g. ``trace`` with the flight recorder disabled) are
    empty subtrees — skipped, exactly as jax's flatten drops them."""
    out = {}
    for name in tree.__dataclass_fields__:
        v = getattr(tree, name)
        if v is None:
            continue
        if hasattr(v, "__dataclass_fields__"):
            for sub, arr in _np(v).items():
                out[f"{name}.{sub}"] = arr
        else:
            out[name] = np.asarray(v)
    return out


@dataclass
class _Log:
    """Scalar view of one group's log ring."""
    ring: np.ndarray  # [L] terms
    cring: np.ndarray  # [L] packed config words (0 = not a config entry)
    base: int
    base_term: int
    base_conf: int
    last: int

    def term_at(self, idx: int) -> int:
        # Mirrors ring_term_at: <= base -> milestone term; > last -> -1.
        if idx <= self.base:
            return int(self.base_term)
        if idx <= self.last:
            return int(self.ring[idx % len(self.ring)])
        return -1

    def conf_at(self, idx: int) -> int:
        # Mirrors ring_conf_batch: the entry's packed config word inside
        # the live window, else 0.
        if self.base < idx <= self.last:
            return int(self.cring[idx % len(self.ring)])
        return 0

    def latest_conf(self, upto: int):
        """(conf_idx, conf_word) of the latest config entry in
        (base, min(upto, last)], else (0, base_conf) — the scalar mirror
        of core.step.latest_conf."""
        L = len(self.ring)
        lo = max(self.base + 1, self.last - L + 1, 1)
        for idx in range(min(upto, self.last), lo - 1, -1):
            w = int(self.cring[idx % L])
            if w != 0:
                return idx, w
        return 0, int(self.base_conf)


def oracle_step(cfg: EngineConfig, state: RaftState, inbox: Messages,
                host: HostInbox):
    """Advance one node by one tick, scalar semantics.

    Returns (state_dict, outbox_dict, info_dict) of numpy arrays with the
    same keys/shapes as the kernel's pytrees (nested fields dotted,
    e.g. ``log.term``).
    """
    G, P, B, L, S = (cfg.n_groups, cfg.n_peers, cfg.batch, cfg.log_slots,
                     cfg.max_submit)
    maj = cfg.majority
    s = _np(state)
    ib = _np(inbox)
    h = _np(host)

    me = int(s["node_id"])
    now = int(s["now"]) + 1

    # Same PRNG stream as the kernel (shared on purpose; see module doc).
    rng, k_to = jax.random.split(state.rng)
    rand_to = np.asarray(jax.random.randint(
        k_to, (G,), cfg.election_ticks, 2 * cfg.election_ticks,
        dtype=np.int32))

    active = s["active"].copy()
    term = s["term"].astype(np.int64).copy()
    role = s["role"].copy()
    voted = s["voted_for"].copy()
    leader_id = s["leader_id"].copy()
    commit = s["commit"].copy()
    ring = s["log.term"].copy()
    cring = s["log.conf"].copy()
    base = s["log.base"].copy()
    base_term = s["log.base_term"].copy()
    base_conf = s["log.base_conf"].copy()
    last = s["log.last"].copy()
    conf_idx_st = s["conf_idx"].copy()
    conf_word_st = s["conf_word"].copy()
    xfer_to = s["xfer_to"].copy()
    xfer_dl = s["xfer_dl"].copy()
    next_idx = s["next_idx"].copy()
    own_from_a = s["own_from"].astype(np.int64).copy()
    match_idx = s["match_idx"].copy()
    send_next = s["send_next"].copy()
    inflight = s["inflight"].copy()
    hb_inflight = s["hb_inflight"].copy()
    sent_at = s["sent_at"].copy()
    need_snap = s["need_snap"].copy()
    ok_at = s["ok_at"].copy()
    fail_at = s["fail_at"].copy()
    fail_streak = s["fail_streak"].copy()
    votes = s["votes"].copy()
    prevotes = s["prevotes"].copy()
    elect_dl = s["elect_deadline"].copy()
    hb_due = s["hb_due"].copy()
    read_evid = s["read_evid"].copy()
    rq_idx = s["rq_idx"].copy()
    rq_stamp = s["rq_stamp"].copy()
    rq_n = s["rq_n"].copy()
    rq_head = s["rq_head"].copy()
    rq_len = s["rq_len"].copy()
    K = cfg.read_slots

    # Flight recorder (cfg.trace_depth): the scalar mirror of the kernel's
    # ring writes — same canonical event order, same ring semantics.
    has_trace = state.trace is not None
    if has_trace:
        tr_tick = s["trace.tick"].copy()
        tr_kind = s["trace.kind"].copy()
        tr_term = s["trace.term"].copy()
        tr_aux = s["trace.aux"].copy()
        tr_n = s["trace.n"].copy()
        D = tr_tick.shape[1]

    # Heat lanes (cfg.heat): the scalar mirror of the kernel's cumulative
    # per-group activity counters (appended / sent / commits / reads).
    has_heat = state.heat is not None
    if has_heat:
        ht_app = s["heat.appended"].copy()
        ht_sent = s["heat.sent"].copy()
        ht_com = s["heat.commits"].copy()
        ht_rd = s["heat.reads"].copy()

    # Quorum-contact lanes (cfg.check_quorum): the scalar mirror of the
    # kernel's CheckQuorum phase 6c.
    has_qc = state.qc is not None
    if has_qc:
        qc_heard = s["qc.heard"].copy()
        qc_since = s["qc.since"].copy()

    old_term = term.copy()
    old_voted = voted.copy()
    old_last = last.copy()
    old_commit = commit.copy()
    old_role = role.copy()

    # Outbox accumulators, [P, G] dense like the kernel's.
    def zi(*shape):
        return np.zeros(shape, np.int32)

    def zb(*shape):
        return np.zeros(shape, bool)

    out = {
        "ae_valid": zb(P, G), "ae_term": zi(P, G), "ae_prev_idx": zi(P, G),
        "ae_prev_term": zi(P, G), "ae_commit": zi(P, G), "ae_n": zi(P, G),
        "ae_ents": zi(P, G, B), "ae_cents": zi(P, G, B),
        "ae_occ": zb(P, G), "ae_tick": zi(P, G),
        "aer_valid": zb(P, G), "aer_term": zi(P, G),
        "aer_success": zb(P, G), "aer_match": zi(P, G),
        "aer_empty": zb(P, G), "aer_occ": zb(P, G), "aer_tick": zi(P, G),
        "rv_valid": zb(P, G), "rv_term": zi(P, G), "rv_last_idx": zi(P, G),
        "rv_last_term": zi(P, G), "rv_prevote": zb(P, G),
        "rvr_valid": zb(P, G), "rvr_term": zi(P, G), "rvr_granted": zb(P, G),
        "rvr_prevote": zb(P, G), "rvr_echo": zi(P, G),
        "is_valid": zb(P, G), "is_term": zi(P, G), "is_idx": zi(P, G),
        "is_last_term": zi(P, G), "is_probe": zb(P, G), "is_conf": zi(P, G),
        "isr_valid": zb(P, G), "isr_term": zi(P, G), "isr_success": zb(P, G),
        "isr_probe": zb(P, G),
        "tn_valid": zb(P, G), "tn_term": zi(P, G),
    }
    info = {
        "submit_start": zi(G), "submit_acc": zi(G), "dirty": zb(G),
        "appended_from": zi(G), "appended_to": zi(G), "log_tail": zi(G),
        "commit": zi(G), "leader": np.full(G, NIL, np.int32),
        "ready": zb(G),
        "snap_req": zb(G), "snap_req_from": zi(G), "snap_req_idx": zi(G),
        "snap_req_term": zi(G), "snap_req_conf": zi(G),
        "noop_idx": zi(G), "noop_term": zi(G),
        "read_acc": zi(G), "read_index": zi(G),
        "read_rel": zi(G), "read_served": zi(G),
        "read_lease": zb(G), "read_abort": zb(G),
        "conf_app_idx": zi(G), "conf_app_term": zi(G),
        "conf_app_word": zi(G),
        "conf_word": zi(G), "conf_idx": zi(G), "conf_pending": zb(G),
        "xfer_fired": zb(G), "xfer_abort": zb(G),
    }
    if has_qc:
        info["cq_stepdown"] = zb(G)
        info["cq_veto"] = zi(G)

    for g in range(G):
        log = _Log(ring[g], cring[g], int(base[g]), int(base_term[g]),
                   int(base_conf[g]), int(last[g]))
        app_from, app_to = 0, 0

        # ---- 0. membership view C0 (tick-start) ---------------------------
        # (kernel phase 0: the state's conf_idx/conf_word cache — always
        # equal to the latest config entry in the log, §6 apply-on-append;
        # tallies count against it.)
        cidx0, w0 = int(conf_idx_st[g]), int(conf_word_st[g])
        voters0, vnew0 = conf_voters_of(w0), conf_new_of(w0)

        # ---- 1. term sync: adopt the highest real inbound term ------------
        # (Raft "if RPC term > currentTerm, become follower"; reference
        # Follower.java:45-47, Leader.java:224-227.  PreVote request terms
        # are speculative and excluded.)
        mt = -1
        for p in range(P):
            if ib["ae_valid"][p, g]:
                mt = max(mt, int(ib["ae_term"][p, g]))
            if ib["aer_valid"][p, g]:
                mt = max(mt, int(ib["aer_term"][p, g]))
            if ib["rv_valid"][p, g] and not ib["rv_prevote"][p, g]:
                mt = max(mt, int(ib["rv_term"][p, g]))
            if ib["rvr_valid"][p, g]:
                mt = max(mt, int(ib["rvr_term"][p, g]))
            if ib["is_valid"][p, g]:
                mt = max(mt, int(ib["is_term"][p, g]))
            if ib["isr_valid"][p, g]:
                mt = max(mt, int(ib["isr_term"][p, g]))
            if ib["tn_valid"][p, g]:
                mt = max(mt, int(ib["tn_term"][p, g]))
        if active[g] and mt > term[g]:
            term[g] = mt
            role[g] = FOLLOWER
            voted[g] = NIL
            leader_id[g] = NIL
            elect_dl[g] = now + rand_to[g]

        last_term_v = log.term_at(log.last)

        # ---- 2. vote requests ---------------------------------------------
        # (reference Follower.requestVote:108-127 / preVote:91-105.)
        def up_to_date(p):
            lt, li = int(ib["rv_last_term"][p, g]), int(ib["rv_last_idx"][p, g])
            return lt > last_term_v or (lt == last_term_v and li >= log.last)

        rv_v = [bool(ib["rv_valid"][p, g]) and active[g] and p != me
                for p in range(P)]
        elig = [rv_v[p] and not ib["rv_prevote"][p, g]
                and int(ib["rv_term"][p, g]) == term[g] and up_to_date(p)
                and (voted[g] == NIL or voted[g] == p)
                for p in range(P)]
        first_elig = next((p for p in range(P) if elig[p]), 0)
        grant_rv = [elig[p] and (voted[g] == p or p == first_elig)
                    for p in range(P)]
        if any(grant_rv) and voted[g] == NIL:
            voted[g] = first_elig
        if any(grant_rv):
            elect_dl[g] = now + rand_to[g]
        lease_open = now >= elect_dl[g] or leader_id[g] == NIL
        for p in range(P):
            if rv_v[p]:
                pv = bool(ib["rv_prevote"][p, g])
                if pv:
                    granted = (int(ib["rv_term"][p, g]) > term[g]
                               and up_to_date(p) and lease_open)
                else:
                    granted = grant_rv[p]
                out["rvr_valid"][p, g] = True
                out["rvr_granted"][p, g] = granted
                out["rvr_prevote"][p, g] = pv
                out["rvr_echo"][p, g] = ib["rv_term"][p, g]
                out["rvr_term"][p, g] = term[g]

        # ---- 3. vote responses + tallies ----------------------------------
        # (reference Candidate.startElection:112-134, prepareElection
        # tally Follower.java:241-275.)
        for p in range(P):
            if not (ib["rvr_valid"][p, g] and active[g]):
                continue
            if (ib["rvr_prevote"][p, g] and ib["rvr_granted"][p, g]
                    and role[g] == PRE_CANDIDATE
                    and int(ib["rvr_echo"][p, g]) == term[g] + 1):
                prevotes[g, p] = True
            if (not ib["rvr_prevote"][p, g] and ib["rvr_granted"][p, g]
                    and role[g] == CANDIDATE
                    and int(ib["rvr_term"][p, g]) == term[g]):
                votes[g, p] = True
        become_cand_pv = (role[g] == PRE_CANDIDATE
                          and _dual_quorum(prevotes[g], voters0, vnew0))
        if become_cand_pv:
            term[g] += 1
            role[g] = CANDIDATE
            voted[g] = me
            leader_id[g] = NIL
            votes[g] = False
            votes[g, me] = True
            elect_dl[g] = now + rand_to[g]
        vote_win = (role[g] == CANDIDATE
                    and _dual_quorum(votes[g], voters0, vnew0))
        if vote_win:
            role[g] = LEADER
            leader_id[g] = me
            next_idx[g] = log.last + 1
            match_idx[g] = 0
            send_next[g] = log.last + 1
            inflight[g] = 0
            hb_inflight[g] = 0
            need_snap[g] = False
            ok_at[g] = 0
            fail_at[g] = 0
            fail_streak[g] = 0
            hb_due[g] = now
            own_from_a[g] = log.last + 1
            # Raft §8 no-op on election win (mirrors kernel phase 3):
            # appended AFTER the replication matrix reset, so
            # next/send point exactly at the no-op.
            if log.last - log.base < L:
                info["noop_idx"][g] = log.last + 1
                info["noop_term"][g] = term[g]
                log.ring[(log.last + 1) % L] = term[g]
                log.cring[(log.last + 1) % L] = 0
                log.last += 1

        # ---- 4. AppendEntries requests ------------------------------------
        # (reference Follower.appendEntries:35-88.)
        ae_ok = [bool(ib["ae_valid"][p, g]) and active[g] and p != me
                 and int(ib["ae_term"][p, g]) == term[g] for p in range(P)]
        ae_peer = next((p for p in range(P) if ae_ok[p]), 0)
        ae_any = any(ae_ok) and role[g] != LEADER
        acc = False
        tail = 0
        if ae_any:
            role[g] = FOLLOWER
            leader_id[g] = ae_peer
            elect_dl[g] = now + rand_to[g]
            prev_i = int(ib["ae_prev_idx"][ae_peer, g])
            prev_t = int(ib["ae_prev_term"][ae_peer, g])
            n_e = int(ib["ae_n"][ae_peer, g])
            # Bounded-window partial accept (see kernel phase 4): never let
            # the live window (base, last] exceed the ring capacity.
            n_e = max(0, min(n_e, log.base + L - prev_i))
            lc = int(ib["ae_commit"][ae_peer, g])
            ents = ib["ae_ents"][ae_peer, g]
            centsv = ib["ae_cents"][ae_peer, g]
            acc = (prev_i <= log.base
                   or (prev_i <= log.last and log.term_at(prev_i) == prev_t))
            if acc:
                tail = prev_i + n_e
                conflict = False
                for k in range(n_e):
                    idx = prev_i + 1 + k
                    if log.base < idx <= log.last \
                            and log.term_at(idx) != int(ents[k]):
                        conflict = True
                        break
                for k in range(n_e):
                    idx = prev_i + 1 + k
                    if idx > log.base:
                        log.ring[idx % L] = ents[k]
                        # Config adoption rides the entry write (§6
                        # apply-on-append via latest_conf).
                        log.cring[idx % L] = centsv[k]
                new_last = tail if conflict else max(log.last, tail)
                wrote = n_e > 0 and (new_last != log.last or conflict)
                if wrote:
                    app_from, app_to = prev_i + 1, new_last
                log.last = new_last
                commit[g] = max(commit[g], min(lc, tail))
        for p in range(P):
            if bool(ib["ae_valid"][p, g]) and active[g] and p != me:
                out["aer_valid"][p, g] = True
                out["aer_term"][p, g] = term[g]
                sel = ae_ok[p] and p == ae_peer
                out["aer_success"][p, g] = sel and acc
                out["aer_match"][p, g] = (
                    tail if (sel and acc)
                    else min(log.last, int(ib["ae_prev_idx"][p, g]) - 1))
                # Heartbeat echo: the sender never charged an empty AE
                # against its window, so the reply must not decrement it.
                out["aer_empty"][p, g] = int(ib["ae_n"][p, g]) == 0
                out["aer_occ"][p, g] = bool(ib["ae_occ"][p, g])
                # Send-tick echo (read-barrier evidence; kernel phase 4
                # echoes it on success AND failure — any same-term reply
                # proves the AE was processed).
                out["aer_tick"][p, g] = ib["ae_tick"][p, g]

        # ---- 5. InstallSnapshot -------------------------------------------
        # (reference Follower.installSnapshot:130-153 + host completion,
        # RaftRoutine.restoreCheckpoint:482-541.)
        is_ok = [bool(ib["is_valid"][p, g]) and active[g] and p != me
                 and int(ib["is_term"][p, g]) == term[g] for p in range(P)]
        is_peer = next((p for p in range(P) if is_ok[p]), 0)
        is_any = any(is_ok) and role[g] != LEADER
        # Coverage is evaluated against the selected offer whenever one
        # passed the term check (the reply is sent even if we are — by an
        # impossible schedule — a same-term leader; matches the kernel).
        off_idx = int(ib["is_idx"][is_peer, g])
        off_term = int(ib["is_last_term"][is_peer, g])
        off_conf = int(ib["is_conf"][is_peer, g])
        covered = (any(is_ok)
                   and (off_idx <= log.base
                        or (off_idx <= log.last
                            and log.term_at(off_idx) == off_term)))
        if is_any:
            role[g] = FOLLOWER
            leader_id[g] = is_peer
            elect_dl[g] = now + rand_to[g]
            if not covered:
                info["snap_req"][g] = True
                info["snap_req_from"][g] = is_peer
                info["snap_req_idx"][g] = off_idx
                info["snap_req_term"][g] = off_term
                info["snap_req_conf"][g] = off_conf
        for p in range(P):
            if bool(ib["is_valid"][p, g]) and active[g] and p != me:
                out["isr_valid"][p, g] = True
                out["isr_term"][p, g] = term[g]
                out["isr_success"][p, g] = (is_ok[p] and p == is_peer
                                            and covered)
                out["isr_probe"][p, g] = bool(ib["is_probe"][p, g])

        snap_inst = (h["snap_done"][g] and active[g]
                     and int(h["snap_idx"][g]) > log.base)
        if snap_inst:
            si, st = int(h["snap_idx"][g]), int(h["snap_term"][g])
            tail_matches = si <= log.last and log.term_at(si) == st
            log.base, log.base_term = si, st
            if int(h["snap_conf"][g]) != 0:
                log.base_conf = int(h["snap_conf"][g])
            if not tail_matches:
                log.last = si
            commit[g] = max(commit[g], si)

        ct = min(int(h["compact_to"][g]), int(commit[g]))
        if active[g] and ct > log.base:
            log.base_term = log.term_at(ct)
            # The milestone config folds into base_conf BEFORE the floor
            # moves (kernel: latest_conf(log, ct) pre-floor).
            _, log.base_conf = log.latest_conf(ct)
            log.base = ct

        # Membership view C1 (kernel: post-AE/snapshot/compaction).
        cidx1, w1 = log.latest_conf(log.last)
        voters1, vnew1 = conf_voters_of(w1), conf_new_of(w1)
        lrn1 = conf_learners_of(w1)
        voter_self = ((voters1 | vnew1) >> me) & 1

        # ---- 6. AppendEntries / snapshot responses (leader side) ----------
        # (reference Leader.java:224-243, Leadership.updateIndex:75-114;
        # pipeline accounting per Leadership.java:10-11; health evidence per
        # statSuccess, Leadership.java:53-63.)
        for p in range(P):
            r = (bool(ib["aer_valid"][p, g]) and active[g]
                 and role[g] == LEADER and int(ib["aer_term"][p, g]) == term[g])
            if r:
                m = int(ib["aer_match"][p, g])
                if ib["aer_success"][p, g]:
                    match_idx[g, p] = max(match_idx[g, p], m)
                    next_idx[g, p] = max(next_idx[g, p], match_idx[g, p] + 1)
                    need_snap[g, p] = False
                else:
                    next_idx[g, p] = min(max(m + 1, 1), next_idx[g, p])
                    need_snap[g, p] = next_idx[g, p] <= log.base
            # Unconditional floor (kernel applies it to every lane).
            next_idx[g, p] = max(next_idx[g, p], log.base + 1)
            if r:
                # Heartbeat replies (aer_empty) release a heartbeat slot;
                # data replies release a data slot (lanes never cross).
                if ib["aer_empty"][p, g]:
                    if ib["aer_occ"][p, g]:
                        hb_inflight[g, p] = max(hb_inflight[g, p] - 1, 0)
                else:
                    inflight[g, p] = max(inflight[g, p] - 1, 0)
                if not ib["aer_success"][p, g]:
                    inflight[g, p] = 0
                    hb_inflight[g, p] = 0
                    send_next[g, p] = next_idx[g, p]
                ok_at[g, p] = now
                fail_streak[g, p] = 0
            ir = (bool(ib["isr_valid"][p, g]) and active[g]
                  and role[g] == LEADER and int(ib["isr_term"][p, g]) == term[g])
            if ir:
                if ib["isr_success"][p, g]:
                    need_snap[g, p] = False
                    next_idx[g, p] = max(next_idx[g, p], log.base + 1)
                    match_idx[g, p] = max(match_idx[g, p], log.base)
                # Probe re-offers never occupied a slot (isr_probe echo).
                if not ib["isr_probe"][p, g]:
                    inflight[g, p] = max(inflight[g, p] - 1, 0)
                ok_at[g, p] = now
                fail_streak[g, p] = 0
            # The pipeline head never trails the ack base.
            send_next[g, p] = max(send_next[g, p], next_idx[g, p])

        # ---- 6b. read-barrier evidence ------------------------------------
        # (kernel phase 6b: a same-term AE reply proves the sender followed
        # us when it processed the AE.  Lease mode stores the RECEIPT tick
        # gated by the echo freshness bound; strict mode stores the ECHOED
        # send tick.)
        for p in range(P):
            if p == me:
                continue
            r = (bool(ib["aer_valid"][p, g]) and active[g]
                 and role[g] == LEADER and int(ib["aer_term"][p, g]) == term[g])
            if not r:
                continue
            echoed = int(ib["aer_tick"][p, g])
            if cfg.read_lease:
                if now - echoed <= cfg.read_fresh_ticks:
                    read_evid[g, p] = now
            else:
                read_evid[g, p] = max(int(read_evid[g, p]), echoed)
        if h["read_veto"]:
            # Host detected a wall-clock tick gap: stored AND same-tick
            # lease evidence is untrustworthy (kernel applies the same
            # zeroing after the evidence store).
            read_evid[g, :] = 0

        # ---- 6c. CheckQuorum step-down (kernel phase 6c) ------------------
        # Any valid inbound RPC from p (term-independent) refreshes the
        # contact lane; the window anchors at election win and advances
        # when a due check passes.  A due leader without a voter-quorum
        # of fresh contact steps down — phase 8b then drops its pending
        # lease reads and zeroes read_evid via keep_reads.
        if has_qc:
            for p in range(P):
                if p == me or not active[g]:
                    continue
                if any(bool(ib[k][p, g]) for k in
                       ("ae_valid", "aer_valid", "rv_valid", "rvr_valid",
                        "is_valid", "isr_valid", "tn_valid")):
                    qc_heard[g, p] = now
            if vote_win:
                qc_since[g] = now
            cq_due = (active[g] and role[g] == LEADER
                      and now - int(qc_since[g]) >= cfg.election_ticks)
            if cq_due:
                flags = [p == me or int(qc_heard[g, p]) >= int(qc_since[g])
                         for p in range(P)]
                if _dual_quorum(flags, voters1, vnew1):
                    qc_since[g] = now
                else:
                    # Count the pending lease reads this step-down vetoes
                    # BEFORE 8b clears the FIFO.
                    info["cq_stepdown"][g] = True
                    info["cq_veto"][g] = sum(
                        int(rq_n[g, (int(rq_head[g]) + j) % K])
                        for j in range(int(rq_len[g])))
                    role[g] = FOLLOWER
                    leader_id[g] = NIL
                    elect_dl[g] = now + rand_to[g]

        # ---- 7. timers -----------------------------------------------------
        # (reference Follower.onTimeout:156-168, Candidate.onTimeout:82-88.)
        start_pre = False
        timer_cand = False
        # Only voters campaign (§6; kernel phase 7 gate on C1).
        if (active[g] and now >= elect_dl[g] and role[g] != LEADER
                and voter_self):
            if cfg.pre_vote:
                if role[g] in (FOLLOWER, PRE_CANDIDATE):
                    start_pre = True
                elif role[g] == CANDIDATE:
                    timer_cand = True
            else:
                timer_cand = True
        # TimeoutNow (§3.10): immediate candidacy, skipping PreVote.
        tn_cand = (active[g] and role[g] != LEADER and voter_self
                   and any(ib["tn_valid"][p, g]
                           and int(ib["tn_term"][p, g]) == term[g]
                           for p in range(P) if p != me))
        if tn_cand:
            start_pre = False
            timer_cand = True
        if timer_cand:
            term[g] += 1
            voted[g] = me
            role[g] = CANDIDATE
            leader_id[g] = NIL
            votes[g] = False
            votes[g, me] = True
            elect_dl[g] = now + rand_to[g]
        elif start_pre:
            role[g] = PRE_CANDIDATE
            leader_id[g] = NIL
            prevotes[g] = False
            prevotes[g, me] = True
            elect_dl[g] = now + rand_to[g]
        became_cand = become_cand_pv or timer_cand
        last_term_v = log.term_at(log.last)

        # ---- 7b. leadership-transfer intake/abort (kernel phase 7b) -------
        pend0 = int(xfer_to[g]) != NIL
        keep_x = (pend0 and active[g] and role[g] == LEADER
                  and term[g] == old_term[g] and now < int(xfer_dl[g]))
        info["xfer_abort"][g] = pend0 and not keep_x
        if not keep_x:
            xfer_to[g], xfer_dl[g] = NIL, 0
        tgt = int(h["xfer_target"][g])
        tgt_voter = 0 <= tgt < P and ((voters1 | vnew1) >> tgt) & 1
        if (active[g] and role[g] == LEADER and int(xfer_to[g]) == NIL
                and tgt_voter and tgt != me):
            xfer_to[g] = tgt
            xfer_dl[g] = now + cfg.election_ticks
        fenced = int(xfer_to[g]) != NIL

        # ---- 8. client submissions ----------------------------------------
        # (reference RaftStub.submit -> Leader.acceptCommand:128-140; a
        # pending leadership transfer fences intake.)
        info["submit_start"][g] = log.last + 1
        n_acc = 0
        if active[g] and role[g] == LEADER and not fenced:
            free = L - (log.last - log.base)
            n_acc = max(0, min(int(h["submit_n"][g]), min(free, S)))
        if n_acc > 0:
            if app_from == 0:
                app_from = log.last + 1
            for k in range(n_acc):
                log.ring[(log.last + 1 + k) % L] = term[g]
                log.cring[(log.last + 1 + k) % L] = 0
            log.last += n_acc
            app_to = log.last
        info["submit_acc"][g] = n_acc

        # ---- 8b. linearizable read plane: intake + barrier release --------
        # (kernel phase 8b: stamp an offered batch with the current commit,
        # release pending batches FIFO once a majority's barrier evidence
        # postdates their stamp — mirrors ops/quorum.read_barrier_release.)
        keep_reads = (active[g] and role[g] == LEADER
                      and term[g] == old_term[g])
        info["read_abort"][g] = int(rq_len[g]) > 0 and not keep_reads
        if not keep_reads:
            rq_head[g] = 0
            rq_len[g] = 0
            read_evid[g, :] = 0
        n_read = 0
        if (keep_reads and commit[g] >= own_from_a[g]
                and int(rq_len[g]) < K):
            n_read = max(0, int(h["read_n"][g]))
        if n_read > 0:
            slot = (int(rq_head[g]) + int(rq_len[g])) % K
            rq_idx[g, slot] = commit[g]
            rq_stamp[g, slot] = now
            rq_n[g, slot] = n_read
            rq_len[g] += 1
            info["read_index"][g] = commit[g]
        info["read_acc"][g] = n_read
        n_rel, n_served = 0, 0
        for j in range(int(rq_len[g])):
            slot = (int(rq_head[g]) + j) % K
            flags = [p == me or int(read_evid[g, p]) >= int(rq_stamp[g, slot])
                     for p in range(P)]
            if not _dual_quorum(flags, voters1, vnew1):
                break   # FIFO: an unreleasable batch blocks younger ones
            n_rel += 1
            n_served += int(rq_n[g, slot])
        rq_head[g] = (int(rq_head[g]) + n_rel) % K
        rq_len[g] -= n_rel
        info["read_rel"][g] = n_rel
        info["read_served"][g] = n_served
        info["read_lease"][g] = (n_read > 0 and n_rel > 0
                                 and int(rq_len[g]) == 0)
        read_kick = n_read > 0 and int(rq_len[g]) > 0

        # ---- 8c. membership-change intake + automatic joint leave ---------
        # (kernel phase 8c: one config entry per request — joint when the
        # voter set moves — one change in flight, C_new leave appended
        # automatically once the joint entry commits.)
        full_bits = (1 << P) - 1
        hv = int(h["conf_voters"][g]) & full_bits
        hl = int(h["conf_learners"][g]) & full_bits & ~hv
        joint1 = vnew1 != 0
        pending1 = cidx1 > commit[g]
        may_append = (active[g] and role[g] == LEADER and not pending1
                      and log.last - log.base < L)
        enter_word = int(conf_pack(voters1, 0, hl) if hv == voters1
                         else conf_pack(voters1, hv, hl))
        want_enter = (may_append and not joint1 and not fenced
                      and hv != 0 and enter_word != w1)
        want_leave = may_append and joint1
        conf_app = want_enter or want_leave
        app_word = int(conf_pack(vnew1, 0, lrn1)) if want_leave \
            else enter_word
        if conf_app:
            nidx = log.last + 1
            log.ring[nidx % L] = term[g]
            log.cring[nidx % L] = app_word
            log.last = nidx
            info["conf_app_idx"][g] = nidx
            info["conf_app_term"][g] = term[g]
            info["conf_app_word"][g] = app_word
            if app_from == 0:
                app_from = nidx
            app_to = log.last
            cidx2, w2 = nidx, app_word
        else:
            cidx2, w2 = cidx1, w1
        voters2, vnew2 = conf_voters_of(w2), conf_new_of(w2)
        lrn2 = conf_learners_of(w2)
        member2 = voters2 | vnew2 | lrn2

        # ---- 9. replication fan-out ---------------------------------------
        # (reference Leader.replicateLog:142-245 + prepareElection fan-out;
        # pipelined up to inflight_limit batches, Leadership.java:10-11;
        # fan-out gated to MEMBER slots of the active config.)
        heartbeat = role[g] == LEADER and (now >= hb_due[g] or read_kick)
        if active[g] and role[g] == LEADER:
            for p in range(P):
                if p == me or not (member2 >> p) & 1:
                    continue
                # RPC timeout — the only failure evidence, anchored to our
                # own last occupying send (see kernel phase 9; reference
                # statFailure, Leadership.java:65-73).
                timed_out = (inflight[g, p] + hb_inflight[g, p] > 0
                             and now - sent_at[g, p] >= cfg.rpc_timeout_ticks)
                if timed_out:
                    fail_streak[g, p] += 1
                    fail_at[g, p] = now
                    send_next[g, p] = next_idx[g, p]
                    inflight[g, p] = 0
                    hb_inflight[g, p] = 0
                has_data = (log.last >= send_next[g, p]
                            and not need_snap[g, p])
                can_send = (inflight[g, p] + hb_inflight[g, p]
                            < cfg.inflight_limit)
                send_data = not need_snap[g, p] and has_data and can_send
                # Heartbeats flow on the cadence regardless of window state
                # (slot-exempt when full; reference heartbeat budget
                # division, Leader.java:162).
                send_hb = (not need_snap[g, p] and heartbeat
                           and not send_data)
                hb_occupy = send_hb and can_send
                send_is_win = (need_snap[g, p]
                               and inflight[g, p] + hb_inflight[g, p] == 0)
                send_is = send_is_win or (need_snap[g, p] and heartbeat)
                if send_data or send_hb:
                    n_send = (min(B, log.last - send_next[g, p] + 1)
                              if send_data else 0)
                    prev = int(send_next[g, p]) - 1
                    out["ae_valid"][p, g] = True
                    out["ae_term"][p, g] = term[g]
                    out["ae_prev_idx"][p, g] = prev
                    # prev term via batch semantics (<= base -> base_term).
                    out["ae_prev_term"][p, g] = (
                        log.base_term if prev <= log.base
                        else (log.ring[prev % L] if prev <= log.last else -1))
                    out["ae_commit"][p, g] = commit[g]
                    out["ae_n"][p, g] = n_send
                    out["ae_tick"][p, g] = now
                    for k in range(B):
                        idx = int(send_next[g, p]) + k
                        out["ae_ents"][p, g, k] = (
                            log.base_term if idx <= log.base
                            else (log.ring[idx % L] if idx <= log.last
                                  else -1))
                        out["ae_cents"][p, g, k] = log.conf_at(idx)
                    send_next[g, p] += n_send
                elif send_is:
                    out["is_valid"][p, g] = True
                    out["is_term"][p, g] = term[g]
                    out["is_idx"][p, g] = log.base
                    out["is_last_term"][p, g] = log.base_term
                    out["is_probe"][p, g] = not send_is_win
                    out["is_conf"][p, g] = log.base_conf
                # Data batches and first snapshot offers occupy data
                # slots, in-window heartbeats occupy heartbeat slots; any
                # occupying send refreshes the send clock.
                if send_data or send_is_win:
                    inflight[g, p] += 1
                if hb_occupy:
                    hb_inflight[g, p] += 1
                if send_data or send_is_win or hb_occupy:
                    sent_at[g, p] = now
        if heartbeat:
            hb_due[g] = now + cfg.heartbeat_ticks

        # Leader readiness (reference Leader.isReady, Leader.java:52-64),
        # as a masked quorum over the active config; self counts iff self
        # is a voter; a pending transfer reports not-ready.
        flags = []
        for p in range(P):
            if p == me:
                flags.append(True)
                continue
            hp = (active[g] and role[g] == LEADER
                  and bool((member2 >> p) & 1)
                  and ok_at[g, p] > 0 and not need_snap[g, p])
            if cfg.avail_crit > 0:
                hp = hp and fail_streak[g, p] <= cfg.avail_crit
            if cfg.recovery_ticks > 0:
                hp = hp and (fail_at[g, p] == 0
                             or now - fail_at[g, p] >= cfg.recovery_ticks)
            flags.append(bool(hp))
        info["ready"][g] = (active[g] and role[g] == LEADER and not fenced
                            and _dual_quorum(flags, voters2, vnew2))

        # TimeoutNow dispatch (kernel: after readiness, pre-commit match).
        xt = int(xfer_to[g])
        fire = (active[g] and role[g] == LEADER and xt != NIL
                and int(match_idx[g, xt]) >= log.last)
        info["xfer_fired"][g] = fire
        if fire:
            out["tn_valid"][xt, g] = True
            out["tn_term"][xt, g] = term[g]

        if active[g] and (became_cand or start_pre):
            for p in range(P):
                if p == me or not ((voters2 | vnew2) >> p) & 1:
                    continue
                out["rv_valid"][p, g] = True
                out["rv_term"][p, g] = term[g] + 1 if start_pre else term[g]
                out["rv_last_idx"][p, g] = log.last
                out["rv_last_term"][p, g] = last_term_v
                out["rv_prevote"][p, g] = start_pre

        # ---- 10. commit advance -------------------------------------------
        # (reference Leadership.majorIndices:116-130 + the own-term rule,
        # Leader.tryCommit:256-261.)
        full = match_idx[g].copy()
        full[me] = log.last

        def _stat(mask: int) -> int:
            # ops/quorum.masked_order_stat, scalar: non-members sort as
            # -1 below every real match; the statistic sits at
            # P - (popcount//2 + 1) of the ascending order.
            vals = sorted(int(full[p]) if (mask >> p) & 1 else -1
                          for p in range(P))
            pos = min(max(P - (_popcount(mask) // 2 + 1), 0), P - 1)
            return vals[pos]

        quorum_idx = _stat(voters2)
        if vnew2:
            # Joint config: a commit needs a quorum in BOTH sets (§6).
            quorum_idx = min(quorum_idx, _stat(vnew2))
        voter_rows = [int(full[p]) for p in range(P)
                      if ((voters2 | vnew2) >> p) & 1]
        full_idx = min(voter_rows) if voter_rows else (1 << 31) - 1
        # Own-term rule via own_from (terms monotone along the log; set at
        # election win) — mirrors ops/quorum.py exactly.
        if (active[g] and role[g] == LEADER and quorum_idx > commit[g]
                and quorum_idx >= own_from_a[g]
                and quorum_idx <= log.last):
            commit[g] = quorum_idx
        # Full-replication lane (reference Leader.java:260, mirrors
        # ops/quorum.py): min over VOTER slots commits without the
        # own-term fence — identical on every voter, hence on every
        # possible future leader; learner lag never stalls it.
        if (active[g] and role[g] == LEADER and full_idx > commit[g]
                and full_idx <= log.last):
            commit[g] = full_idx
        match_idx[g] = full

        # §6 epilogue (kernel post-phase-10): a leader removed by its
        # committed simple config resigns.
        if (active[g] and role[g] == LEADER and vnew2 == 0
                and cidx2 <= commit[g] and not (voters2 >> me) & 1):
            role[g] = FOLLOWER
            leader_id[g] = NIL
            elect_dl[g] = now + rand_to[g]

        info["conf_word"][g] = w2
        info["conf_idx"][g] = cidx2
        info["conf_pending"][g] = cidx2 > commit[g]
        conf_idx_st[g], conf_word_st[g] = cidx2, w2

        # ---- 11. flight recorder ------------------------------------------
        # (kernel trailing block: same masks, same canonical order, same
        # ring-overwrite semantics.  All records carry the end-of-tick
        # term; TR_CRASH_RESTART is emitted by types.crash_restart.)
        if has_trace and active[g]:
            def tr_emit(mask, kind, aux):
                if not mask:
                    return
                slot = int(tr_n[g]) % D
                tr_tick[g, slot] = now
                tr_kind[g, slot] = kind
                tr_term[g, slot] = term[g]
                tr_aux[g, slot] = aux
                tr_n[g] += 1

            tr_emit(term[g] != old_term[g], TR_TERM_BUMP, old_term[g])
            tr_emit(old_role[g] == LEADER and role[g] != LEADER,
                    TR_STEPPED_DOWN, leader_id[g])
            tr_emit(start_pre, TR_BECAME_PRE_CANDIDATE, 0)
            # Candidacy cause: 0 prevote / 1 timer / 2 TimeoutNow.
            tr_emit(became_cand, TR_BECAME_CANDIDATE,
                    (2 if tn_cand else 1) if timer_cand else 0)
            tr_emit(vote_win, TR_BECAME_LEADER, info["noop_idx"][g])
            tr_emit(snap_inst, TR_SNAPSHOT_INSTALL, h["snap_idx"][g])
            tr_emit(commit[g] > old_commit[g], TR_COMMIT_ADVANCE, commit[g])
            tr_emit(n_rel > 0, TR_READ_RELEASE, n_served)
            tr_emit(w2 != w0 or cidx2 != cidx0, TR_CONF_CHANGE_ENTER, w2)
            tr_emit(cidx2 > 0 and old_commit[g] < cidx2 <= commit[g],
                    TR_CONF_CHANGE_COMMIT, cidx2)
            tr_emit(fire, TR_LEADER_TRANSFER, xfer_to[g])

        ring[g] = log.ring
        cring[g] = log.cring
        base[g], base_term[g], last[g] = log.base, log.base_term, log.last
        base_conf[g] = log.base_conf
        info["dirty"][g] = (term[g] != old_term[g] or voted[g] != old_voted[g]
                            or last[g] != old_last[g] or app_to > 0)
        info["appended_from"][g] = app_from
        info["appended_to"][g] = app_to
        info["log_tail"][g] = log.last
        info["commit"][g] = commit[g]
        info["leader"][g] = leader_id[g]

        # ---- 12. heat lanes -----------------------------------------------
        # (kernel trailing block: per-group cumulative activity.  By the
        # end of this iteration every out[...][:, g] column is final, so
        # the sent count matches the kernel's sum over the outbox valid
        # planes exactly.)
        if has_heat:
            sent_n = 0
            for k in ("ae_valid", "aer_valid", "rv_valid", "rvr_valid",
                      "is_valid", "isr_valid", "tn_valid"):
                for p in range(P):
                    sent_n += int(out[k][p, g])
            ht_app[g] += (app_to - app_from + 1) if app_to > 0 else 0
            ht_sent[g] += sent_n
            ht_com[g] += int(commit[g]) - int(old_commit[g])
            ht_rd[g] += n_served

    new_state = {
        "node_id": np.asarray(me, np.int32),
        "now": np.asarray(now, np.int32),
        "rng": np.asarray(rng),
        "active": active,
        "term": term.astype(np.int32),
        "role": role,
        "voted_for": voted,
        "leader_id": leader_id,
        "commit": commit,
        "applied": s["applied"],
        "log.term": ring, "log.conf": cring, "log.base": base,
        "log.base_term": base_term, "log.base_conf": base_conf,
        "log.last": last,
        "own_from": own_from_a.astype(np.int32),
        "next_idx": next_idx, "match_idx": match_idx,
        "send_next": send_next, "inflight": inflight,
        "hb_inflight": hb_inflight,
        "sent_at": sent_at, "need_snap": need_snap,
        "ok_at": ok_at, "fail_at": fail_at, "fail_streak": fail_streak,
        "votes": votes, "prevotes": prevotes,
        "elect_deadline": elect_dl, "hb_due": hb_due,
        "read_evid": read_evid,
        "rq_idx": rq_idx, "rq_stamp": rq_stamp, "rq_n": rq_n,
        "rq_head": rq_head, "rq_len": rq_len,
        "conf_idx": conf_idx_st, "conf_word": conf_word_st,
        "xfer_to": xfer_to, "xfer_dl": xfer_dl,
    }
    if has_trace:
        new_state.update({
            "trace.tick": tr_tick, "trace.kind": tr_kind,
            "trace.term": tr_term, "trace.aux": tr_aux, "trace.n": tr_n,
        })
    if has_heat:
        new_state.update({
            "heat.appended": ht_app, "heat.sent": ht_sent,
            "heat.commits": ht_com, "heat.reads": ht_rd,
        })
    if has_qc:
        new_state.update({"qc.heard": qc_heard, "qc.since": qc_since})
    return new_state, out, info
