"""Wing & Gong linearizability checker over recorded client histories.

Checks the one property users actually buy: every completed operation
appears to take effect atomically at some instant between its invocation
and its response.  The algorithm is the classic Wing & Gong search
("Testing and Verifying Concurrent Objects", 1993) with the
memoization refinement popularized by Lowe/Horn ("Faster linearizability
checking via P-compositionality"): depth-first search over the states
``(set of linearized ops, model state)``, pruning re-visited pairs.

Tractability comes from LOCALITY (Herlihy & Wing): a history over a map
is linearizable iff its per-key sub-histories are — so the checker is
compositional per key and the search space is bounded by per-key
concurrency (the number of client threads), not total history length.

Semantics of the op statuses (testkit/history.py):

* ``ok``   ops MUST linearize between invoke and response.
* ``fail`` ops are excluded — the node proved they never happened.
* ``info`` WRITES are forever-concurrent: the search may linearize one
  at any point after its invocation or drop it entirely (the crash
  window / timeout / retry-duplicate ambiguity).  ``info`` reads
  constrain nothing and are excluded.

The model is a per-key register+list hybrid matching the KV machine's
vocabulary (machine/kv_machine.py): ``w`` sets the value, ``a`` appends
to a list, ``r`` must return exactly the current value.  A duplicated
append (client retry whose first attempt committed) is therefore
OBSERVABLE — an ok read returning ``[v, v]`` only verifies if two
appends of ``v`` may linearize, i.e. if the first attempt was recorded
``info``; recording it ``fail`` makes the same history non-linearizable
(tests/test_linz.py pins this).

Counterexamples: on failure the checker shrinks to the shortest
response-prefix of the key's sub-history that is already
non-linearizable and renders it op by op (LinzResult.render) — read it
bottom-up: the last ok op is the one no linearization order can
explain.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .history import History, Op

__all__ = ["LinzResult", "check", "check_ops"]


def _norm(v: Any) -> Any:
    """Hashable canonical form for model states and read results (JSON
    round-trips turn tuples into lists; the model must not care)."""
    if isinstance(v, (list, tuple)):
        return tuple(_norm(x) for x in v)
    return v


def _apply(state: Any, op: Op) -> Tuple[bool, Any]:
    """Step the register+list model: returns (legal, next_state)."""
    if op.kind == "w":
        return True, _norm(op.value)
    if op.kind == "a":
        base = state if isinstance(state, tuple) else ()
        return True, base + (_norm(op.value),)
    # read: legal iff it returned exactly the current value
    return _norm(op.result) == state, state


@dataclass
class LinzResult:
    ok: bool
    key: Optional[str] = None          # failing key (ok=False)
    counterexample: List[Op] = field(default_factory=list)
    checked_keys: int = 0
    n_ops: int = 0
    counts: Dict[str, int] = field(default_factory=dict)

    def render(self) -> str:
        if self.ok:
            return (f"linearizable: {self.n_ops} ops over "
                    f"{self.checked_keys} keys {self.counts}")
        lines = [f"NON-LINEARIZABLE at key {self.key!r} — minimal "
                 f"counterexample ({len(self.counterexample)} ops, "
                 f"in invocation order):"]
        for op in sorted(self.counterexample, key=lambda o: o.invoke_seq):
            lines.append("  " + op.describe())
        lines.append("  (no order of the ok/info ops explains every ok "
                     "read; the latest-responding ok op is the witness)")
        return "\n".join(lines)


def check_ops(ops: List[Op], initial: Any = None) -> bool:
    """Wing & Gong over ONE key's sub-history.  True = linearizable."""
    live = [o for o in ops
            if o.status == "ok" or (o.status == "info"
                                    and o.kind in ("w", "a"))]
    must = frozenset(o.id for o in live if o.status == "ok")
    if not must:
        return True      # nothing observable completed: vacuously fine
    initial = _norm(initial)
    seen = set()
    stack: List[Tuple[frozenset, Any]] = [(frozenset(), initial)]
    while stack:
        done, state = stack.pop()
        key = (done, state)
        if key in seen:
            continue
        seen.add(key)
        if must <= done:
            return True
        pending = [o for o in live if o.id not in done]
        # Minimal ops: nothing still pending responded before their
        # invocation (info ops respond at +inf, so they never bar
        # others but stay optional themselves).
        bar = min(o.resp_seq for o in pending)
        for o in pending:
            if o.invoke_seq < bar:
                legal, nxt = _apply(state, o)
                if legal:
                    stack.append((done | {o.id}, nxt))
    return False


def _clip(ops: List[Op], cutoff: float) -> List[Op]:
    """The history as the world looked at sequence time ``cutoff``:
    ops invoked later don't exist; ops still open at the cutoff have
    unknown outcomes — pending writes downgrade to info, pending reads
    constrain nothing and drop."""
    out = []
    for o in ops:
        if o.invoke_seq >= cutoff:
            continue
        if o.resp_seq >= cutoff and o.status == "ok":
            if o.kind == "r":
                continue
            c = Op(**{**o.__dict__})
            c.status = "info"
            c.resp_seq = math.inf
            out.append(c)
        else:
            out.append(o)
    return out


def _shrink(ops: List[Op], initial: Any = None) -> List[Op]:
    """Shortest failing response-prefix: walk completions in response
    order and return the first prefix that is already non-linearizable
    (minimal in the Jepsen sense — everything after the witness response
    is noise)."""
    resps = sorted(o.resp_seq for o in ops if math.isfinite(o.resp_seq))
    for r in resps:
        sub = _clip(ops, r + 0.5)
        if not check_ops(sub, initial):
            return sub
    return ops


def check(history, initial: Any = None) -> LinzResult:
    """Check a whole history (or a prepared per-key dict / op list),
    compositionally per key.

    SINGLE-KEY OPS ONLY: per-key composition is sound exactly because
    each op touches one key (Herlihy & Wing locality).  A cross-group
    TRANSACTION (op kind ``t``, runtime/txn.py) touches several keys
    atomically — splitting it per key would silently judge each leg as
    an independent single-key op and certify histories where atomicity
    was in fact violated.  Such histories must go to the transfer
    invariant instead (testkit/invariants.py check_transfer_atomicity);
    this guard makes the mis-route loud rather than silently unsound."""
    if isinstance(history, History):
        keys = history.by_key()
    elif isinstance(history, dict):
        keys = history
    else:
        keys = {}
        for op in history:
            keys.setdefault(op.key, []).append(op)
    for ops in keys.values():
        for o in ops:
            if o.kind not in ("w", "a", "r"):
                raise ValueError(
                    f"linz.check got a multi-key op (kind {o.kind!r}, "
                    f"op id {o.id}): per-key composition is unsound for "
                    f"transactions — route txn histories to "
                    f"testkit.invariants.check_transfer_atomicity")
    n_ops = sum(len(v) for v in keys.values())
    counts: Dict[str, int] = {"ok": 0, "fail": 0, "info": 0}
    for ops in keys.values():
        for o in ops:
            counts[o.status] = counts.get(o.status, 0) + 1
    for key, ops in sorted(keys.items()):
        if not check_ops(ops, initial):
            return LinzResult(ok=False, key=key,
                              counterexample=_shrink(ops, initial),
                              checked_keys=len(keys), n_ops=n_ops,
                              counts=counts)
    return LinzResult(ok=True, checked_keys=len(keys), n_ops=n_ops,
                      counts=counts)
