"""Pallas TPU kernels for the engine's hot ops."""

from .quorum import quorum_commit, quorum_commit_pallas, quorum_commit_ref

__all__ = ["quorum_commit", "quorum_commit_pallas", "quorum_commit_ref"]
