"""Pallas TPU kernel for the quorum-commit scan — the flagship hot op.

Computes, for every Raft group at once, the leader's commit advancement
(reference Leader.tryCommit + Leadership.majorIndices,
context/member/Leader.java:247-280, Leadership.java:116-130), generalized
to the §6 membership plane:

  1. quorum index = majority-order statistic of the (group x peer) match
     matrix over the group's VOTER bitmask (self slot pre-filled with the
     leader's own last index).  Non-voter slots (learners, removed peers)
     sort below every real match and the per-group majority position is
     popcount(voters) // 2 + 1 — the fixed-majority order statistic is
     the degenerate full-membership case;
  2. JOINT configs (voters_new nonzero) take the MINIMUM of the two
     sets' order statistics: an entry commits only with a quorum in both
     C_old and C_new (Raft §6);
  3. the commit-only-own-term rule (Raft §5.4.2, Leader.java:256-261),
     reduced to ``quorum_idx >= own_from`` — terms are monotone along the
     log and ``own_from`` (RaftState) is the first index of the leader's
     current term, pinned at election win by the §8 no-op;
  4. the full-replication lane (Leader.java:260 ``fullIndex``) takes the
     min over VOTER slots only — a learner hauling itself up from a
     snapshot must never stall the lane (its match says nothing about
     what electable nodes hold);
  5. masked monotone update of commitIndex.

Layout: group-major arrays are reshaped to [rows, 128] so the group axis
rides the TPU lanes; the peer axis (3-10) is a static unroll of an
odd-even transposition sorting network on [rows, 128] tiles in VMEM (one
network per voter set; the joint pass reuses the same plane loads).

``quorum_commit`` dispatches to the Pallas kernel, the pure-jnp masked
reference (identical semantics, parity-tested in tests/test_ops.py), or —
``cfg.quorum_fixed`` — the legacy fixed-majority baseline kept ONLY for
the BENCH_MEMBER A/B (valid only while every group holds the boot
full-voter config).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.types import I32

BLOCK_ROWS = 8          # sublanes per grid step
LANES = 128

# NOTE: no module-level jnp constants here — this module is first
# imported lazily INSIDE node_step's jit trace, where array creation
# would capture a tracer and leak it across traces.
_I32_MAX = (1 << 31) - 1


def _bits(mask: jax.Array, P: int) -> jax.Array:
    """[G] peer bitmask -> [G, P] bool (local copy of core.step.mask_bits;
    ops must not import the step module)."""
    return ((mask[:, None] >> jnp.arange(P, dtype=I32)[None, :]) & 1) > 0


# ---------------------------------------------------------------- reference --

def masked_order_stat(match: jax.Array, bits: jax.Array) -> jax.Array:
    """Majority-order statistic of ``match`` [G, P] over ``bits`` [G, P]:
    the largest x such that at least popcount//2+1 of the masked slots
    hold match >= x.  Non-members become -1 (below any real match, which
    is >= 0), so after an ascending sort the statistic sits at position
    P - majority.  An empty mask yields -1 (no quorum ever).

    The per-lane position select is a STATIC where-chain over the P
    columns, not a take_along_axis: a [G, 1] dynamic gather lowers to a
    per-row scatter/gather loop on the CPU backend and measured ~2x on
    the whole step at 32k groups; P compares+selects are pure vector
    ops."""
    P = match.shape[1]
    sm = jnp.sort(jnp.where(bits, match, jnp.asarray(-1, I32)), axis=1)
    nv = bits.sum(axis=1).astype(I32)
    pos = jnp.clip(P - (nv // 2 + 1), 0, P - 1)
    q = sm[:, 0]
    for p in range(1, P):
        q = jnp.where(pos == p, sm[:, p], q)
    return q


def quorum_commit_ref(match_full, own_from, last, commit, can_lead,
                      voters, voters_new) -> jax.Array:
    """Pure-jnp reference (exactly core/step.py phase 10).

    Two commit lanes, the reference's tryCommit (Leader.java:256-261)
    membership-generalized:

    * quorum lane — the masked majority order statistic (JOINT: min over
      both voter sets), gated by the commit-only-own-term rule
      (``quorum_idx >= own_from``);
    * full-replication lane — the MINIMUM over VOTER slots (both sets
      while joint; learners excluded — Leader.java:260 ``fullIndex``): an
      entry replicated on every voter is on every electable future
      leader's log, so committing it needs no own-term fence.  This is
      what lets a fully-replicated prior-term suffix commit on a
      ring-full lane where the §8 no-op could not be appended.
    """
    P = match_full.shape[1]
    vb = _bits(voters, P)
    q = masked_order_stat(match_full, vb)
    nb = _bits(voters_new, P)
    qn = masked_order_stat(match_full, nb)
    joint = voters_new != 0
    q = jnp.where(joint, jnp.minimum(q, qn), q)
    full = jnp.where(vb | nb, match_full,
                     jnp.asarray(_I32_MAX, I32)).min(axis=1)
    can = can_lead & (q > commit) & (q >= own_from) & (q <= last)
    can_full = can_lead & (full > commit) & (full <= last)
    return jnp.maximum(jnp.where(can, q, commit),
                       jnp.where(can_full, full, commit))


def quorum_commit_fixed(cfg, match_full, last, commit, own_from, can_lead
                        ) -> jax.Array:
    """The legacy fixed-majority kernel (pre-membership behavior): order
    statistic at the STATIC majority over all P slots, full lane = min of
    the whole row.  Kept as the BENCH_MEMBER baseline; only valid while
    every group holds the boot full-voter config."""
    P = match_full.shape[1]
    if P == 3 and cfg.majority == 2:
        a, b, c = match_full[:, 0], match_full[:, 1], match_full[:, 2]
        quorum_idx = jnp.maximum(jnp.minimum(a, b),
                                 jnp.minimum(jnp.maximum(a, b), c))
        full_idx = jnp.minimum(jnp.minimum(a, b), c)
    else:
        sorted_m = jnp.sort(match_full, axis=1)
        quorum_idx = sorted_m[:, P - cfg.majority]
        full_idx = sorted_m[:, 0]
    can = can_lead & (quorum_idx > commit) & \
        (quorum_idx >= own_from) & (quorum_idx <= last)
    can_full = can_lead & (full_idx > commit) & (full_idx <= last)
    return jnp.maximum(jnp.where(can, quorum_idx, commit),
                       jnp.where(can_full, full_idx, commit))


# ------------------------------------------------------------------- kernel --

def _kernel(P: int, match_ref, own_from_ref, commit_ref, last_ref, lead_ref,
            vot_ref, new_ref, out_ref):
    planes_raw = [match_ref[p] for p in range(P)]
    vot = vot_ref[...]
    new = new_ref[...]

    def popcount(word):
        n = (word >> 0) & 1
        for p in range(1, P):
            n = n + ((word >> p) & 1)
        return n

    def order_stat(word):
        # Mask non-members below every real match, run the odd-even
        # transposition network, then select the per-lane majority plane
        # (pos = P - (popcount//2 + 1), a static unroll of P selects).
        planes = [jnp.where(((word >> p) & 1) > 0, planes_raw[p], -1)
                  for p in range(P)]
        for _ in range(P):
            for i in range(0, P - 1, 2):
                lo = jnp.minimum(planes[i], planes[i + 1])
                hi = jnp.maximum(planes[i], planes[i + 1])
                planes[i], planes[i + 1] = lo, hi
            for i in range(1, P - 1, 2):
                lo = jnp.minimum(planes[i], planes[i + 1])
                hi = jnp.maximum(planes[i], planes[i + 1])
                planes[i], planes[i + 1] = lo, hi
        nv = popcount(word)
        pos = P - (nv // 2 + 1)
        pos = jnp.clip(pos, 0, P - 1)
        q = jnp.where(pos == 0, planes[0], 0)
        for p in range(1, P):
            q = jnp.where(pos == p, planes[p], q)
        return q

    q = order_stat(vot)
    qn = order_stat(new)
    q = jnp.where(new != 0, jnp.minimum(q, qn), q)
    both = vot | new
    big = jnp.asarray((1 << 31) - 1, jnp.int32)
    full = jnp.where(((both >> 0) & 1) > 0, planes_raw[0], big)
    for p in range(1, P):
        full = jnp.minimum(
            full, jnp.where(((both >> p) & 1) > 0, planes_raw[p], big))

    commit = commit_ref[...]
    last = last_ref[...]
    lead = lead_ref[...] != 0
    can = lead & (q > commit) & (q >= own_from_ref[...]) & (q <= last)
    can_full = lead & (full > commit) & (full <= last)
    out_ref[...] = jnp.maximum(jnp.where(can, q, commit),
                               jnp.where(can_full, full, commit))


def _pad_rows(a: np.ndarray | jax.Array, G: int, Gp: int, fill=0):
    if Gp == G:
        return a
    pad = [(0, Gp - G)] + [(0, 0)] * (a.ndim - 1)
    return jnp.pad(a, pad, constant_values=fill)


@functools.partial(jax.jit, static_argnums=(3,))
def quorum_commit_pallas(match_full, own_from, state_vec,
                         interpret: bool = False) -> jax.Array:
    """Pallas path.  ``state_vec`` packs (commit, last, can_lead, voters,
    voters_new) as a [5, G] i32 array (can_lead nonzero = active leader
    lane; voters_new nonzero = joint config)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    G, P = match_full.shape
    commit, last, can_lead = state_vec[0], state_vec[1], state_vec[2]
    voters, voters_new = state_vec[3], state_vec[4]

    step = BLOCK_ROWS * LANES
    Gp = (G + step - 1) // step * step
    R = Gp // LANES

    def rows(v, fill=0):
        return _pad_rows(v, G, Gp, fill).reshape(R, LANES)

    match_t = _pad_rows(match_full, G, Gp).T.reshape(P, R, LANES)

    grid = (R // BLOCK_ROWS,)
    vec = lambda: pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, 0))
    out = pl.pallas_call(
        functools.partial(_kernel, P),
        out_shape=jax.ShapeDtypeStruct((R, LANES), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((P, BLOCK_ROWS, LANES), lambda i: (0, i, 0)),
            vec(), vec(), vec(), vec(), vec(), vec(),
        ],
        out_specs=vec(),
        interpret=interpret,
    )(match_t, rows(own_from, fill=1), rows(commit), rows(last),
      rows(can_lead), rows(voters), rows(voters_new))
    return out.reshape(Gp)[:G]


# ------------------------------------------------------------ read barrier --

def read_barrier_release(voters, voters_new, me, read_evid, rq_stamp,
                         rq_head, rq_len, rq_n):
    """ReadIndex barrier for every group at once: how many pending read
    batches (FIFO from ``rq_head``) have a confirmed leadership quorum.

    A batch stamped at tick ``s`` releases once the set {self} ∪ {p :
    read_evid[g, p] >= s} covers a majority of the VOTERS — and, while
    joint, of voters_new too (§6: a leadership confirmation is a joint
    decision like any other quorum).  Self counts only if self is a
    voter; learner evidence never counts.  Release is prefix-monotone by
    construction — stamps increase along the FIFO and evidence is a
    per-peer maximum, so a releasable batch implies every older one is
    releasable — but the cumulative-AND guard below keeps FIFO order
    even if a caller hands in unordered stamps.

    Returns ``(n_rel [G] int32, n_served [G] int32)``: batches released
    and the total individual reads inside them.  This lives beside the
    commit kernel because it is the same shape of op — a quorum order
    statistic over the peer axis feeding a masked monotone update — and
    the Pallas treatment, if ever needed, would tile identically.
    """
    G, K = rq_stamp.shape
    P = read_evid.shape[1]
    j = jnp.arange(K, dtype=I32)[None, :]                       # FIFO pos
    slot = jnp.remainder(rq_head[:, None] + j, K)               # [G, K]
    st = jnp.take_along_axis(rq_stamp, slot, axis=1)
    n = jnp.take_along_axis(rq_n, slot, axis=1)
    pending = j < rq_len[:, None]
    # Evidence 0 means "none this leadership"; stamps are >= 1 (the tick
    # clock starts at 1), so the comparison needs no extra guard.
    self_hot = (jnp.arange(P, dtype=I32) == me)[None, None, :]
    flags = (read_evid[:, None, :] >= st[:, :, None]) | self_hot  # [G,K,P]
    vb = _bits(voters, P)[:, None, :]
    nb = _bits(voters_new, P)[:, None, :]
    ok_v = ((flags & vb).sum(axis=2)
            >= vb.sum(axis=2) // 2 + 1)                         # [G, K]
    ok_n = (flags & nb).sum(axis=2) >= nb.sum(axis=2) // 2 + 1
    ok = pending & ok_v & ((voters_new == 0)[:, None] | ok_n)
    rel = pending & (jnp.cumsum((~ok).astype(I32), axis=1) == 0)
    return rel.sum(axis=1).astype(I32), (rel * n).sum(axis=1).astype(I32)


def contact_quorum(voters, voters_new, me, heard, since):
    """CheckQuorum contact test for every group at once: has a majority of
    the VOTERS — and, while joint, of ``voters_new`` too (§6: leadership
    liveness is a joint decision like any other quorum) — been heard from
    at/after the window anchor ``since``?

    ``heard`` is [G, P] (own-clock tick of the last valid inbound RPC per
    peer), ``since`` [G].  Self always counts (a node is always in
    contact with itself — the single-voter group is the degenerate case);
    learner contact never does.  The same masked-popcount shape as
    :func:`read_barrier_release` — only the per-peer flag differs.
    Returns [G] bool.
    """
    P = heard.shape[1]
    self_hot = (jnp.arange(P, dtype=I32) == me)[None, :]
    flags = (heard >= since[:, None]) | self_hot                # [G, P]
    vb = _bits(voters, P)
    nb = _bits(voters_new, P)
    ok_v = (flags & vb).sum(axis=1) >= vb.sum(axis=1) // 2 + 1
    ok_n = (flags & nb).sum(axis=1) >= nb.sum(axis=1) // 2 + 1
    return ok_v & ((voters_new == 0) | ok_n)


def quorum_commit(cfg, match_full, log, commit, own_from, can_lead,
                  voters, voters_new):
    """Dispatch: the legacy fixed-majority baseline when
    ``cfg.quorum_fixed`` (bench A/B only), the Pallas kernel when
    ``cfg.use_pallas``, else inline jnp (the default; all membership
    paths are semantically identical)."""
    if getattr(cfg, "quorum_fixed", False):
        return quorum_commit_fixed(cfg, match_full, log.last, commit,
                                   own_from, can_lead)
    if getattr(cfg, "use_pallas", False):
        import os
        state_vec = jnp.stack([commit, log.last, can_lead.astype(I32),
                               voters, voters_new])
        # Interpret only on the CPU backend; any accelerator attempts the
        # compiled lowering (an unsupported backend then fails LOUDLY
        # instead of silently running the interpreter at 1000x cost — the
        # trap a TPU-plugin-name allowlist would re-arm every time a
        # plugin registers under a new name, e.g. the bench host's 'axon').
        # RAFT_PALLAS_INTERPRET=0/1 overrides either way.
        env = os.environ.get("RAFT_PALLAS_INTERPRET", "").strip().lower()
        if env:
            interpret = env not in ("0", "false", "no", "off")
        else:
            interpret = jax.default_backend() == "cpu"
        return quorum_commit_pallas(match_full, own_from, state_vec,
                                    interpret)
    return quorum_commit_ref(match_full, own_from, log.last, commit,
                             can_lead, voters, voters_new)
