"""Pallas TPU kernel for the quorum-commit scan — the flagship hot op.

Computes, for every Raft group at once, the leader's commit advancement
(reference Leader.tryCommit + Leadership.majorIndices,
context/member/Leader.java:247-280, Leadership.java:116-130):

  1. quorum index = majority-order statistic of the (group x peer) match
     matrix (self slot pre-filled with the leader's own last index);
  2. the commit-only-own-term rule: advance only if the entry at the
     quorum index carries the CURRENT term (Raft §5.4.2,
     Leader.java:256-261);
  3. masked monotone update of commitIndex.

Layout: group-major arrays are reshaped to [rows, 128] so the group axis
rides the TPU lanes; the peer axis (3-9) is a static unroll of an
odd-even transposition sorting network on [rows, 128] tiles in VMEM; the
per-group ring-term lookup is an unrolled select over the ring's L slots
(no per-lane dynamic addressing on TPU).

``quorum_commit`` dispatches to the Pallas kernel or the pure-jnp
reference (identical semantics, parity-tested in tests/test_ops.py).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.types import I32

BLOCK_ROWS = 8          # sublanes per grid step
LANES = 128


# ---------------------------------------------------------------- reference --

def quorum_commit_ref(match_full: jax.Array, ring_term_at_quorum, commit,
                      term, can_lead, majority: int) -> jax.Array:
    """Pure-jnp reference (exactly core/step.py phase 10)."""
    P = match_full.shape[1]
    sorted_m = jnp.sort(match_full, axis=1)
    quorum_idx = sorted_m[:, P - majority]
    can = can_lead & (quorum_idx > commit) & \
        (ring_term_at_quorum(quorum_idx) == term)
    return jnp.where(can, quorum_idx, commit)


# ------------------------------------------------------------------- kernel --

def _kernel(P: int, L: int, majority: int,
            match_ref, ring_ref, base_ref, base_term_ref, last_ref,
            commit_ref, term_ref, lead_ref, out_ref):
    # Load the P match planes ([R, 128] tiles) and run an odd-even
    # transposition network; after P passes the planes are sorted
    # ascending, so plane P-majority is the quorum order statistic.
    planes = [match_ref[p] for p in range(P)]
    for _ in range(P):
        for i in range(0, P - 1, 2):
            lo = jnp.minimum(planes[i], planes[i + 1])
            hi = jnp.maximum(planes[i], planes[i + 1])
            planes[i], planes[i + 1] = lo, hi
        for i in range(1, P - 1, 2):
            lo = jnp.minimum(planes[i], planes[i + 1])
            hi = jnp.maximum(planes[i], planes[i + 1])
            planes[i], planes[i + 1] = lo, hi
    q = planes[P - majority]

    base = base_ref[...]
    last = last_ref[...]
    commit = commit_ref[...]
    term = term_ref[...]
    lead = lead_ref[...]

    # Ring term at the quorum index: unrolled select over the L slots
    # (ring layout is slot-major [L, R, 128]).  Semantics match
    # core/step.py ring_term_at: <= base -> base_term; > last -> -1.
    slot = jnp.remainder(q, L)
    t_at = jnp.full_like(q, -1)
    for l in range(L):
        t_at = jnp.where(slot == l, ring_ref[l], t_at)
    t_at = jnp.where(q <= base, base_term_ref[...],
                     jnp.where(q <= last, t_at, jnp.full_like(q, -1)))

    can = (lead != 0) & (q > commit) & (t_at == term)
    out_ref[...] = jnp.where(can, q, commit)


def _pad_rows(a: np.ndarray | jax.Array, G: int, Gp: int, fill=0):
    if Gp == G:
        return a
    pad = [(0, Gp - G)] + [(0, 0)] * (a.ndim - 1)
    return jnp.pad(a, pad, constant_values=fill)


@functools.partial(jax.jit, static_argnums=(6, 7))
def quorum_commit_pallas(match_full, log_term_ring, base, base_term, last,
                         state_vec, majority: int, interpret: bool = False
                         ) -> jax.Array:
    """Pallas path.  ``state_vec`` packs (commit, term, can_lead) as a
    [3, G] i32 array (can_lead nonzero = active leader lane)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    G, P = match_full.shape
    L = log_term_ring.shape[1]
    commit, term, can_lead = state_vec[0], state_vec[1], state_vec[2]

    step = BLOCK_ROWS * LANES
    Gp = (G + step - 1) // step * step
    R = Gp // LANES

    def rows(v, fill=0):
        return _pad_rows(v, G, Gp, fill).reshape(R, LANES)

    match_t = _pad_rows(match_full, G, Gp).T.reshape(P, R, LANES)
    ring_t = _pad_rows(log_term_ring, G, Gp).T.reshape(L, R, LANES)

    grid = (R // BLOCK_ROWS,)
    vec = lambda: pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, 0))
    out = pl.pallas_call(
        functools.partial(_kernel, P, L, majority),
        out_shape=jax.ShapeDtypeStruct((R, LANES), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((P, BLOCK_ROWS, LANES), lambda i: (0, i, 0)),
            pl.BlockSpec((L, BLOCK_ROWS, LANES), lambda i: (0, i, 0)),
            vec(), vec(), vec(), vec(), vec(), vec(),
        ],
        out_specs=vec(),
        interpret=interpret,
    )(match_t, ring_t, rows(base), rows(base_term), rows(last),
      rows(commit), rows(term), rows(can_lead))
    return out.reshape(Gp)[:G]


def quorum_commit(cfg, match_full, log, commit, term, can_lead):
    """Dispatch: Pallas when ``cfg.use_pallas``, else inline jnp (the
    default; both paths are semantically identical)."""
    from ..core.step import ring_term_at

    if getattr(cfg, "use_pallas", False):
        import os
        state_vec = jnp.stack(
            [commit, term, can_lead.astype(I32)])
        # Interpret only on the CPU backend; any accelerator attempts the
        # compiled lowering (an unsupported backend then fails LOUDLY
        # instead of silently running the interpreter at 1000x cost — the
        # trap a TPU-plugin-name allowlist would re-arm every time a
        # plugin registers under a new name, e.g. the bench host's 'axon').
        # RAFT_PALLAS_INTERPRET=0/1 overrides either way.
        env = os.environ.get("RAFT_PALLAS_INTERPRET", "").strip().lower()
        if env:
            interpret = env not in ("0", "false", "no", "off")
        else:
            interpret = jax.default_backend() == "cpu"
        return quorum_commit_pallas(
            match_full, log.term, log.base, log.base_term, log.last,
            state_vec, cfg.majority, interpret)
    P = match_full.shape[1]
    if P == 3 and cfg.majority == 2:
        # 3-peer fast path: the quorum index is the median — three
        # min/max ops instead of a sort (the overwhelmingly common
        # cluster size; reference test clusters are all 3-node).
        a, b, c = match_full[:, 0], match_full[:, 1], match_full[:, 2]
        quorum_idx = jnp.maximum(jnp.minimum(a, b),
                                 jnp.minimum(jnp.maximum(a, b), c))
    else:
        sorted_m = jnp.sort(match_full, axis=1)
        quorum_idx = sorted_m[:, P - cfg.majority]
    can = can_lead & (quorum_idx > commit) & \
        (ring_term_at(log, quorum_idx) == term)
    return jnp.where(can, quorum_idx, commit)
