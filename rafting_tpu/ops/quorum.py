"""Pallas TPU kernel for the quorum-commit scan — the flagship hot op.

Computes, for every Raft group at once, the leader's commit advancement
(reference Leader.tryCommit + Leadership.majorIndices,
context/member/Leader.java:247-280, Leadership.java:116-130):

  1. quorum index = majority-order statistic of the (group x peer) match
     matrix (self slot pre-filled with the leader's own last index);
  2. the commit-only-own-term rule (Raft §5.4.2, Leader.java:256-261),
     reduced to ``quorum_idx >= own_from`` — terms are monotone along the
     log and ``own_from`` (RaftState) is the first index of the leader's
     current term, pinned at election win by the §8 no-op.  Round 4's
     kernel instead looked the term up in the ring with an O(L) unrolled
     select (fine at L=64, 4x the work at the tuned L=256 and pure
     overhead on every lane); the reduction deletes that loop AND the
     [L, G] ring transfer from the kernel entirely, and drops the
     dynamic ring gather from the inline path too;
  3. masked monotone update of commitIndex.

Layout: group-major arrays are reshaped to [rows, 128] so the group axis
rides the TPU lanes; the peer axis (3-9) is a static unroll of an
odd-even transposition sorting network on [rows, 128] tiles in VMEM.

``quorum_commit`` dispatches to the Pallas kernel or the pure-jnp
reference (identical semantics, parity-tested in tests/test_ops.py).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.types import I32

BLOCK_ROWS = 8          # sublanes per grid step
LANES = 128


# ---------------------------------------------------------------- reference --

def quorum_commit_ref(match_full: jax.Array, own_from, last, commit,
                      can_lead, majority: int) -> jax.Array:
    """Pure-jnp reference (exactly core/step.py phase 10).

    Two commit lanes, exactly the reference's tryCommit
    (Leader.java:256-261):

    * quorum lane — the majority order statistic, gated by the
      commit-only-own-term rule (``quorum_idx >= own_from``);
    * full-replication lane — the MINIMUM of the match row
      (Leader.java:260 ``fullIndex``): an entry replicated on EVERY node
      is identical on every node up to that index (matchIndex semantics),
      so any electable future leader already holds it — committing it
      needs no own-term fence.  This is what lets a fully-replicated
      prior-term suffix commit on a ring-full lane where the §8 no-op
      could not be appended (core/step.py phase 3 skips it at capacity).
    """
    P = match_full.shape[1]
    sorted_m = jnp.sort(match_full, axis=1)
    quorum_idx = sorted_m[:, P - majority]
    full_idx = sorted_m[:, 0]
    can = can_lead & (quorum_idx > commit) & \
        (quorum_idx >= own_from) & (quorum_idx <= last)
    can_full = can_lead & (full_idx > commit) & (full_idx <= last)
    return jnp.maximum(jnp.where(can, quorum_idx, commit),
                       jnp.where(can_full, full_idx, commit))


# ------------------------------------------------------------------- kernel --

def _kernel(P: int, majority: int,
            match_ref, own_from_ref, last_ref, commit_ref, lead_ref,
            out_ref):
    # Load the P match planes ([R, 128] tiles) and run an odd-even
    # transposition network; after P passes the planes are sorted
    # ascending, so plane P-majority is the quorum order statistic.
    planes = [match_ref[p] for p in range(P)]
    for _ in range(P):
        for i in range(0, P - 1, 2):
            lo = jnp.minimum(planes[i], planes[i + 1])
            hi = jnp.maximum(planes[i], planes[i + 1])
            planes[i], planes[i + 1] = lo, hi
        for i in range(1, P - 1, 2):
            lo = jnp.minimum(planes[i], planes[i + 1])
            hi = jnp.maximum(planes[i], planes[i + 1])
            planes[i], planes[i + 1] = lo, hi
    q = planes[P - majority]
    full = planes[0]   # minimum of the match row: the full-replication lane

    commit = commit_ref[...]
    last = last_ref[...]
    lead = lead_ref[...] != 0
    can = lead & (q > commit) & (q >= own_from_ref[...]) & (q <= last)
    can_full = lead & (full > commit) & (full <= last)
    out_ref[...] = jnp.maximum(jnp.where(can, q, commit),
                               jnp.where(can_full, full, commit))


def _pad_rows(a: np.ndarray | jax.Array, G: int, Gp: int, fill=0):
    if Gp == G:
        return a
    pad = [(0, Gp - G)] + [(0, 0)] * (a.ndim - 1)
    return jnp.pad(a, pad, constant_values=fill)


@functools.partial(jax.jit, static_argnums=(3, 4))
def quorum_commit_pallas(match_full, own_from, state_vec,
                         majority: int, interpret: bool = False
                         ) -> jax.Array:
    """Pallas path.  ``state_vec`` packs (commit, last, can_lead) as a
    [3, G] i32 array (can_lead nonzero = active leader lane)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    G, P = match_full.shape
    commit, last, can_lead = state_vec[0], state_vec[1], state_vec[2]

    step = BLOCK_ROWS * LANES
    Gp = (G + step - 1) // step * step
    R = Gp // LANES

    def rows(v, fill=0):
        return _pad_rows(v, G, Gp, fill).reshape(R, LANES)

    match_t = _pad_rows(match_full, G, Gp).T.reshape(P, R, LANES)

    grid = (R // BLOCK_ROWS,)
    vec = lambda: pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, 0))
    out = pl.pallas_call(
        functools.partial(_kernel, P, majority),
        out_shape=jax.ShapeDtypeStruct((R, LANES), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((P, BLOCK_ROWS, LANES), lambda i: (0, i, 0)),
            vec(), vec(), vec(), vec(),
        ],
        out_specs=vec(),
        interpret=interpret,
    )(match_t, rows(own_from, fill=1), rows(last), rows(commit),
      rows(can_lead))
    return out.reshape(Gp)[:G]


# ------------------------------------------------------------ read barrier --

def read_barrier_release(majority: int, read_evid, rq_stamp, rq_head,
                         rq_len, rq_n):
    """ReadIndex barrier for every group at once: how many pending read
    batches (FIFO from ``rq_head``) have a confirmed leadership quorum.

    A batch stamped at tick ``s`` releases once ``1 + #{p : read_evid[g, p]
    >= s} >= majority`` — the leader itself plus peers whose barrier
    evidence (core/step.py read-barrier phase: ack receipt tick under the
    lease, echoed send tick under strict ReadIndex) postdates the stamp.
    Release is prefix-monotone by construction — stamps increase along the
    FIFO and evidence is a per-peer maximum, so a releasable batch implies
    every older one is releasable — but the cumulative-AND guard below
    keeps FIFO order even if a caller hands in unordered stamps.

    Returns ``(n_rel [G] int32, n_served [G] int32)``: batches released
    and the total individual reads inside them.  This lives beside the
    commit kernel because it is the same shape of op — a quorum order
    statistic over the peer axis feeding a masked monotone update — and
    the Pallas treatment, if ever needed, would tile identically.
    """
    G, K = rq_stamp.shape
    j = jnp.arange(K, dtype=I32)[None, :]                       # FIFO pos
    slot = jnp.remainder(rq_head[:, None] + j, K)               # [G, K]
    st = jnp.take_along_axis(rq_stamp, slot, axis=1)
    n = jnp.take_along_axis(rq_n, slot, axis=1)
    pending = j < rq_len[:, None]
    # Evidence 0 means "none this leadership"; stamps are >= 1 (the tick
    # clock starts at 1), so the comparison needs no extra guard.
    peer_ok = read_evid[:, None, :] >= st[:, :, None]           # [G, K, P]
    cnt = 1 + peer_ok.sum(axis=2).astype(I32)                   # self counts
    ok = pending & (cnt >= majority)
    rel = pending & (jnp.cumsum((~ok).astype(I32), axis=1) == 0)
    return rel.sum(axis=1).astype(I32), (rel * n).sum(axis=1).astype(I32)


def quorum_commit(cfg, match_full, log, commit, own_from, can_lead):
    """Dispatch: Pallas when ``cfg.use_pallas``, else inline jnp (the
    default; both paths are semantically identical)."""
    if getattr(cfg, "use_pallas", False):
        import os
        state_vec = jnp.stack([commit, log.last, can_lead.astype(I32)])
        # Interpret only on the CPU backend; any accelerator attempts the
        # compiled lowering (an unsupported backend then fails LOUDLY
        # instead of silently running the interpreter at 1000x cost — the
        # trap a TPU-plugin-name allowlist would re-arm every time a
        # plugin registers under a new name, e.g. the bench host's 'axon').
        # RAFT_PALLAS_INTERPRET=0/1 overrides either way.
        env = os.environ.get("RAFT_PALLAS_INTERPRET", "").strip().lower()
        if env:
            interpret = env not in ("0", "false", "no", "off")
        else:
            interpret = jax.default_backend() == "cpu"
        return quorum_commit_pallas(
            match_full, own_from, state_vec, cfg.majority, interpret)
    P = match_full.shape[1]
    if P == 3 and cfg.majority == 2:
        # 3-peer fast path: the quorum index is the median — three
        # min/max ops instead of a sort (the overwhelmingly common
        # cluster size; reference test clusters are all 3-node).
        a, b, c = match_full[:, 0], match_full[:, 1], match_full[:, 2]
        quorum_idx = jnp.maximum(jnp.minimum(a, b),
                                 jnp.minimum(jnp.maximum(a, b), c))
        full_idx = jnp.minimum(jnp.minimum(a, b), c)
    else:
        sorted_m = jnp.sort(match_full, axis=1)
        quorum_idx = sorted_m[:, P - cfg.majority]
        full_idx = sorted_m[:, 0]
    can = can_lead & (quorum_idx > commit) & \
        (quorum_idx >= own_from) & (quorum_idx <= log.last)
    # Full-replication lane (reference Leader.java:260): min of the match
    # row commits with NO own-term fence — an all-nodes-replicated prefix
    # is on every future leader's log by construction.
    can_full = can_lead & (full_idx > commit) & (full_idx <= log.last)
    return jnp.maximum(jnp.where(can, quorum_idx, commit),
                       jnp.where(can_full, full_idx, commit))
