"""Host-side gray-failure scorecards: decayed per-peer and self health.

A *gray* failure is the one the binary health checks miss: the node is
up, the sockets connect, but one direction of a link is dead, a disk
fsyncs at 100x its usual latency, or the process is so overloaded its
acks crawl.  The device tier's CheckQuorum (core/step.py phase 6c)
handles the acute case — a leader that cannot HEAR a voter quorum steps
down.  This registry is the chronic case's bookkeeping: it folds the
signals the runtime already collects into per-peer and self scores, so
the node can *proactively evacuate* leadership off itself while it is
merely degraded, before it becomes the fleet's slowest quorum member —
and never INTO a peer that looks worse.

Inputs (all already collected elsewhere; this module only folds):

* per-peer hop-segment histograms (utils/latency.py HopTracer): the
  ``hop_{wire,follower_fsync,ack_return}_p{p}_s`` windowed deltas — a
  peer whose delta-window p50 sits far above the fleet median is slow
  in a way aggregate percentiles hide;
* the storage-fault plane (runtime/node.py): quarantined WAL stripes,
  ENOSPC backpressure, the slow-I/O watchdog — *self* signals;
* the transport's ``reconnects_total`` counter — a flapping link is a
  self signal too (every peer shares this node's NIC);
* the admission controller's shed level (runtime/admission.py);
* CheckQuorum contact lanes (core/types.py QuorumContact), drained at
  an admin cadence: the per-peer last-heard tick feeds the scorecard's
  ``last_contact`` column and a stale-contact penalty.

Scores DECAY (half-life in ticks, the utils/heat.py discipline): a
healed peer's score melts back to 0 instead of branding it forever.
0 = healthy; ``degraded_after`` and up = degraded.  numpy + stdlib
only, single-writer ``ingest`` on the tick thread, HTTP-safe
``snapshot`` — the same relaxed-read contract as /metrics.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

# Hop segments that indict a PEER (leader_pack is our own packing time;
# quorum_wait blames the quorum, not one peer).
PEER_SEGMENTS = ("wire", "follower_fsync", "ack_return")


def _delta_quantile(bounds: List[float], delta: List[int],
                    q: float) -> float:
    """Upper-bound quantile over a windowed bucket-count delta (the
    delta analog of utils/metrics.Histogram.quantile — conservative,
    returns the bucket's upper bound)."""
    n = sum(delta)
    if n <= 0:
        return 0.0
    target = q * n
    seen = 0
    for i, c in enumerate(delta):
        seen += c
        if seen >= target:
            return bounds[i] if i < len(bounds) else bounds[-1] * 2
    return bounds[-1] * 2


class HealthRegistry:
    """Decayed per-peer + self health scores from runtime signals.

    ``ingest`` runs once per tick on the tick thread; everything it
    reads from the metrics registry is reader-safe (atomic list
    snapshots of histogram counts).  Scores are penalties: 0 is
    healthy, ``degraded_after`` is the evacuation/avoidance threshold.
    """

    def __init__(self, n_peers: int, node_id: int,
                 half_life_ticks: float = 256.0,
                 degraded_after: float = 4.0,
                 min_window_samples: int = 8,
                 slow_ratio: float = 4.0,
                 contact_stale_ticks: int = 64):
        self.n_peers = int(n_peers)
        self.node_id = int(node_id)
        self.half_life = float(half_life_ticks)
        self.degraded_after = float(degraded_after)
        self.min_window_samples = int(min_window_samples)
        self.slow_ratio = float(slow_ratio)
        self.contact_stale_ticks = int(contact_stale_ticks)
        self.score = np.zeros(self.n_peers, np.float64)
        self.self_score = 0.0
        # Own-clock tick each peer was last heard from (-1 = never /
        # unknown; fed from the CheckQuorum contact lanes when the
        # engine carries them).
        self.last_contact = np.full(self.n_peers, -1, np.int64)
        self.last_contact[self.node_id] = 0
        self.tick = 0
        self._score_tick = 0
        # Previous cumulative bucket counts per (segment, peer) — the
        # window baseline for delta quantiles.
        self._prev_counts: Dict[tuple, List[int]] = {}
        self._prev_poisoned = 0
        self._prev_reconnects = 0.0
        # Evacuation audit (appended by the node when it evacuates).
        self.evacuations = 0
        self.recent_evacuations: List[dict] = []
        # Score timeline ring: one decayed sample every
        # ``sample_every`` ingests, capped — the post-mortem CLI
        # (tools/health_report.py) plots these next to the evacuation
        # audit to show WHEN a node went gray, not just that it did.
        self.sample_every = 16
        self.history: List[dict] = []
        self._hist_next = 0

    # ------------------------------------------------------------ ingest

    def _decay(self, tick: int) -> None:
        dt = tick - self._score_tick
        if dt > 0:
            f = 0.5 ** (dt / self.half_life)
            self.score *= f
            self.self_score *= f
            self._score_tick = tick

    def _peer_window_p50(self, metrics, seg: str, p: int) -> tuple:
        """(windowed delta p50 seconds, delta sample count) for one
        peer's hop segment since the last ingest."""
        h = metrics._histograms.get(f"hop_{seg}_p{p}_s")
        if h is None:
            return 0.0, 0
        cur = list(h.counts)
        prev = self._prev_counts.get((seg, p))
        self._prev_counts[(seg, p)] = cur
        if prev is None or len(prev) != len(cur):
            return 0.0, 0
        delta = [c - q for c, q in zip(cur, prev)]
        n = sum(delta)
        if n < self.min_window_samples:
            return 0.0, n
        return _delta_quantile(h.bounds, delta, 0.5), n

    def ingest(self, tick: int, metrics, *,
               io_slow: bool = False, poisoned_stripes: int = 0,
               backpressure: bool = False, admission_level: float = 0.0
               ) -> None:
        """Fold one tick's signals.  Tick thread only."""
        self.tick = int(tick)
        self._decay(self.tick)
        # -- peers: relative hop-segment slowness -----------------------
        for seg in PEER_SEGMENTS:
            p50s = {}
            for p in range(self.n_peers):
                if p == self.node_id:
                    continue
                v, n = self._peer_window_p50(metrics, seg, p)
                if n >= self.min_window_samples:
                    p50s[p] = v
            if len(p50s) < 2:
                continue   # no fleet to compare against
            med = float(np.median(list(p50s.values())))
            if med <= 0.0:
                continue
            for p, v in p50s.items():
                ratio = v / med
                if ratio >= self.slow_ratio:
                    # Penalty grows with how far past the threshold the
                    # peer sits, capped so one wild window cannot brand
                    # a peer past any realistic decay horizon.
                    self.score[p] += min(ratio / self.slow_ratio, 4.0)
        # -- peers: stale contact (CheckQuorum lanes, when fed) ---------
        heard = self.last_contact
        for p in range(self.n_peers):
            if p == self.node_id or heard[p] < 0:
                continue
            if self.tick - heard[p] > self.contact_stale_ticks:
                self.score[p] += 1.0
        # -- self -------------------------------------------------------
        if io_slow:
            self.self_score += 1.0
        if backpressure:
            self.self_score += 1.0
        new_poison = max(0, int(poisoned_stripes) - self._prev_poisoned)
        self._prev_poisoned = max(self._prev_poisoned,
                                  int(poisoned_stripes))
        if new_poison:
            self.self_score += 2.0 * new_poison
        rec = float(metrics._counters.get("reconnects_total", 0.0))
        d_rec = rec - self._prev_reconnects
        self._prev_reconnects = rec
        if d_rec > 0:
            self.self_score += 0.5 * d_rec
        if admission_level > 0.0:
            self.self_score += float(admission_level)
        # -- timeline sample --------------------------------------------
        if self.tick >= self._hist_next:
            self._hist_next = self.tick + self.sample_every
            self.history.append({
                "tick": self.tick,
                "self": round(self.self_score, 3),
                "peers": [round(float(s), 3) for s in self.score],
            })
            del self.history[:-256]

    def note_contact(self, heard_ticks: np.ndarray) -> None:
        """Fold the device contact lanes' per-peer max-over-groups
        last-heard ticks ([P] int32, own engine clock; 0 = never).
        Tick thread only, admin cadence."""
        h = np.asarray(heard_ticks, np.int64)
        upd = h > 0
        self.last_contact[upd] = np.maximum(self.last_contact[upd], h[upd])

    def note_evacuation(self, group: int, target: int) -> None:
        self.evacuations += 1
        self.recent_evacuations.append(
            {"tick": self.tick, "group": int(group), "target": int(target)})
        del self.recent_evacuations[:-32]

    # ----------------------------------------------------------- queries

    def _decayed(self, v: float) -> float:
        return v * 0.5 ** (max(self.tick - self._score_tick, 0)
                           / self.half_life)

    def degraded_peers(self) -> set:
        thr = self.degraded_after
        return {int(p) for p in range(self.n_peers)
                if p != self.node_id
                and self._decayed(float(self.score[p])) >= thr}

    def self_degraded(self) -> bool:
        return self._decayed(self.self_score) >= self.degraded_after

    def snapshot(self) -> dict:
        """The /healthz ``peers`` block: scores, last contact ages,
        degraded flags, evacuation audit."""
        peers = []
        for p in range(self.n_peers):
            lc = int(self.last_contact[p])
            peers.append({
                "peer": p,
                "self": p == self.node_id,
                "score": round(self._decayed(float(self.score[p])), 3),
                "degraded": (p != self.node_id
                             and p in self.degraded_peers()),
                "last_contact_tick": lc if lc >= 0 else None,
                "contact_age_ticks": (int(self.tick - lc)
                                      if 0 <= lc else None),
            })
        return {
            "tick": self.tick,
            "half_life_ticks": self.half_life,
            "degraded_after": self.degraded_after,
            "self_score": round(self._decayed(self.self_score), 3),
            "self_degraded": self.self_degraded(),
            "peers": peers,
            "evacuations": self.evacuations,
            "recent_evacuations": list(self.recent_evacuations),
            "timeline": list(self.history),
        }


def health_from_env(n_peers: int, node_id: int
                    ) -> Optional[HealthRegistry]:
    """Build the node's health registry from the environment (default
    on; RAFT_HEALTH=0 disables).  Tunables: RAFT_HEALTH_HALF_LIFE
    (ticks, 256), RAFT_HEALTH_DEGRADED (score threshold, 4.0),
    RAFT_HEALTH_SLOW_RATIO (peer p50 / fleet median, 4.0),
    RAFT_HEALTH_STALE_TICKS (contact age penalty threshold, 64)."""
    import os

    raw = os.environ.get("RAFT_HEALTH", "").strip().lower()
    if raw in ("0", "false", "no", "off"):
        return None
    half = float(os.environ.get("RAFT_HEALTH_HALF_LIFE", "256"))
    thr = float(os.environ.get("RAFT_HEALTH_DEGRADED", "4"))
    ratio = float(os.environ.get("RAFT_HEALTH_SLOW_RATIO", "4"))
    stale = int(os.environ.get("RAFT_HEALTH_STALE_TICKS", "64"))
    return HealthRegistry(n_peers, node_id,
                          half_life_ticks=max(half, 1.0),
                          degraded_after=max(thr, 0.5),
                          slow_ratio=max(ratio, 1.5),
                          contact_stale_ticks=max(stale, 1))
