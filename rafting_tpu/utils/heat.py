"""Host-side decaying heat registry over the device heat lanes.

The device engine accumulates cumulative per-group activity counters
(core/types.py HeatState: entries appended, RPCs sent, commit advance,
reads served) when ``cfg.heat`` is on; the runtime drains them once per
tick and feeds the deltas here.  The registry keeps:

* a per-group exponentially-decaying **work score** (half-life in ticks)
  over client-driven work — appended + commits + reads.  ``sent`` is
  tracked but EXCLUDED from the score on purpose: heartbeats and vote
  traffic touch every group at cadence, so a score that counted RPCs
  would declare the whole idle fleet hot;
* the **last-active tick** per group, giving the idleness-age
  distribution;
* the **active-set size**: groups with client work inside the trailing
  window — the direct proof metric for the sparse-tick refactor
  (ROADMAP item 2: commit latency should track this gauge, not G).

numpy + stdlib only (like utils/tracelog.py) so post-mortem tooling can
load dumps without the engine.  Single-writer: ``ingest`` runs on the
tick thread only; ``snapshot`` is read-only over arrays that are
replaced, not resized, so serving it from an HTTP thread is safe under
the same relaxed contract as /metrics.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

LANES = ("appended", "sent", "commits", "reads")

# Idleness-age histogram bucket upper bounds, in ticks (powers of two);
# the last bucket is open-ended and a "never" lane counts groups with no
# client work since boot.
IDLE_BUCKETS = tuple(1 << i for i in range(13))   # 1 .. 4096 ticks


class HeatRegistry:
    """Decaying per-group heat from the drained device heat lanes."""

    def __init__(self, n_groups: int, half_life_ticks: float = 64.0,
                 active_window_ticks: int = 64):
        self.n_groups = int(n_groups)
        self.half_life = float(half_life_ticks)
        self.window = int(active_window_ticks)
        # Last drained cumulative device counters, one row per lane.
        self._cum = np.zeros((len(LANES), self.n_groups), np.int64)
        self.totals = np.zeros(len(LANES), np.int64)
        self.score = np.zeros(self.n_groups, np.float64)
        self.last_active = np.full(self.n_groups, -1, np.int64)
        self._score_tick = 0
        self.tick = 0

    # ------------------------------------------------------------ ingest

    def ingest(self, tick: int, appended: np.ndarray, sent: np.ndarray,
               commits: np.ndarray, reads: np.ndarray) -> tuple:
        """Fold one tick's cumulative lanes; returns the per-lane delta
        sums ``(appended, sent, commits, reads)`` for the metrics fold.
        Tick thread only."""
        cur = np.stack([
            np.asarray(appended, np.int64), np.asarray(sent, np.int64),
            np.asarray(commits, np.int64), np.asarray(reads, np.int64)])
        delta = cur - self._cum
        self._cum = cur
        lane_sums = delta.sum(axis=1)
        self.totals += lane_sums
        self.tick = int(tick)
        work = delta[0] + delta[2] + delta[3]
        if work.any():
            dt = self.tick - self._score_tick
            if dt > 0:
                self.score *= 0.5 ** (dt / self.half_life)
                self._score_tick = self.tick
            self.score += work
            self.last_active[work > 0] = self.tick
        return tuple(int(v) for v in lane_sums)

    def reset_group(self, g: int) -> None:
        """Purged lane: the device counters restart at 0 — the mirror
        must too, or the next drain folds a negative delta."""
        self._cum[:, g] = 0
        self.score[g] = 0.0
        self.last_active[g] = -1

    # ---------------------------------------------------------- queries

    def active_set_size(self) -> int:
        """Groups with client work inside the trailing window."""
        ever = self.last_active >= 0
        return int((ever & (self.tick - self.last_active
                            <= self.window)).sum())

    def top_k(self, k: int) -> list:
        """The k hottest groups by decayed work score (score > 0 only),
        hottest first, with their cumulative lane counters."""
        k = max(0, min(int(k), self.n_groups))
        order = np.argsort(-self.score, kind="stable")[:k]
        decay = 0.5 ** (max(self.tick - self._score_tick, 0)
                        / self.half_life)
        out = []
        for g in order.tolist():
            if self.score[g] <= 0.0:
                break
            out.append({
                "group": int(g),
                "score": round(float(self.score[g] * decay), 3),
                "appended": int(self._cum[0, g]),
                "sent": int(self._cum[1, g]),
                "commits": int(self._cum[2, g]),
                "reads": int(self._cum[3, g]),
                "idle_ticks": int(self.tick - self.last_active[g]),
            })
        return out

    def idleness_histogram(self) -> dict:
        """Idleness-age distribution over groups that ever saw client
        work, plus the never-active count."""
        ever = self.last_active >= 0
        ages = (self.tick - self.last_active[ever]).astype(np.int64)
        bounds = np.asarray(IDLE_BUCKETS, np.int64)
        counts = np.zeros(len(IDLE_BUCKETS) + 1, np.int64)
        if len(ages):
            counts[:-1] = (ages[None, :] <= bounds[:, None]).sum(axis=1)
            counts[-1] = len(ages)
            # Cumulative -> per-bucket.
            counts[1:] = np.diff(counts)
        return {
            "le_ticks": [int(b) for b in IDLE_BUCKETS] + ["inf"],
            "counts": counts.tolist(),
            "never_active": int((~ever).sum()),
        }

    def snapshot(self, k: int = 16) -> dict:
        """The /heatmap document."""
        return {
            "tick": self.tick,
            "half_life_ticks": self.half_life,
            "window_ticks": self.window,
            "active_set": self.active_set_size(),
            "groups": self.n_groups,
            "totals": {name: int(v)
                       for name, v in zip(LANES, self.totals)},
            "top": self.top_k(k),
            "idleness": self.idleness_histogram(),
        }


def heat_registry_from_env(n_groups: int) -> HeatRegistry:
    """Build a registry with env-tunable decay/window:
    RAFT_HEAT_HALF_LIFE (ticks, default 64) and RAFT_HEAT_WINDOW
    (ticks, default 64)."""
    import os

    half = float(os.environ.get("RAFT_HEAT_HALF_LIFE", "64"))
    window = int(os.environ.get("RAFT_HEAT_WINDOW", "64"))
    return HeatRegistry(n_groups, half_life_ticks=max(half, 1.0),
                        active_window_ticks=max(window, 1))
