"""Flight-recorder decoding: device event rings -> structured timelines.

The device engine writes per-group event rings (core/types.py TraceState,
emitted branchlessly at core/step.py phase boundaries).  This module is
the host half: a stateless decoder for raw rings (post-mortem dumps, the
tools/dump_timeline.py CLI) and an incremental ``TraceLog`` accumulator
the node runtime drains each tick — turning device events into per-group
timelines plus *labeled* metrics the aggregate counters cannot express
(elections by cause, leader churn per group), the per-replica timeline
currency "Paxos vs Raft" (arxiv 2004.05074) identifies as the real
consensus-debugging need.

Dependency-free on purpose (numpy + stdlib json), like utils/metrics.py:
the decoder must work in a post-mortem context with no engine import.
This module therefore OWNS the event-kind taxonomy (core/types.py imports
it back for the kernel), imports nothing from the engine, and
tools/dump_timeline.py loads it by file path — a box with only
numpy + stdlib can decode dumps.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from typing import Dict, List, Optional

import numpy as np

# Flight-recorder event kinds (the device kernel in core/step.py emits,
# testkit/oracle.py mirrors; see core/types.py for the canonical
# intra-tick emission order and per-kind aux payloads).
TR_TERM_BUMP = 1
TR_STEPPED_DOWN = 2
TR_BECAME_PRE_CANDIDATE = 3
TR_BECAME_CANDIDATE = 4
TR_BECAME_LEADER = 5
TR_SNAPSHOT_INSTALL = 6
TR_COMMIT_ADVANCE = 7
TR_READ_RELEASE = 8
TR_CRASH_RESTART = 9
# Membership plane (Raft §6 joint consensus + §3.10 leadership transfer).
# CONF_CHANGE_ENTER fires whenever the group's ACTIVE config changes —
# enter-joint, auto-leave, learner-set change, follower adoption, and
# truncation rollback all count; aux = the new packed config word.
# CONF_CHANGE_COMMIT fires when the commit index first covers the active
# config entry; aux = that entry's log index.  LEADER_TRANSFER fires on
# the leader the tick it sends TimeoutNow; aux = the target peer.
TR_CONF_CHANGE_ENTER = 10
TR_CONF_CHANGE_COMMIT = 11
TR_LEADER_TRANSFER = 12

# Packed-config-word layout (§6 membership plane).  OWNED here like the
# event taxonomy — the decoder must unpack config words with no engine
# import, and core/types.py imports these back for the kernel, so both
# sides share one definition.
CONF_MASK_BITS = 10
CONF_MASK = (1 << CONF_MASK_BITS) - 1
CONF_NEW_SHIFT = CONF_MASK_BITS
CONF_LRN_SHIFT = 2 * CONF_MASK_BITS
CONF_FLAG = 1 << 30

TRACE_EVENTS = {
    TR_TERM_BUMP: "TERM_BUMP",
    TR_STEPPED_DOWN: "STEPPED_DOWN",
    TR_BECAME_PRE_CANDIDATE: "BECAME_PRE_CANDIDATE",
    TR_BECAME_CANDIDATE: "BECAME_CANDIDATE",
    TR_BECAME_LEADER: "BECAME_LEADER",
    TR_SNAPSHOT_INSTALL: "SNAPSHOT_INSTALL",
    TR_COMMIT_ADVANCE: "COMMIT_ADVANCE",
    TR_READ_RELEASE: "READ_RELEASE",
    TR_CRASH_RESTART: "CRASH_RESTART",
    TR_CONF_CHANGE_ENTER: "CONF_CHANGE_ENTER",
    TR_CONF_CHANGE_COMMIT: "CONF_CHANGE_COMMIT",
    TR_LEADER_TRANSFER: "LEADER_TRANSFER",
}

__all__ = ["TraceEvent", "TraceLog", "decode_group", "trace_to_numpy",
           "save_dump", "load_dump", "TRACE_EVENTS",
           "TR_TERM_BUMP", "TR_STEPPED_DOWN", "TR_BECAME_PRE_CANDIDATE",
           "TR_BECAME_CANDIDATE", "TR_BECAME_LEADER", "TR_SNAPSHOT_INSTALL",
           "TR_COMMIT_ADVANCE", "TR_READ_RELEASE", "TR_CRASH_RESTART",
           "TR_CONF_CHANGE_ENTER", "TR_CONF_CHANGE_COMMIT",
           "TR_LEADER_TRANSFER"]


class TraceEvent(dict):
    """One decoded event word: {seq, tick, event, kind, term, aux}.

    A dict subclass so timelines serialize to JSON as-is (HTTP timeline
    endpoint, dump CLI) while still reading naturally in test code."""

    __slots__ = ()

    @classmethod
    def make(cls, seq: int, tick: int, kind: int, term: int,
             aux: int) -> "TraceEvent":
        return cls(seq=seq, tick=tick, kind=kind,
                   event=TRACE_EVENTS.get(kind, f"UNKNOWN_{kind}"),
                   term=term, aux=aux)


def trace_to_numpy(trace) -> Dict[str, np.ndarray]:
    """Pull a TraceState (device or host, [G, D] or stacked [N, G, D])
    into plain numpy arrays keyed by lane name."""
    return {name: np.asarray(getattr(trace, name))
            for name in ("tick", "kind", "term", "aux", "n")}


def decode_group(lanes: Dict[str, np.ndarray], g: int, since: int = 0,
                 node: Optional[int] = None):
    """Decode one group's ring into ``(events, dropped)``.

    ``lanes`` is a ``trace_to_numpy`` dict (2-D [G, D] lanes, or 3-D
    [N, G, D] stacked — then ``node`` selects the node).  ``since`` is
    the caller's drained-through event count: only events with sequence
    >= ``since`` are returned, and ``dropped`` counts events the ring
    overwrote before they could be read (n - since > depth)."""
    idx = (g,) if lanes["n"].ndim == 1 else ((0 if node is None else node), g)
    n = int(lanes["n"][idx])
    tick, kind = lanes["tick"][idx], lanes["kind"][idx]
    term, aux = lanes["term"][idx], lanes["aux"][idx]
    D = tick.shape[0]
    first = max(since, n - D)
    dropped = first - since
    events = [TraceEvent.make(seq, int(tick[seq % D]), int(kind[seq % D]),
                              int(term[seq % D]), int(aux[seq % D]))
              for seq in range(first, n)]
    return events, dropped


class TraceLog:
    """Incremental host accumulator over repeated ring drains.

    ``ingest`` is called with the freshly pulled lanes each sync; it
    appends only the NEW events per group (tracked by the drained-through
    count), keeps a bounded per-group timeline, and returns the tick's
    labeled-metric deltas so the caller can fold them into its Metrics
    registry:

    * ``elections_won``            — BECAME_LEADER events
    * ``elections_cause_timer``    — candidacies from timer expiry
    * ``elections_cause_prevote``  — candidacies from a PreVote majority
    * ``leader_churn``             — leadership changes past each group's
                                     first election (the stability signal)
    * ``elections_cause_transfer`` — candidacies from TimeoutNow (§3.10
                                     leadership transfer)
    * ``crash_restarts``           — in-scan crash-restart events
    * ``conf_changes_entered``     — active-config changes (ENTER events)
    * ``conf_changes_committed``   — config entries whose commit landed
    * ``leader_transfers``         — TimeoutNow sends (LEADER_TRANSFER)
    * ``trace_events``             — everything decoded this drain
    * ``trace_dropped``            — events the ring overwrote undrained
    """

    def __init__(self, cfg, timeline_cap: int = 256):
        self.depth = int(cfg.trace_depth)
        self.timeline_cap = timeline_cap
        self._seen = np.zeros(cfg.n_groups, np.int64)
        self._timelines: Dict[int, deque] = {}
        self._led_before = np.zeros(cfg.n_groups, bool)
        self.dropped_total = 0
        # ingest runs on the tick thread; timeline() is read by HTTP
        # handler threads (runtime/obsrv.py) — a lock keeps a scrape from
        # observing a deque mid-mutation.
        self._lock = threading.Lock()

    def moved(self, n_lane) -> bool:
        """Cheap pre-drain check against just the [G] event-count lane:
        lets the runtime skip pulling the full rings on quiet ticks."""
        return bool((np.asarray(n_lane).astype(np.int64)
                     > self._seen).any())

    def ingest(self, trace) -> Dict[str, int]:
        if trace is None or self.depth == 0:
            return {}
        with self._lock:
            return self._ingest(trace)

    def _ingest(self, trace) -> Dict[str, int]:
        lanes = trace_to_numpy(trace)
        deltas = {"elections_won": 0, "elections_cause_timer": 0,
                  "elections_cause_prevote": 0,
                  "elections_cause_transfer": 0, "leader_churn": 0,
                  "crash_restarts": 0, "conf_changes_entered": 0,
                  "conf_changes_committed": 0, "leader_transfers": 0,
                  "trace_events": 0, "trace_dropped": 0}
        moved = np.nonzero(lanes["n"].astype(np.int64) > self._seen)[0]
        for g in moved.tolist():
            events, dropped = decode_group(lanes, g,
                                           since=int(self._seen[g]))
            self._seen[g] = int(lanes["n"][g])
            deltas["trace_dropped"] += dropped
            deltas["trace_events"] += len(events)
            tl = self._timelines.get(g)
            if tl is None:
                tl = self._timelines[g] = deque(maxlen=self.timeline_cap)
            for ev in events:
                tl.append(ev)
                k = ev["kind"]
                if k == TR_BECAME_LEADER:
                    deltas["elections_won"] += 1
                    if self._led_before[g]:
                        deltas["leader_churn"] += 1
                    self._led_before[g] = True
                elif k == TR_BECAME_CANDIDATE:
                    # aux: 0 = PreVote majority, 1 = timer expiry,
                    # 2 = TimeoutNow (leadership transfer).
                    cause = ("elections_cause_prevote",
                             "elections_cause_timer",
                             "elections_cause_transfer")[min(ev["aux"], 2)]
                    deltas[cause] += 1
                elif k == TR_CRASH_RESTART:
                    deltas["crash_restarts"] += 1
                elif k == TR_CONF_CHANGE_ENTER:
                    deltas["conf_changes_entered"] += 1
                elif k == TR_CONF_CHANGE_COMMIT:
                    deltas["conf_changes_committed"] += 1
                elif k == TR_LEADER_TRANSFER:
                    deltas["leader_transfers"] += 1
        self.dropped_total += deltas["trace_dropped"]
        return deltas

    def timeline(self, g: int) -> List[TraceEvent]:
        with self._lock:
            return list(self._timelines.get(g, ()))

    def reset_group(self, g: int) -> None:
        """Lane purge support: a destroyed lane's recorder restarts from
        event 0 (the runtime zeroes the device ring with the lane)."""
        with self._lock:
            self._seen[g] = 0
            self._timelines.pop(g, None)
            self._led_before[g] = False


def format_aux(kind: int, aux: int) -> str:
    """Human rendering of an event's aux payload (decoder-owned, like the
    taxonomy itself): config words decode into voter/new/learner masks,
    candidacy causes into names — everything else prints raw."""
    if kind == TR_CONF_CHANGE_ENTER:
        v = aux & CONF_MASK
        n = (aux >> CONF_NEW_SHIFT) & CONF_MASK
        l = (aux >> CONF_LRN_SHIFT) & CONF_MASK
        s = f"voters={v:b}"
        if n:
            s += f" new={n:b}"
        if l:
            s += f" learners={l:b}"
        return s
    if kind == TR_BECAME_CANDIDATE:
        return ("prevote", "timer", "timeout_now")[min(int(aux), 2)]
    return str(aux)


# ------------------------------------------------------------------ dumps --

def _open_dump(path: str, mode: str = "rt"):
    """Gzip-transparent artifact open: a ``.gz`` path (de)compresses,
    and a bare path being READ falls back to its ``.gz`` sibling when
    only the compressed form exists on disk."""
    import gzip
    import os
    if path.endswith(".gz"):
        return gzip.open(path, mode)
    if "r" in mode and not os.path.exists(path) \
            and os.path.exists(path + ".gz"):
        return gzip.open(path + ".gz", mode)
    return open(path, mode)


def save_dump(path: str, trace, meta: Optional[dict] = None) -> None:
    """Persist raw rings as a JSON artifact for post-mortem decoding
    (tools/dump_timeline.py).  Accepts a TraceState ([G, D] single node or
    [N, G, D] stacked cluster) or a ``trace_to_numpy`` dict.  A ``.gz``
    path writes gzip-compressed (ring dumps are big and repetitive)."""
    lanes = trace if isinstance(trace, dict) else trace_to_numpy(trace)
    doc = {name: np.asarray(arr).tolist() for name, arr in lanes.items()}
    doc["_meta"] = dict(meta or {})
    with _open_dump(path, "wt") as f:
        json.dump(doc, f)


def load_dump(path: str) -> Dict[str, np.ndarray]:
    with _open_dump(path) as f:
        doc = json.load(f)
    return {name: np.asarray(doc[name], np.int64)
            for name in ("tick", "kind", "term", "aux", "n")}
