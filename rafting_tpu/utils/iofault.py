"""Process-wide I/O fault hook for the host storage tier.

The WAL engines carry their own per-engine fault tables (native: the
fault fields in ``struct Wal``; Python: ``PyWal._faults``) because the
hot path must not pay a Python call per record.  The *cold* storage
paths — ConfMeta flush, snapshot-archive copy/fsync — instead consult
this module-level hook, which a test (typically via
``testkit.faultfs``) installs for the duration of a scenario.

The hook is a callable ``hook(op: str, path: str) -> None`` that may:

* return normally            — no fault;
* raise ``OSError``          — injected failure (errno chosen by the
                               scheduler, e.g. EIO / ENOSPC);
* raise ``TornWrite(keep=n)``— the caller should persist only the
                               first ``n`` bytes, then fail;
* ``time.sleep``             — injected slow-I/O (gray failure).

Op names in use: ``"conf.flush"``, ``"archive.write"``,
``"archive.fsync"``.  Production runs never install a hook, so
``check`` is a single global load + ``is None`` test.
"""

from __future__ import annotations

import errno
from typing import Callable, Optional

Hook = Callable[[str, str], None]

_hook: Optional[Hook] = None


class TornWrite(OSError):
    """Injected short write: persist only the first ``keep`` bytes of the
    staged buffer, then fail the operation (simulates a crash/medium
    error mid-write).  Callers that cannot honor partial persistence
    treat it as a plain I/O error."""

    def __init__(self, keep: int = 0):
        super().__init__(errno.EIO, f"injected torn write (keep={keep})")
        self.keep = keep


def install(hook: Hook) -> Optional[Hook]:
    """Install ``hook`` process-wide; returns the previous hook so tests
    can nest/restore."""
    global _hook
    prev = _hook
    _hook = hook
    return prev


def uninstall() -> None:
    global _hook
    _hook = None


def installed() -> bool:
    return _hook is not None


def check(op: str, path: str) -> None:
    """Consult the hook (no-op when none installed)."""
    h = _hook
    if h is not None:
        h(op, path)
