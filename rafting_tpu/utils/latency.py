"""Per-entry commit-path latency tracing (the sampled span plane).

Every signal the runtime exported before this module was per-tick: the
stage histograms and the flight recorder measure what a *tick* costs,
never what one *command* experienced from submit to ack.  CD-Raft
(arXiv:2603.10555) and "Paxos vs Raft" (arXiv:2004.05074) both frame
consensus quality as end-to-end commit latency — and the ROADMAP's
"millions of users" claim is a p999 claim, so the runtime needs to know
where the microseconds go per entry, not per tick.

Design:

* **Sampling** is a seeded stride: submission seq ``s`` is sampled iff
  ``(s + seed % rate) % rate == 0`` (~1/rate of submits).  The sampled
  SET is a pure function of (seed, rate) — same seed, same set — and
  membership of a contiguous seq range [s0, s0+n) is O(1) arithmetic
  (``first_in``), so the 100k-group fan-out path never loops to decide.
  ``rate=0`` disables the plane entirely: the node holds no tracer and
  every hot-path hook is one attribute-is-None check.
* **Spans** stamp wall-clock marks through the commit path:
  ``submitted → offered → staged → fsynced → sent → committed →
  applied → acked`` (writes) and ``submitted → served`` (reads).  A
  span that dies before its ack — leadership loss, storage fault, lane
  close — retires with ``outcome-unknown`` (or ``refused`` for marked
  pre-log refusals) and contributes NO latency sample: a crashed span
  must never fabricate a latency.
* **Rings**: spans retire into per-thread ring buffers (client threads,
  stripe workers, the tick thread each own one deque; registration of
  a new ring takes the only lock in the retire path).  The tick thread
  merges rings at :meth:`harvest` and is the sole writer of the shared
  histograms — the registry keeps its single-writer contract (see
  utils/metrics.py) with W striped workers in play.
* **Admission** is bounded (``max_live``): the sampler's *selection* is
  deterministic, but at most ``max_live`` spans are in flight at once —
  overflow candidates are counted (``span_overflow``), not traced, so
  a 100k-group burst cannot turn the trace plane into the workload.

Histograms land in the node's Metrics registry as ``lat_<pair>_s``
per phase pair plus ``lat_e2e_s`` / ``lat_read_e2e_s`` end-to-end, so
/metrics exposition and /latency percentiles come from one source.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

# Phase indices (Span.t slots).
SUBMITTED, OFFERED, STAGED, FSYNCED, SENT, COMMITTED, APPLIED, ACKED, \
    SERVED = range(9)

PHASE_NAMES = ("submitted", "offered", "staged", "fsynced", "sent",
               "committed", "applied", "acked", "served")

# Adjacent phase pairs reported as histograms (writes).  Summing these
# medians ≈ the e2e median on an idle cluster (the reconciliation the
# acceptance criteria check).
PHASE_PAIRS = (
    ("submit_offer", SUBMITTED, OFFERED),
    ("offer_stage", OFFERED, STAGED),
    ("stage_fsync", STAGED, FSYNCED),
    ("fsync_send", FSYNCED, SENT),
    ("send_commit", SENT, COMMITTED),
    ("commit_apply", COMMITTED, APPLIED),
    ("apply_ack", APPLIED, ACKED),
)

# Transaction phase indices (TxnSpan.t slots) — the 2PC lifecycle the
# txn plane (runtime/txn.py) stamps per sampled transaction.
T_BEGIN, T_PREPARED, T_DECIDED, T_APPLIED, T_ACKED = range(5)

TXN_PHASE_NAMES = ("begin", "prepared", "decided", "applied", "acked")

TXN_PHASE_PAIRS = (
    ("begin_prepare", T_BEGIN, T_PREPARED),    # begin replicated + all
    #                                            participant PREPAREs acked
    ("prepare_decide", T_PREPARED, T_DECIDED),  # decision replicated in
    #                                            the coordinator group
    ("decide_apply", T_DECIDED, T_APPLIED),    # commit/abort fan-out
    ("apply_ack", T_APPLIED, T_ACKED),         # result handed to caller
)


class Span:
    """One sampled entry's lifecycle record.  Mutated by whichever
    thread reaches the stamp site; each slot has exactly one writer per
    lifecycle (the stamp sites are ordered by the commit protocol), so
    no locking — a torn read can only be observed by the harvester for
    an outcome-unknown span, which reports no latency anyway."""

    __slots__ = ("seq", "kind", "k", "group", "idx", "tick", "t",
                 "outcome", "tr")

    def __init__(self, seq: int, kind: str, k: int):
        self.seq = seq
        self.kind = kind          # "w" (write) | "r" (read)
        self.k = k                # entry offset within its batch
        self.group = -1
        self.idx = -1             # log index (writes; stamped at offer)
        self.tick = -1            # node tick at device accept — the
        #                           shared axis flight-recorder events
        #                           and worker-util intervals plot on
        self.t = [0.0] * 9
        self.outcome: Optional[str] = None   # None=in flight, "ok",
        #                                      "unknown", "refused"
        self.tr: Optional["LatencyTracer"] = None   # set by make_span —
        # completion sites (BatchSubmit sinks) retire via the span alone

    def mark(self, phase: int) -> None:
        if self.t[phase] == 0.0:
            self.t[phase] = time.perf_counter()

    def to_dict(self) -> dict:
        """Per-phase breakdown for /latency and save_dump meta: deltas
        from ``submitted`` (seconds), only for stamped phases."""
        t0 = self.t[SUBMITTED]
        phases = {PHASE_NAMES[i]: round(self.t[i] - t0, 9)
                  for i in range(1, 9) if self.t[i] > 0.0}
        return {"seq": self.seq, "kind": self.kind, "group": self.group,
                "idx": self.idx, "k": self.k, "tick": self.tick,
                "outcome": self.outcome or "in-flight", "phases": phases}


class TxnSpan:
    """One sampled cross-group transaction's 2PC lifecycle record
    (begin → prepared → decided → applied → acked).  Stamped by the
    driving client thread only (runtime/txn.py runs the whole 2PC flow
    on the caller's thread), retired into that thread's ring like any
    Span — the tick thread folds it at harvest.  Outcomes: ``commit`` /
    ``abort`` (clean decisions — both contribute latency samples),
    ``refused`` (txn-level admission shed, pre-PREPARE), ``unknown``
    (coordinator unreachable mid-flight; resolved later by recovery)."""

    __slots__ = ("seq", "tid", "parts", "t", "outcome", "tr")

    def __init__(self, seq: int):
        self.seq = seq
        self.tid = ""
        self.parts = 0            # participant count
        self.t = [0.0] * 5
        self.outcome: Optional[str] = None
        self.tr: Optional["LatencyTracer"] = None

    def mark(self, phase: int) -> None:
        if self.t[phase] == 0.0:
            self.t[phase] = time.perf_counter()

    def to_dict(self) -> dict:
        t0 = self.t[T_BEGIN]
        phases = {TXN_PHASE_NAMES[i]: round(self.t[i] - t0, 9)
                  for i in range(1, 5) if self.t[i] > 0.0}
        return {"seq": self.seq, "kind": "t", "txn": self.tid,
                "parts": self.parts,
                "outcome": self.outcome or "in-flight", "phases": phases}


class LatencyTracer:
    """Sampler + span bookkeeping + harvest for one node.

    Thread contract: ``next_seq_w`` is called under the node's submit
    lock and ``next_seq_r`` under its read lock (the counters need no
    lock of their own); ``retire`` may run on any thread (per-thread
    rings); ``harvest``/``mark_committed``/``tick_spans`` run on the
    tick thread only.
    """

    def __init__(self, rate: int, seed: int = 0, slo_s: float = 0.5,
                 max_live: int = 512, recent: int = 64):
        assert rate >= 1
        self.rate = int(rate)
        self.seed = int(seed)
        self.phase = self.seed % self.rate
        self.slo_s = float(slo_s)
        self.max_live = int(max_live)
        self._seq_w = 0           # guarded by the node's submit lock
        self._seq_r = 0           # guarded by the node's read lock
        self._seq_t = 0           # txn drivers run on arbitrary client
        self._seq_t_lock = threading.Lock()   # threads: own tiny lock
        self._txn_seen = False    # tick thread: any TxnSpan harvested yet
        self._live = 0
        self._live_lock = threading.Lock()
        self._rings_lock = threading.Lock()
        self._rings: List[deque] = []
        self._tls = threading.local()
        # Tick-thread-only state.
        self.pending_commit: List[Span] = []   # offered, awaiting commit
        self.recent: deque = deque(maxlen=recent)
        self.counts: Dict[str, int] = {
            "sampled": 0, "ok": 0, "unknown": 0, "refused": 0,
            "overflow": 0, "slo_violations": 0}

    # -- sampling (pure arithmetic) -------------------------------------
    def sampled(self, seq: int) -> bool:
        return (seq + self.phase) % self.rate == 0

    def first_in(self, seq0: int, n: int) -> int:
        """Offset of the first sampled seq in [seq0, seq0+n), or -1.
        O(1): the stride has exactly one hit per ``rate`` seqs."""
        off = (-(seq0 + self.phase)) % self.rate
        return off if off < n else -1

    def next_seq_w(self, n: int) -> int:
        s = self._seq_w
        self._seq_w = s + n
        return s

    def next_seq_r(self, n: int) -> int:
        s = self._seq_r
        self._seq_r = s + n
        return s

    def next_seq_t(self) -> int:
        with self._seq_t_lock:
            s = self._seq_t
            self._seq_t = s + 1
        return s

    # -- span lifecycle -------------------------------------------------
    def make_span(self, seq: int, kind: str, k: int) -> Optional[Span]:
        """Admit a sampled candidate (bounded by ``max_live``)."""
        with self._live_lock:
            if self._live >= self.max_live:
                self.counts["overflow"] += 1   # GIL-atomic enough: the
                return None                    # lock serializes writers
            self._live += 1
            self.counts["sampled"] += 1
        sp = Span(seq, kind, k)
        sp.tr = self
        sp.mark(SUBMITTED)
        return sp

    def make_txn_span(self, seq: int) -> Optional[TxnSpan]:
        """Admit a sampled txn candidate (same ``max_live`` bound and
        overflow accounting as entry spans)."""
        with self._live_lock:
            if self._live >= self.max_live:
                self.counts["overflow"] += 1
                return None
            self._live += 1
            self.counts["sampled"] += 1
        sp = TxnSpan(seq)
        sp.tr = self
        sp.mark(T_BEGIN)
        return sp

    def _ring(self) -> deque:
        ring = getattr(self._tls, "ring", None)
        if ring is None:
            ring = self._tls.ring = deque()
            with self._rings_lock:
                self._rings.append(ring)
        return ring

    def retire(self, sp: Span, outcome: str) -> None:
        """Finish a span on the CURRENT thread: record its outcome and
        park it in this thread's ring for the tick thread to harvest.
        Idempotent — the first outcome wins (an abort racing a late
        completion must not retire the span twice)."""
        if sp.outcome is not None:
            return
        sp.outcome = outcome
        self._ring().append(sp)
        with self._live_lock:
            self._live -= 1

    def observe_client(self, seconds: float, read: bool = False) -> None:
        """Any thread (api/stub.py execute / execute_read): park one
        client-perceived wall time — queueing + forward chase included —
        in this thread's ring; harvest folds it into
        ``lat_client_execute_s`` / ``lat_client_read_s``.  Client
        threads never touch the shared registry (single-writer rule)."""
        self._ring().append((seconds, read))

    def mark_committed(self, h_commit) -> None:
        """Tick thread: stamp ``committed`` on in-flight spans whose
        group's commit frontier reached their log index."""
        pend = self.pending_commit
        if not pend:
            return
        keep: List[Span] = []
        for sp in pend:
            if sp.outcome is not None:
                continue          # already retired (abort path)
            if sp.idx >= 0 and int(h_commit[sp.group]) >= sp.idx:
                sp.mark(COMMITTED)
            else:
                keep.append(sp)
        self.pending_commit = keep

    # -- harvest (tick thread: the registry's single writer) ------------
    def harvest(self, metrics) -> None:
        with self._rings_lock:
            rings = list(self._rings)
        c = self.counts
        observe = metrics.observe
        for ring in rings:
            while ring:
                sp = ring.popleft()
                if sp.__class__ is tuple:     # client wall-time sample
                    observe("lat_client_read_s" if sp[1]
                            else "lat_client_execute_s", sp[0])
                    continue
                if sp.__class__ is TxnSpan:   # 2PC lifecycle sample
                    self._txn_seen = True
                    self.recent.append(sp)
                    key = "txn_" + (sp.outcome or "unknown")
                    c[key] = c.get(key, 0) + 1
                    if sp.outcome in ("commit", "abort"):
                        t = sp.t
                        for name, a, b in TXN_PHASE_PAIRS:
                            if t[a] > 0.0 and t[b] > 0.0:
                                observe(f"lat_txn_{name}_s",
                                        max(0.0, t[b] - t[a]))
                        if t[T_ACKED] > 0.0:
                            observe("lat_txn_e2e_s",
                                    t[T_ACKED] - t[T_BEGIN])
                    continue
                self.recent.append(sp)
                if sp.outcome != "ok":
                    c[sp.outcome] = c.get(sp.outcome, 0) + 1
                    continue      # never fabricate a latency
                c["ok"] += 1
                t = sp.t
                if sp.kind == "r":
                    if t[SERVED] > 0.0:
                        observe("lat_read_e2e_s", t[SERVED] - t[SUBMITTED])
                    continue
                for name, a, b in PHASE_PAIRS:
                    if t[a] > 0.0 and t[b] > 0.0:
                        observe(f"lat_{name}_s", max(0.0, t[b] - t[a]))
                end = t[ACKED] if t[ACKED] > 0.0 else 0.0
                if end > 0.0:
                    e2e = end - t[SUBMITTED]
                    observe("lat_e2e_s", e2e)
                    if e2e > self.slo_s:
                        c["slo_violations"] += 1
        # Percentile + SLO-burn gauges from the registry's own histogram
        # (one source for /metrics, /healthz and /latency).
        h = metrics.histogram("lat_e2e_s")
        metrics.gauge("lat_e2e_p50_s", h.quantile(0.5))
        metrics.gauge("lat_e2e_p99_s", h.quantile(0.99))
        metrics.gauge("lat_e2e_p999_s", h.quantile(0.999))
        metrics.gauge("lat_slo_target_s", self.slo_s)
        ok = c["ok"]
        metrics.gauge("lat_slo_burn_ratio",
                      c["slo_violations"] / ok if ok else 0.0)
        metrics["lat_sampled"] = c["sampled"]
        metrics["lat_spans_ok"] = ok
        metrics["lat_spans_unknown"] = c["unknown"]
        metrics["lat_spans_refused"] = c["refused"]
        metrics["lat_span_overflow"] = c["overflow"]
        if self._txn_seen:
            th = metrics.histogram("lat_txn_e2e_s")
            metrics.gauge("lat_txn_e2e_p50_s", th.quantile(0.5))
            metrics.gauge("lat_txn_e2e_p99_s", th.quantile(0.99))
            metrics.gauge("lat_txn_e2e_p999_s", th.quantile(0.999))
            nc = c.get("txn_commit", 0)
            na = c.get("txn_abort", 0)
            metrics.gauge("lat_txn_abort_ratio",
                          na / (nc + na) if (nc + na) else 0.0)

    # -- views -----------------------------------------------------------
    def snapshot(self, metrics) -> dict:
        """The /latency document: sampler config, SLO state, per-phase
        and end-to-end percentile table, recent sampled spans."""
        phases = {}
        for name, _a, _b in PHASE_PAIRS:
            h = metrics._histograms.get(f"lat_{name}_s")
            if h is not None and h.n:
                phases[name] = h.summary() | {"p999": h.quantile(0.999)}
        doc = {
            "sampling": {"rate": self.rate, "seed": self.seed,
                         "counts": dict(self.counts),
                         "in_flight": self._live},
            "slo": {
                "target_s": self.slo_s,
                "e2e_p999_s": metrics._gauges.get("lat_e2e_p999_s", 0.0),
                "burn_ratio": metrics._gauges.get("lat_slo_burn_ratio",
                                                  0.0),
            },
            "phases": phases,
            "recent": [sp.to_dict() for sp in list(self.recent)],
        }
        for key in ("lat_e2e_s", "lat_read_e2e_s"):
            h = metrics._histograms.get(key)
            if h is not None and h.n:
                doc[key[:-2]] = h.summary() | {"p999": h.quantile(0.999)}
        if self._txn_seen:
            txn_phases = {}
            for name, _a, _b in TXN_PHASE_PAIRS:
                h = metrics._histograms.get(f"lat_txn_{name}_s")
                if h is not None and h.n:
                    txn_phases[name] = h.summary() | \
                        {"p999": h.quantile(0.999)}
            c = self.counts
            txn = {"phases": txn_phases,
                   "counts": {k: v for k, v in c.items()
                              if k.startswith("txn_")},
                   "abort_ratio": metrics._gauges.get(
                       "lat_txn_abort_ratio", 0.0)}
            h = metrics._histograms.get("lat_txn_e2e_s")
            if h is not None and h.n:
                txn["e2e"] = h.summary() | {"p999": h.quantile(0.999)}
            doc["txn"] = txn
        return doc


# ---------------------------------------------------------------------------
# Cross-node hop tracing (the fleet attribution plane).
#
# A sampled write span tells us WHEN the replication phase (send_commit)
# burned its time but not WHERE.  For every sampled span the leader
# attaches a compact hop context to the AppendEntries traffic that ships
# the entry (transport/codec.py HOPS frames, piggybacked on the same
# per-peer slice); the follower stamps receive → staged → fsynced on its
# OWN clock and echoes the context with single-clock durations; the
# leader pairs the echo like an RPC and decomposes the phase into
#
#   leader_pack     AE computed -> frame handed to the transport
#                   (leader clock; includes the persist-before-send
#                   barrier in serial mode)
#   wire            one-way estimate: (rtt - follower_residence) / 2
#                   (both terms single-clock: rtt on the leader,
#                   residence on the follower — clock skew cancels)
#   follower_fsync  receive -> entry durable (follower clock)
#   ack_return      remainder of the rtt after wire + fsync (the
#                   follower's post-fsync residence + the return trip)
#   quorum_wait     echo received -> commit stamped (leader clock;
#                   waiting on the rest of the quorum + tick cadence)
#
# The five segments telescope: leader_pack + wire + follower_fsync +
# ack_return = (t_send - t_pack) + rtt, and quorum_wait covers echo ->
# commit, so for any peer whose echo beat the commit the sum equals
# commit - t_pack exactly — which is send_commit plus the sub-tick
# pack-to-SENT sliver (the ≤5% reconciliation in tests/test_hops.py).
# Spans that die before committing are DROPPED (never fabricate a hop
# latency); un-echoed contexts expire by TTL on both ends.
# ---------------------------------------------------------------------------

HOP_SEGMENTS = ("leader_pack", "wire", "follower_fsync", "ack_return",
                "quorum_wait")

# HOPS frame directions (transport/codec.py pack_hops).
HOP_REQUEST, HOP_ECHO = 0, 1


class _HopRec:
    """Leader-side pending context for one sampled span's replication."""

    __slots__ = ("hop_id", "span", "t_pack", "born", "sent", "echo")

    def __init__(self, hop_id: int, span: Span, born_ns: int):
        self.hop_id = hop_id
        self.span = span
        self.t_pack = 0       # ns — first AE coverage detected
        self.born = born_ns
        self.sent = {}        # peer -> t_send_ns (0 = queued, unsent)
        self.echo = {}        # peer -> (t_echo_recv_ns, rtt_ns,
        #                       d_staged_ns, d_fsync_ns, d_echo_ns)


class _ForeignHop:
    """Follower-side context received from an origin leader."""

    __slots__ = ("origin", "hop_id", "group", "idx", "t_send", "t_recv",
                 "d_staged", "d_fsync")

    def __init__(self, origin: int, hop_id: int, group: int, idx: int,
                 t_send: int, t_recv: int):
        self.origin = origin
        self.hop_id = hop_id
        self.group = group
        self.idx = idx
        self.t_send = t_send      # origin clock, echoed back verbatim
        self.t_recv = t_recv      # OUR clock (reader-thread arrival)
        self.d_staged = 0         # ns from t_recv (our clock)
        self.d_fsync = 0


class HopTracer:
    """Per-node hop bookkeeping — both roles at once (every node leads
    some groups and follows others).

    Thread contract: ``recv_requests``/``recv_echoes`` run on transport
    reader threads (lock-free deque appends); everything else —
    ``track``, ``scan_outbox``, ``fold_foreign``, ``take_out``,
    ``fold`` — runs on the tick/host-phase thread only."""

    def __init__(self, node_id: int, n_peers: int, ttl_s: float = 30.0,
                 recent: int = 64):
        self.node_id = int(node_id)
        self.n_peers = int(n_peers)
        self._ttl_ns = int(ttl_s * 1e9)
        # Leader side.
        self._next_id = 1
        self._live: Dict[int, _HopRec] = {}
        self._by_group: Dict[int, List[_HopRec]] = {}
        self._out_req: Dict[int, List[_HopRec]] = {}    # peer -> queued
        self._in_echo: deque = deque()   # (origin, records, t_recv_ns)
        # Follower side.
        self._in_req: deque = deque()    # (origin, records, t_recv_ns)
        self._foreign: List[_ForeignHop] = []
        self._out_echo: Dict[int, List[_ForeignHop]] = {}
        self.recent: deque = deque(maxlen=recent)
        self.counts: Dict[str, int] = {
            "tracked": 0, "requests_sent": 0, "echoes": 0,
            "echo_orphan": 0, "finalized": 0, "dropped_unknown": 0,
            "expired": 0, "foreign_seen": 0, "foreign_expired": 0}

    # -- leader: context creation + AE coverage -------------------------
    def track(self, span: Span) -> None:
        """Register a device-accepted sampled span (group/idx pinned)
        for hop attribution.  Tick thread."""
        r = _HopRec(self._next_id, span, time.perf_counter_ns())
        self._next_id += 1
        self._live[r.hop_id] = r
        self._by_group.setdefault(span.group, []).append(r)
        self.counts["tracked"] += 1

    def scan_outbox(self, ae_valid, ae_prev_idx, ae_n) -> None:
        """Detect which peers' AE frames this tick cover a tracked
        span's (group, idx) and queue a hop request for each — one per
        (span, peer), first coverage wins.  Arrays are the host-fetched
        [P, G] outbox planes; the walk is over tracked groups only (at
        most a handful of sampled spans are live)."""
        if not self._by_group:
            return
        now = time.perf_counter_ns()
        for g, recs in self._by_group.items():
            for r in recs:
                idx = r.span.idx
                for p in range(self.n_peers):
                    if p == self.node_id or p in r.sent:
                        continue
                    if ae_valid[p, g]:
                        prev = int(ae_prev_idx[p, g])
                        if prev < idx <= prev + int(ae_n[p, g]):
                            if r.t_pack == 0:
                                r.t_pack = now
                            r.sent[p] = 0
                            self._out_req.setdefault(p, []).append(r)

    # -- follower: intake + durability stamping -------------------------
    def recv_requests(self, origin: int, records, t_recv_ns: int) -> None:
        """Reader thread: park an inbound HOPS request batch."""
        self._in_req.append((origin, records, t_recv_ns))

    def recv_echoes(self, origin: int, records, t_recv_ns: int) -> None:
        """Reader thread: park an inbound HOPS echo batch."""
        self._in_echo.append((origin, records, t_recv_ns))

    def fold_foreign(self, tail, fsynced: bool) -> None:
        """Tick/host-phase thread: drain inbound requests and stamp the
        ones whose (group, idx) the given per-group tail now covers —
        ``fsynced=False`` after staging (marks ``staged``),
        ``fsynced=True`` after the durability barrier (marks ``fsynced``
        and readies the echo for the next flush to the origin)."""
        while self._in_req:
            origin, records, t_recv = self._in_req.popleft()
            for hop_id, group, idx, t_send in records:
                self._foreign.append(_ForeignHop(
                    origin, hop_id, int(group), int(idx), t_send, t_recv))
                self.counts["foreign_seen"] += 1
        if not self._foreign:
            return
        now = time.perf_counter_ns()
        keep: List[_ForeignHop] = []
        for f in self._foreign:
            if 0 <= f.group < len(tail) and int(tail[f.group]) >= f.idx:
                if f.d_staged == 0:
                    f.d_staged = max(now - f.t_recv, 1)
                if fsynced:
                    f.d_fsync = max(now - f.t_recv, 1)
                    self._out_echo.setdefault(f.origin, []).append(f)
                    continue
            elif now - f.t_recv > self._ttl_ns:
                # The entry never became durable here (conflict
                # truncation, leadership churn, lane close): expire —
                # an unstamped context must never fabricate a latency.
                self.counts["foreign_expired"] += 1
                continue
            keep.append(f)
        self._foreign = keep

    # -- both roles: outbound records for one peer ----------------------
    def take_out(self, peer: int):
        """Outbound hop records riding this flush to ``peer``:
        ``(requests, echoes)`` or None.  Stamps send times (requests)
        and residence (echoes) NOW — call immediately before handing
        the peer's bytes to the transport.  Tick/host-phase thread."""
        reqs = self._out_req.pop(peer, None)
        echoes = self._out_echo.pop(peer, None)
        if not reqs and not echoes:
            return None
        t = time.perf_counter_ns()
        req_records = []
        for r in reqs or ():
            r.sent[peer] = t
            req_records.append((r.hop_id, r.span.group, r.span.idx, t))
            self.counts["requests_sent"] += 1
        echo_records = []
        for f in echoes or ():
            echo_records.append((f.hop_id, f.t_send, f.d_staged,
                                 f.d_fsync, max(t - f.t_recv, 1)))
        return req_records, echo_records

    def has_out(self, peer: int) -> bool:
        return peer in self._out_req or peer in self._out_echo

    def out_peers(self):
        return set(self._out_req) | set(self._out_echo)

    # -- leader: echo folding + finalization ----------------------------
    def fold(self, metrics) -> None:
        """Tick thread: pair echoes with pending contexts, finalize
        contexts whose span settled (observing per-peer segment
        histograms for committed spans only), expire the rest by TTL,
        and fold the counters into the registry."""
        while self._in_echo:
            origin, records, t_recv = self._in_echo.popleft()
            for hop_id, _t_send, d_staged, d_fsync, d_echo in records:
                r = self._live.get(hop_id)
                if r is None:
                    self.counts["echo_orphan"] += 1
                    continue
                t_sent = r.sent.get(origin, 0)
                if not t_sent or origin in r.echo:
                    continue
                r.echo[origin] = (t_recv, max(t_recv - t_sent, 0),
                                  d_staged, d_fsync, d_echo)
                self.counts["echoes"] += 1
        if self._live:
            now = time.perf_counter_ns()
            done: List[int] = []
            for hop_id, r in self._live.items():
                sp = r.span
                if sp.outcome is None:
                    if now - r.born > self._ttl_ns:
                        done.append(hop_id)
                        self.counts["expired"] += 1
                    continue
                done.append(hop_id)
                if sp.outcome != "ok" or sp.t[COMMITTED] <= 0.0:
                    # Crashed / refused / outcome-unknown span: its hop
                    # context dies with it — no segment is observed.
                    self.counts["dropped_unknown"] += 1
                    continue
                self._observe(r, metrics)
            for hop_id in done:
                r = self._live.pop(hop_id)
                recs = self._by_group.get(r.span.group)
                if recs is not None:
                    try:
                        recs.remove(r)
                    except ValueError:
                        pass
                    if not recs:
                        del self._by_group[r.span.group]
        c = self.counts
        metrics["hop_tracked"] = c["tracked"]
        metrics["hop_requests_sent"] = c["requests_sent"]
        metrics["hop_echoes"] = c["echoes"]
        metrics["hop_finalized"] = c["finalized"]
        metrics["hop_dropped_unknown"] = c["dropped_unknown"]
        metrics["hop_expired"] = c["expired"]
        metrics["hop_foreign_seen"] = c["foreign_seen"]
        metrics["hop_foreign_expired"] = c["foreign_expired"]

    def _observe(self, r: _HopRec, metrics) -> None:
        t_commit = r.span.t[COMMITTED]
        peers = {}
        for p, (t_er, rtt, _d_staged, d_fsync, d_echo) in r.echo.items():
            t_send = r.sent.get(p, 0)
            if not t_send or r.t_pack == 0:
                continue
            rtt_s = rtt * 1e-9
            resid_s = min(max(d_echo, 0) * 1e-9, rtt_s)
            wire = (rtt_s - resid_s) / 2.0
            fsync_s = min(max(d_fsync, 0) * 1e-9, resid_s)
            segs = {
                "leader_pack": max(t_send - r.t_pack, 0) * 1e-9,
                "wire": wire,
                "follower_fsync": fsync_s,
                "ack_return": max(rtt_s - wire - fsync_s, 0.0),
                "quorum_wait": max(t_commit - t_er * 1e-9, 0.0),
            }
            peers[p] = segs
            for name, v in segs.items():
                metrics.observe(f"hop_{name}_s", v)
                metrics.observe(f"hop_{name}_p{p}_s", v)
        if peers:
            self.counts["finalized"] += 1
            sp = r.span
            sc = (t_commit - sp.t[SENT]) if sp.t[SENT] > 0.0 else 0.0
            self.recent.append({
                "seq": sp.seq, "group": sp.group, "idx": sp.idx,
                "tick": sp.tick, "send_commit_s": round(sc, 9),
                "peers": {p: {k: round(v, 9) for k, v in segs.items()}
                          for p, segs in peers.items()},
            })

    # -- views -----------------------------------------------------------
    def snapshot(self, metrics) -> dict:
        """The /hops document: per-peer and aggregate segment summaries
        + bookkeeping counters + recent finalized decompositions."""
        def summarize(name):
            h = metrics._histograms.get(name)
            if h is None or not h.n:
                return None
            return h.summary() | {"p999": h.quantile(0.999)}

        segments = {}
        for seg in HOP_SEGMENTS:
            agg = summarize(f"hop_{seg}_s")
            if agg is None:
                continue
            per_peer = {}
            for p in range(self.n_peers):
                s = summarize(f"hop_{seg}_p{p}_s")
                if s is not None:
                    per_peer[p] = s
            segments[seg] = {"all": agg, "peers": per_peer}
        return {
            "counts": dict(self.counts),
            "pending": len(self._live),
            "foreign_pending": len(self._foreign),
            "segments": segments,
            "recent": list(self.recent),
        }


def hops_from_env(node_id: int, n_peers: int) -> Optional[HopTracer]:
    """Build the node's hop tracer from RAFT_HOP_TRACE (default on;
    0/false disables).  Cheap when idle: a node with latency sampling
    off never tracks a span, so the per-tick fold is a no-op — but the
    tracer must exist on FOLLOWERS regardless of their own sampling
    config, or a sampled leader's contexts would never echo."""
    import os
    raw = os.environ.get("RAFT_HOP_TRACE", "").strip().lower()
    if raw in ("0", "false", "no", "off"):
        return None
    ttl = float(os.environ.get("RAFT_HOP_TTL_S", "30"))
    return HopTracer(node_id, n_peers, ttl_s=max(ttl, 1.0))


def tracer_from_env(seed: int = 0, slo_s: float = 0.5,
                    default_rate: int = 64) -> Optional[LatencyTracer]:
    """Build the node's tracer from RAFT_LAT_SAMPLE (1/N sampling;
    0/negative disables — the node then holds None and every hot-path
    hook is one is-None check)."""
    import os
    raw = os.environ.get("RAFT_LAT_SAMPLE", "").strip()
    try:
        rate = int(raw) if raw else default_rate
    except ValueError:
        rate = default_rate
    if rate <= 0:
        return None
    return LatencyTracer(rate, seed=seed, slo_s=slo_s)
