"""Per-entry commit-path latency tracing (the sampled span plane).

Every signal the runtime exported before this module was per-tick: the
stage histograms and the flight recorder measure what a *tick* costs,
never what one *command* experienced from submit to ack.  CD-Raft
(arXiv:2603.10555) and "Paxos vs Raft" (arXiv:2004.05074) both frame
consensus quality as end-to-end commit latency — and the ROADMAP's
"millions of users" claim is a p999 claim, so the runtime needs to know
where the microseconds go per entry, not per tick.

Design:

* **Sampling** is a seeded stride: submission seq ``s`` is sampled iff
  ``(s + seed % rate) % rate == 0`` (~1/rate of submits).  The sampled
  SET is a pure function of (seed, rate) — same seed, same set — and
  membership of a contiguous seq range [s0, s0+n) is O(1) arithmetic
  (``first_in``), so the 100k-group fan-out path never loops to decide.
  ``rate=0`` disables the plane entirely: the node holds no tracer and
  every hot-path hook is one attribute-is-None check.
* **Spans** stamp wall-clock marks through the commit path:
  ``submitted → offered → staged → fsynced → sent → committed →
  applied → acked`` (writes) and ``submitted → served`` (reads).  A
  span that dies before its ack — leadership loss, storage fault, lane
  close — retires with ``outcome-unknown`` (or ``refused`` for marked
  pre-log refusals) and contributes NO latency sample: a crashed span
  must never fabricate a latency.
* **Rings**: spans retire into per-thread ring buffers (client threads,
  stripe workers, the tick thread each own one deque; registration of
  a new ring takes the only lock in the retire path).  The tick thread
  merges rings at :meth:`harvest` and is the sole writer of the shared
  histograms — the registry keeps its single-writer contract (see
  utils/metrics.py) with W striped workers in play.
* **Admission** is bounded (``max_live``): the sampler's *selection* is
  deterministic, but at most ``max_live`` spans are in flight at once —
  overflow candidates are counted (``span_overflow``), not traced, so
  a 100k-group burst cannot turn the trace plane into the workload.

Histograms land in the node's Metrics registry as ``lat_<pair>_s``
per phase pair plus ``lat_e2e_s`` / ``lat_read_e2e_s`` end-to-end, so
/metrics exposition and /latency percentiles come from one source.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

# Phase indices (Span.t slots).
SUBMITTED, OFFERED, STAGED, FSYNCED, SENT, COMMITTED, APPLIED, ACKED, \
    SERVED = range(9)

PHASE_NAMES = ("submitted", "offered", "staged", "fsynced", "sent",
               "committed", "applied", "acked", "served")

# Adjacent phase pairs reported as histograms (writes).  Summing these
# medians ≈ the e2e median on an idle cluster (the reconciliation the
# acceptance criteria check).
PHASE_PAIRS = (
    ("submit_offer", SUBMITTED, OFFERED),
    ("offer_stage", OFFERED, STAGED),
    ("stage_fsync", STAGED, FSYNCED),
    ("fsync_send", FSYNCED, SENT),
    ("send_commit", SENT, COMMITTED),
    ("commit_apply", COMMITTED, APPLIED),
    ("apply_ack", APPLIED, ACKED),
)

# Transaction phase indices (TxnSpan.t slots) — the 2PC lifecycle the
# txn plane (runtime/txn.py) stamps per sampled transaction.
T_BEGIN, T_PREPARED, T_DECIDED, T_APPLIED, T_ACKED = range(5)

TXN_PHASE_NAMES = ("begin", "prepared", "decided", "applied", "acked")

TXN_PHASE_PAIRS = (
    ("begin_prepare", T_BEGIN, T_PREPARED),    # begin replicated + all
    #                                            participant PREPAREs acked
    ("prepare_decide", T_PREPARED, T_DECIDED),  # decision replicated in
    #                                            the coordinator group
    ("decide_apply", T_DECIDED, T_APPLIED),    # commit/abort fan-out
    ("apply_ack", T_APPLIED, T_ACKED),         # result handed to caller
)


class Span:
    """One sampled entry's lifecycle record.  Mutated by whichever
    thread reaches the stamp site; each slot has exactly one writer per
    lifecycle (the stamp sites are ordered by the commit protocol), so
    no locking — a torn read can only be observed by the harvester for
    an outcome-unknown span, which reports no latency anyway."""

    __slots__ = ("seq", "kind", "k", "group", "idx", "tick", "t",
                 "outcome", "tr")

    def __init__(self, seq: int, kind: str, k: int):
        self.seq = seq
        self.kind = kind          # "w" (write) | "r" (read)
        self.k = k                # entry offset within its batch
        self.group = -1
        self.idx = -1             # log index (writes; stamped at offer)
        self.tick = -1            # node tick at device accept — the
        #                           shared axis flight-recorder events
        #                           and worker-util intervals plot on
        self.t = [0.0] * 9
        self.outcome: Optional[str] = None   # None=in flight, "ok",
        #                                      "unknown", "refused"
        self.tr: Optional["LatencyTracer"] = None   # set by make_span —
        # completion sites (BatchSubmit sinks) retire via the span alone

    def mark(self, phase: int) -> None:
        if self.t[phase] == 0.0:
            self.t[phase] = time.perf_counter()

    def to_dict(self) -> dict:
        """Per-phase breakdown for /latency and save_dump meta: deltas
        from ``submitted`` (seconds), only for stamped phases."""
        t0 = self.t[SUBMITTED]
        phases = {PHASE_NAMES[i]: round(self.t[i] - t0, 9)
                  for i in range(1, 9) if self.t[i] > 0.0}
        return {"seq": self.seq, "kind": self.kind, "group": self.group,
                "idx": self.idx, "k": self.k, "tick": self.tick,
                "outcome": self.outcome or "in-flight", "phases": phases}


class TxnSpan:
    """One sampled cross-group transaction's 2PC lifecycle record
    (begin → prepared → decided → applied → acked).  Stamped by the
    driving client thread only (runtime/txn.py runs the whole 2PC flow
    on the caller's thread), retired into that thread's ring like any
    Span — the tick thread folds it at harvest.  Outcomes: ``commit`` /
    ``abort`` (clean decisions — both contribute latency samples),
    ``refused`` (txn-level admission shed, pre-PREPARE), ``unknown``
    (coordinator unreachable mid-flight; resolved later by recovery)."""

    __slots__ = ("seq", "tid", "parts", "t", "outcome", "tr")

    def __init__(self, seq: int):
        self.seq = seq
        self.tid = ""
        self.parts = 0            # participant count
        self.t = [0.0] * 5
        self.outcome: Optional[str] = None
        self.tr: Optional["LatencyTracer"] = None

    def mark(self, phase: int) -> None:
        if self.t[phase] == 0.0:
            self.t[phase] = time.perf_counter()

    def to_dict(self) -> dict:
        t0 = self.t[T_BEGIN]
        phases = {TXN_PHASE_NAMES[i]: round(self.t[i] - t0, 9)
                  for i in range(1, 5) if self.t[i] > 0.0}
        return {"seq": self.seq, "kind": "t", "txn": self.tid,
                "parts": self.parts,
                "outcome": self.outcome or "in-flight", "phases": phases}


class LatencyTracer:
    """Sampler + span bookkeeping + harvest for one node.

    Thread contract: ``next_seq_w`` is called under the node's submit
    lock and ``next_seq_r`` under its read lock (the counters need no
    lock of their own); ``retire`` may run on any thread (per-thread
    rings); ``harvest``/``mark_committed``/``tick_spans`` run on the
    tick thread only.
    """

    def __init__(self, rate: int, seed: int = 0, slo_s: float = 0.5,
                 max_live: int = 512, recent: int = 64):
        assert rate >= 1
        self.rate = int(rate)
        self.seed = int(seed)
        self.phase = self.seed % self.rate
        self.slo_s = float(slo_s)
        self.max_live = int(max_live)
        self._seq_w = 0           # guarded by the node's submit lock
        self._seq_r = 0           # guarded by the node's read lock
        self._seq_t = 0           # txn drivers run on arbitrary client
        self._seq_t_lock = threading.Lock()   # threads: own tiny lock
        self._txn_seen = False    # tick thread: any TxnSpan harvested yet
        self._live = 0
        self._live_lock = threading.Lock()
        self._rings_lock = threading.Lock()
        self._rings: List[deque] = []
        self._tls = threading.local()
        # Tick-thread-only state.
        self.pending_commit: List[Span] = []   # offered, awaiting commit
        self.recent: deque = deque(maxlen=recent)
        self.counts: Dict[str, int] = {
            "sampled": 0, "ok": 0, "unknown": 0, "refused": 0,
            "overflow": 0, "slo_violations": 0}

    # -- sampling (pure arithmetic) -------------------------------------
    def sampled(self, seq: int) -> bool:
        return (seq + self.phase) % self.rate == 0

    def first_in(self, seq0: int, n: int) -> int:
        """Offset of the first sampled seq in [seq0, seq0+n), or -1.
        O(1): the stride has exactly one hit per ``rate`` seqs."""
        off = (-(seq0 + self.phase)) % self.rate
        return off if off < n else -1

    def next_seq_w(self, n: int) -> int:
        s = self._seq_w
        self._seq_w = s + n
        return s

    def next_seq_r(self, n: int) -> int:
        s = self._seq_r
        self._seq_r = s + n
        return s

    def next_seq_t(self) -> int:
        with self._seq_t_lock:
            s = self._seq_t
            self._seq_t = s + 1
        return s

    # -- span lifecycle -------------------------------------------------
    def make_span(self, seq: int, kind: str, k: int) -> Optional[Span]:
        """Admit a sampled candidate (bounded by ``max_live``)."""
        with self._live_lock:
            if self._live >= self.max_live:
                self.counts["overflow"] += 1   # GIL-atomic enough: the
                return None                    # lock serializes writers
            self._live += 1
            self.counts["sampled"] += 1
        sp = Span(seq, kind, k)
        sp.tr = self
        sp.mark(SUBMITTED)
        return sp

    def make_txn_span(self, seq: int) -> Optional[TxnSpan]:
        """Admit a sampled txn candidate (same ``max_live`` bound and
        overflow accounting as entry spans)."""
        with self._live_lock:
            if self._live >= self.max_live:
                self.counts["overflow"] += 1
                return None
            self._live += 1
            self.counts["sampled"] += 1
        sp = TxnSpan(seq)
        sp.tr = self
        sp.mark(T_BEGIN)
        return sp

    def _ring(self) -> deque:
        ring = getattr(self._tls, "ring", None)
        if ring is None:
            ring = self._tls.ring = deque()
            with self._rings_lock:
                self._rings.append(ring)
        return ring

    def retire(self, sp: Span, outcome: str) -> None:
        """Finish a span on the CURRENT thread: record its outcome and
        park it in this thread's ring for the tick thread to harvest.
        Idempotent — the first outcome wins (an abort racing a late
        completion must not retire the span twice)."""
        if sp.outcome is not None:
            return
        sp.outcome = outcome
        self._ring().append(sp)
        with self._live_lock:
            self._live -= 1

    def observe_client(self, seconds: float, read: bool = False) -> None:
        """Any thread (api/stub.py execute / execute_read): park one
        client-perceived wall time — queueing + forward chase included —
        in this thread's ring; harvest folds it into
        ``lat_client_execute_s`` / ``lat_client_read_s``.  Client
        threads never touch the shared registry (single-writer rule)."""
        self._ring().append((seconds, read))

    def mark_committed(self, h_commit) -> None:
        """Tick thread: stamp ``committed`` on in-flight spans whose
        group's commit frontier reached their log index."""
        pend = self.pending_commit
        if not pend:
            return
        keep: List[Span] = []
        for sp in pend:
            if sp.outcome is not None:
                continue          # already retired (abort path)
            if sp.idx >= 0 and int(h_commit[sp.group]) >= sp.idx:
                sp.mark(COMMITTED)
            else:
                keep.append(sp)
        self.pending_commit = keep

    # -- harvest (tick thread: the registry's single writer) ------------
    def harvest(self, metrics) -> None:
        with self._rings_lock:
            rings = list(self._rings)
        c = self.counts
        observe = metrics.observe
        for ring in rings:
            while ring:
                sp = ring.popleft()
                if sp.__class__ is tuple:     # client wall-time sample
                    observe("lat_client_read_s" if sp[1]
                            else "lat_client_execute_s", sp[0])
                    continue
                if sp.__class__ is TxnSpan:   # 2PC lifecycle sample
                    self._txn_seen = True
                    self.recent.append(sp)
                    key = "txn_" + (sp.outcome or "unknown")
                    c[key] = c.get(key, 0) + 1
                    if sp.outcome in ("commit", "abort"):
                        t = sp.t
                        for name, a, b in TXN_PHASE_PAIRS:
                            if t[a] > 0.0 and t[b] > 0.0:
                                observe(f"lat_txn_{name}_s",
                                        max(0.0, t[b] - t[a]))
                        if t[T_ACKED] > 0.0:
                            observe("lat_txn_e2e_s",
                                    t[T_ACKED] - t[T_BEGIN])
                    continue
                self.recent.append(sp)
                if sp.outcome != "ok":
                    c[sp.outcome] = c.get(sp.outcome, 0) + 1
                    continue      # never fabricate a latency
                c["ok"] += 1
                t = sp.t
                if sp.kind == "r":
                    if t[SERVED] > 0.0:
                        observe("lat_read_e2e_s", t[SERVED] - t[SUBMITTED])
                    continue
                for name, a, b in PHASE_PAIRS:
                    if t[a] > 0.0 and t[b] > 0.0:
                        observe(f"lat_{name}_s", max(0.0, t[b] - t[a]))
                end = t[ACKED] if t[ACKED] > 0.0 else 0.0
                if end > 0.0:
                    e2e = end - t[SUBMITTED]
                    observe("lat_e2e_s", e2e)
                    if e2e > self.slo_s:
                        c["slo_violations"] += 1
        # Percentile + SLO-burn gauges from the registry's own histogram
        # (one source for /metrics, /healthz and /latency).
        h = metrics.histogram("lat_e2e_s")
        metrics.gauge("lat_e2e_p50_s", h.quantile(0.5))
        metrics.gauge("lat_e2e_p99_s", h.quantile(0.99))
        metrics.gauge("lat_e2e_p999_s", h.quantile(0.999))
        metrics.gauge("lat_slo_target_s", self.slo_s)
        ok = c["ok"]
        metrics.gauge("lat_slo_burn_ratio",
                      c["slo_violations"] / ok if ok else 0.0)
        metrics["lat_sampled"] = c["sampled"]
        metrics["lat_spans_ok"] = ok
        metrics["lat_spans_unknown"] = c["unknown"]
        metrics["lat_spans_refused"] = c["refused"]
        metrics["lat_span_overflow"] = c["overflow"]
        if self._txn_seen:
            th = metrics.histogram("lat_txn_e2e_s")
            metrics.gauge("lat_txn_e2e_p50_s", th.quantile(0.5))
            metrics.gauge("lat_txn_e2e_p99_s", th.quantile(0.99))
            metrics.gauge("lat_txn_e2e_p999_s", th.quantile(0.999))
            nc = c.get("txn_commit", 0)
            na = c.get("txn_abort", 0)
            metrics.gauge("lat_txn_abort_ratio",
                          na / (nc + na) if (nc + na) else 0.0)

    # -- views -----------------------------------------------------------
    def snapshot(self, metrics) -> dict:
        """The /latency document: sampler config, SLO state, per-phase
        and end-to-end percentile table, recent sampled spans."""
        phases = {}
        for name, _a, _b in PHASE_PAIRS:
            h = metrics._histograms.get(f"lat_{name}_s")
            if h is not None and h.n:
                phases[name] = h.summary() | {"p999": h.quantile(0.999)}
        doc = {
            "sampling": {"rate": self.rate, "seed": self.seed,
                         "counts": dict(self.counts),
                         "in_flight": self._live},
            "slo": {
                "target_s": self.slo_s,
                "e2e_p999_s": metrics._gauges.get("lat_e2e_p999_s", 0.0),
                "burn_ratio": metrics._gauges.get("lat_slo_burn_ratio",
                                                  0.0),
            },
            "phases": phases,
            "recent": [sp.to_dict() for sp in list(self.recent)],
        }
        for key in ("lat_e2e_s", "lat_read_e2e_s"):
            h = metrics._histograms.get(key)
            if h is not None and h.n:
                doc[key[:-2]] = h.summary() | {"p999": h.quantile(0.999)}
        if self._txn_seen:
            txn_phases = {}
            for name, _a, _b in TXN_PHASE_PAIRS:
                h = metrics._histograms.get(f"lat_txn_{name}_s")
                if h is not None and h.n:
                    txn_phases[name] = h.summary() | \
                        {"p999": h.quantile(0.999)}
            c = self.counts
            txn = {"phases": txn_phases,
                   "counts": {k: v for k, v in c.items()
                              if k.startswith("txn_")},
                   "abort_ratio": metrics._gauges.get(
                       "lat_txn_abort_ratio", 0.0)}
            h = metrics._histograms.get("lat_txn_e2e_s")
            if h is not None and h.n:
                txn["e2e"] = h.summary() | {"p999": h.quantile(0.999)}
            doc["txn"] = txn
        return doc


def tracer_from_env(seed: int = 0, slo_s: float = 0.5,
                    default_rate: int = 64) -> Optional[LatencyTracer]:
    """Build the node's tracer from RAFT_LAT_SAMPLE (1/N sampling;
    0/negative disables — the node then holds None and every hot-path
    hook is one is-None check)."""
    import os
    raw = os.environ.get("RAFT_LAT_SAMPLE", "").strip()
    try:
        rate = int(raw) if raw else default_rate
    except ValueError:
        rate = default_rate
    if rate <= 0:
        return None
    return LatencyTracer(rate, seed=seed, slo_s=slo_s)
