"""CRC-32C (Castagnoli) — dependency-free software implementation.

Used for the snapshot-archive integrity sidecars (snapshot payloads are
opaque machine bytes; the WAL keeps its existing per-record CRC-32/IEEE
frames, which run at C speed via zlib in the Python tier and a table in
the native tier).  Castagnoli is the standard choice for storage
checksums (iSCSI, ext4, RocksDB) for its better burst-error detection;
this table-driven version is pure Python and therefore only lives on
cold paths — checkpoint copies (off the tick thread) and the background
scrubber (budgeted per maintain pass).
"""

from __future__ import annotations

_POLY = 0x82F63B78  # reversed Castagnoli polynomial


def _make_table():
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ _POLY if c & 1 else c >> 1
        table.append(c)
    return tuple(table)


_TABLE = _make_table()


def crc32c(data: bytes, crc: int = 0) -> int:
    """Incremental CRC-32C: ``crc32c(b, crc32c(a)) == crc32c(a + b)``."""
    table = _TABLE
    c = (crc ^ 0xFFFFFFFF) & 0xFFFFFFFF
    for b in memoryview(data):
        c = table[(c ^ b) & 0xFF] ^ (c >> 8)
    return (c ^ 0xFFFFFFFF) & 0xFFFFFFFF


def crc32c_file(path: str, chunk: int = 1 << 20, limit: int = -1) -> int:
    """CRC-32C of a file's first ``limit`` bytes (whole file when -1)."""
    c = 0
    remaining = limit
    with open(path, "rb") as f:
        while True:
            n = chunk if remaining < 0 else min(chunk, remaining)
            if n == 0:
                break
            buf = f.read(n)
            if not buf:
                break
            c = crc32c(buf, c)
            if remaining > 0:
                remaining -= len(buf)
    return c
