"""Device profiling hooks (SURVEY §5: the reference has no tracing at all —
logback lines only, support/RaftConfig.java:137-141 — so the TPU build adds
JAX profiler integration from the start).

Two entry points:

* :func:`device_trace` — context manager wrapping a measurement region in
  ``jax.profiler.trace`` so XLA device timelines land in TensorBoard format
  (the benchmark uses this around its measure loop via BENCH_PROFILE_DIR).
* :meth:`TickProfiler` — bounded capture of a live node's tick loop: each
  tick becomes a ``StepTraceAnnotation`` so host phases and the fused device
  step line up on one timeline.  Armed via RaftNode.profile_ticks() or the
  RAFT_PROFILE_DIR environment variable.
"""

from __future__ import annotations

import contextlib
import os
from typing import Optional


@contextlib.contextmanager
def device_trace(log_dir: Optional[str]):
    """Trace the enclosed region to ``log_dir`` (no-op if falsy)."""
    if not log_dir:
        yield
        return
    import jax
    with jax.profiler.trace(log_dir):
        yield


# jax.profiler traces are PROCESS-global (start_trace raises if one is
# already running), so at most one TickProfiler may hold a trace at a time —
# in-process multi-node harnesses construct several RaftNodes, and with
# RAFT_PROFILE_DIR set each would otherwise try to arm.
_TRACE_OWNER: list = []


class TickProfiler:
    """Capture N ticks of a node runtime into a profiler trace.

    Start/stop are explicit and bounded (a trace left running grows without
    bound); each tick is annotated so per-phase host time and device time
    correlate in the viewer.  Only the first profiler to arm in a process
    captures — later arms are silently skipped (the trace is process-global).
    """

    def __init__(self):
        self._remaining = 0
        self._active = False

    def arm(self, log_dir: str, n_ticks: int = 64) -> None:
        if self._active or not log_dir or n_ticks <= 0 or _TRACE_OWNER:
            return
        import jax
        os.makedirs(log_dir, exist_ok=True)
        try:
            jax.profiler.start_trace(log_dir)
        except RuntimeError:  # someone else (outside this module) is tracing
            return
        _TRACE_OWNER.append(self)
        self._remaining = n_ticks
        self._active = True

    @classmethod
    def from_env(cls) -> "TickProfiler":
        """Armed from RAFT_PROFILE_DIR / RAFT_PROFILE_TICKS if set."""
        p = cls()
        d = os.environ.get("RAFT_PROFILE_DIR", "")
        if d:
            p.arm(d, int(os.environ.get("RAFT_PROFILE_TICKS", "64")))
        return p

    def step(self, tick_no: int):
        """Context for one tick; stops the trace after the armed budget."""
        if not self._active:
            return contextlib.nullcontext()
        import jax
        return jax.profiler.StepTraceAnnotation("raft_tick", step_num=tick_no)

    def after_tick(self) -> None:
        if not self._active:
            return
        self._remaining -= 1
        if self._remaining <= 0:
            import jax
            jax.profiler.stop_trace()
            self._release()

    def _release(self) -> None:
        self._active = False
        if self in _TRACE_OWNER:
            _TRACE_OWNER.remove(self)

    def close(self) -> None:
        if self._active:
            import jax
            try:
                jax.profiler.stop_trace()
            except RuntimeError:
                pass
            self._release()
