"""Metrics: counters, gauges and latency histograms for the node runtime.

The reference has no metrics beyond logback debug lines and a config block
feeding the health detector (SURVEY §5; support/RaftConfig.java:137-141) —
the survey explicitly calls for commits/sec, election counts and per-step
latency histograms in this build.  This module is dependency-free and
cheap on the hot path (a counter bump is a dict add; histogram observe is
a bisect into fixed log-spaced buckets).
"""

from __future__ import annotations

import bisect
import json
import re
import time
from typing import Dict, List, Optional

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str, prefix: str) -> str:
    """Sanitize a registry name into a Prometheus metric name."""
    return prefix + _NAME_RE.sub("_", name)


class Histogram:
    """Fixed log-spaced buckets (microseconds to minutes by default)."""

    def __init__(self, bounds: Optional[List[float]] = None):
        if bounds is None:
            # 2x-spaced: 1us .. ~2.2min.  (4x spacing made tick-latency
            # quantiles useless — a p50 of 1.2s reported as "4.19s".)
            bounds = [1e-6 * (2 ** i) for i in range(28)]
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)
        self.total = 0.0
        self.n = 0
        self.max = 0.0

    def observe(self, v: float) -> None:
        self.counts[bisect.bisect_right(self.bounds, v)] += 1
        self.total += v
        self.n += 1
        if v > self.max:
            self.max = v

    def reset(self) -> None:
        """Zero the histogram in place (e.g. a benchmark separating its
        measure phase from warmup/compile ticks)."""
        self.counts = [0] * len(self.counts)
        self.total = 0.0
        self.n = 0
        self.max = 0.0

    def quantile(self, q: float) -> float:
        """Upper bucket bound at quantile q (conservative estimate)."""
        if self.n == 0:
            return 0.0
        target = q * self.n
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                return self.bounds[i] if i < len(self.bounds) else self.max
        return self.max

    def summary(self) -> dict:
        return {
            "count": self.n,
            "mean": self.total / self.n if self.n else 0.0,
            "p50": self.quantile(0.5),
            "p99": self.quantile(0.99),
            "max": self.max,
        }


class Metrics:
    """Counter/gauge/histogram registry with dict-style counter access
    (``m["commits"] += 1`` and ``m.inc("commits")`` both work)."""

    def __init__(self):
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._t0 = time.monotonic()

    # counters ---------------------------------------------------------------
    def inc(self, name: str, delta: float = 1) -> None:
        self._counters[name] = self._counters.get(name, 0) + delta

    def __getitem__(self, name: str) -> float:
        return self._counters.get(name, 0)

    def __setitem__(self, name: str, value: float) -> None:
        self._counters[name] = value

    # gauges -----------------------------------------------------------------
    def gauge(self, name: str, value: float) -> None:
        self._gauges[name] = value

    # histograms -------------------------------------------------------------
    def histogram(self, name: str) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            h = self._histograms[name] = Histogram()
        return h

    def observe(self, name: str, v: float) -> None:
        self.histogram(name).observe(v)

    # reporting --------------------------------------------------------------
    def rates(self) -> Dict[str, float]:
        """Counters divided by registry lifetime (e.g. commits/sec)."""
        dt = max(time.monotonic() - self._t0, 1e-9)
        return {f"{k}_per_sec": v / dt for k, v in self._counters.items()}

    def to_dict(self) -> dict:
        return {
            "uptime_s": time.monotonic() - self._t0,
            "counters": dict(self._counters),
            "gauges": dict(self._gauges),
            "rates": self.rates(),
            "histograms": {k: h.summary()
                           for k, h in self._histograms.items()},
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    def render_prometheus(self, prefix: str = "raft_") -> str:
        """Prometheus text exposition format 0.0.4 of the whole registry.

        Counters render as ``<prefix><name>_total`` (counter), gauges as
        ``<prefix><name>`` (gauge), histograms as the standard cumulative
        ``_bucket{le=...}`` / ``_sum`` / ``_count`` triplet over the fixed
        log-spaced bounds.  Names are sanitized to the Prometheus charset;
        dependency-free (no client library) by design, like the rest of
        this module — serve it from any HTTP handler with content type
        ``text/plain; version=0.0.4``."""
        lines: List[str] = []
        for name in sorted(self._counters):
            m = _prom_name(name, prefix) + "_total"
            lines.append(f"# TYPE {m} counter")
            lines.append(f"{m} {self._counters[name]}")
        for name in sorted(self._gauges):
            m = _prom_name(name, prefix)
            lines.append(f"# TYPE {m} gauge")
            lines.append(f"{m} {self._gauges[name]}")
        for name in sorted(self._histograms):
            h = self._histograms[name]
            m = _prom_name(name, prefix)
            lines.append(f"# TYPE {m} histogram")
            cum = 0
            for bound, c in zip(h.bounds, h.counts):
                cum += c
                lines.append(f'{m}_bucket{{le="{bound:.6g}"}} {cum}')
            lines.append(f'{m}_bucket{{le="+Inf"}} {h.n}')
            lines.append(f"{m}_sum {h.total}")
            lines.append(f"{m}_count {h.n}")
        return "\n".join(lines) + "\n"
