"""Metrics: counters, gauges and latency histograms for the node runtime.

The reference has no metrics beyond logback debug lines and a config block
feeding the health detector (SURVEY §5; support/RaftConfig.java:137-141) —
the survey explicitly calls for commits/sec, election counts and per-step
latency histograms in this build.  This module is dependency-free and
cheap on the hot path (a counter bump is a dict add; histogram observe is
a bisect into fixed log-spaced buckets).
"""

from __future__ import annotations

import bisect
import json
import math
import re
import time
from typing import Dict, List, Optional

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str, prefix: str) -> str:
    """Sanitize a registry name into a Prometheus metric name."""
    return prefix + _NAME_RE.sub("_", name)


def _prom_value(v) -> str:
    """Render a sample value in exposition-format syntax.

    Python would print ``nan``/``inf``/``-inf``, which the format does not
    accept — the canonical spellings are ``NaN``/``+Inf``/``-Inf``.  A
    non-finite gauge (e.g. a rate over a zero interval) must not corrupt
    the whole scrape page."""
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    return str(v)


def escape_label_value(v: str) -> str:
    """Escape a label VALUE for ``name{label="<here>"}`` (backslash, quote
    and newline, per the exposition format's label escaping rules) — for
    handlers that render labeled series on top of this registry."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


class Histogram:
    """Fixed log-spaced buckets (microseconds to minutes by default).

    Thread contract — SINGLE WRITER, many readers.  ``observe`` (and
    ``reset``/``merge``) must only be called from one thread at a time;
    in the node runtime that is the tick thread: the striped host tier's
    W workers return their stage timings through the phase barrier and
    the tick thread observes the per-tick max (runtime/node.py striped
    phase), and the latency tracer's client-thread samples park in
    per-thread rings that the tick thread drains in ``harvest``
    (utils/latency.py).  Concurrent ``observe`` from two threads would
    lose increments (``counts[i] += 1`` is a read-modify-write) — grow a
    per-worker shard and fold it with ``merge`` instead.  Readers
    (HTTP scrape threads calling ``summary``/``quantile``/
    ``render_prometheus``) may race the writer freely: they take an
    atomic ``list(counts)`` snapshot and derive the sample count from
    its sum, so bucket series stay monotone even mid-observe.  The test
    suite enforces both halves (tests/test_latency.py)."""

    def __init__(self, bounds: Optional[List[float]] = None):
        if bounds is None:
            # 2x-spaced: 1us .. ~2.2min.  (4x spacing made tick-latency
            # quantiles useless — a p50 of 1.2s reported as "4.19s".)
            bounds = [1e-6 * (2 ** i) for i in range(28)]
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)
        self.total = 0.0
        self.n = 0
        self.max = 0.0

    def observe(self, v: float) -> None:
        self.counts[bisect.bisect_right(self.bounds, v)] += 1
        self.total += v
        self.n += 1
        if v > self.max:
            self.max = v

    def reset(self) -> None:
        """Zero the histogram in place (e.g. a benchmark separating its
        measure phase from warmup/compile ticks)."""
        self.counts = [0] * len(self.counts)
        self.total = 0.0
        self.n = 0
        self.max = 0.0

    def merge(self, other: "Histogram") -> None:
        """Fold another histogram's samples into this one (writer-side
        only — same single-writer contract as ``observe``).  Bounds must
        match; this is the shard-fold primitive for any future
        per-worker histogram sharding."""
        if other.bounds != self.bounds:
            raise ValueError("histogram bounds mismatch")
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.total += other.total
        self.n += other.n
        if other.max > self.max:
            self.max = other.max

    def quantile(self, q: float, _counts: Optional[List[int]] = None
                 ) -> float:
        """Upper bucket bound at quantile q (conservative estimate).
        Safe to call from reader threads: operates on an atomic snapshot
        of the counts (``_counts`` lets ``summary`` reuse one snapshot
        for all three quantiles)."""
        counts = list(self.counts) if _counts is None else _counts
        n = sum(counts)
        if n == 0:
            return 0.0
        target = q * n
        seen = 0
        for i, c in enumerate(counts):
            seen += c
            if seen >= target:
                return self.bounds[i] if i < len(self.bounds) else self.max
        return self.max

    def summary(self) -> dict:
        # One atomic counts snapshot serves count and every quantile, so
        # a scrape racing the writer reports an internally consistent
        # row; mean pairs it with a total read just after (the skew is
        # at most the samples observed in between — harmless for a
        # monitoring mean, and never a crash or negative value).
        counts = list(self.counts)
        n = sum(counts)
        return {
            "count": n,
            "mean": self.total / n if n else 0.0,
            "p50": self.quantile(0.5, counts),
            "p99": self.quantile(0.99, counts),
            "max": self.max,
        }


class Metrics:
    """Counter/gauge/histogram registry with dict-style counter access
    (``m["commits"] += 1`` and ``m.inc("commits")`` both work)."""

    def __init__(self):
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._t0 = time.monotonic()
        self._ckpt_counters: Dict[str, float] = {}
        self._ckpt_t = self._t0

    # counters ---------------------------------------------------------------
    def inc(self, name: str, delta: float = 1) -> None:
        self._counters[name] = self._counters.get(name, 0) + delta

    def __getitem__(self, name: str) -> float:
        return self._counters.get(name, 0)

    def __setitem__(self, name: str, value: float) -> None:
        self._counters[name] = value

    # gauges -----------------------------------------------------------------
    def gauge(self, name: str, value: float) -> None:
        self._gauges[name] = value

    # histograms -------------------------------------------------------------
    def histogram(self, name: str) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            h = self._histograms[name] = Histogram()
        return h

    def observe(self, name: str, v: float) -> None:
        self.histogram(name).observe(v)

    # reporting --------------------------------------------------------------
    def checkpoint(self) -> None:
        """Snapshot the counters as the baseline for windowed rates: a
        long-lived node's ``rates(since_last=True)`` then reports CURRENT
        throughput over the window since this call, not a lifetime
        average diluted by hours of history (the benchmark checkpoints at
        the start of its measure phase).  Race note: ``dict(d)`` is one
        atomic C call under the GIL, so a checkpoint racing the tick
        thread's counter bumps captures a point-in-time copy; the window
        between the copy and ``monotonic()`` only skews the first
        windowed rate by nanoseconds."""
        self._ckpt_counters = dict(self._counters)
        self._ckpt_t = time.monotonic()

    def rates(self, since_last: bool = False) -> Dict[str, float]:
        """Counters per second — over the registry lifetime, or (with
        ``since_last``) over the window since the last :meth:`checkpoint`
        (boot, if never checkpointed).  Iterates a dict snapshot: readers
        (HTTP scrape threads) race the tick thread's first-seen counter
        inserts, and dict(d) is one atomic C call under the GIL."""
        counters = dict(self._counters)
        if since_last:
            dt = max(time.monotonic() - self._ckpt_t, 1e-9)
            base = self._ckpt_counters
            return {f"{k}_per_sec": (v - base.get(k, 0)) / dt
                    for k, v in counters.items()}
        dt = max(time.monotonic() - self._t0, 1e-9)
        return {f"{k}_per_sec": v / dt for k, v in counters.items()}

    def breakdown(self, prefix: str = "tick_stage_") -> Dict[str, dict]:
        """Summaries of every histogram under ``prefix`` keyed by the bare
        stage name — the per-stage tick breakdown (scan-wait, wal, fsync,
        send, apply, maintain) the runtime observes each tick and the
        durable bench reports per run."""
        return {name[len(prefix):]: h.summary()
                for name, h in dict(self._histograms).items()
                if name.startswith(prefix)}

    def to_dict(self) -> dict:
        return {
            "uptime_s": time.monotonic() - self._t0,
            "counters": dict(self._counters),
            "gauges": dict(self._gauges),
            "rates": self.rates(),
            "histograms": {k: h.summary()
                           for k, h in dict(self._histograms).items()},
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    def render_prometheus(self, prefix: str = "raft_") -> str:
        """Prometheus text exposition format 0.0.4 of the whole registry.

        Counters render as ``<prefix><name>_total`` (counter), gauges as
        ``<prefix><name>`` (gauge), histograms as the standard cumulative
        ``_bucket{le=...}`` / ``_sum`` / ``_count`` triplet over the fixed
        log-spaced bounds.  Names are sanitized to the Prometheus charset;
        dependency-free (no client library) by design, like the rest of
        this module — serve it from any HTTP handler with content type
        ``text/plain; version=0.0.4``."""
        lines: List[str] = []
        # Dict snapshots: the renderer runs on HTTP scrape threads while
        # the tick thread inserts first-seen keys (atomic C-level copies
        # under the GIL — see rates()).
        counters = dict(self._counters)
        gauges = dict(self._gauges)
        histograms = dict(self._histograms)
        for name in sorted(counters):
            m = _prom_name(name, prefix) + "_total"
            lines.append(f"# TYPE {m} counter")
            lines.append(f"{m} {_prom_value(counters[name])}")
        for name in sorted(gauges):
            m = _prom_name(name, prefix)
            lines.append(f"# TYPE {m} gauge")
            lines.append(f"{m} {_prom_value(gauges[name])}")
        for name in sorted(histograms):
            h = histograms[name]
            m = _prom_name(name, prefix)
            lines.append(f"# TYPE {m} histogram")
            # Atomic counts snapshot with _count derived from its sum:
            # reading the live list while the tick thread observes could
            # render cum > h.n (read at a different instant), a
            # non-monotone bucket series scrapers reject.
            counts = list(h.counts)
            n = sum(counts)
            cum = 0
            for bound, c in zip(h.bounds, counts):
                cum += c
                lines.append(f'{m}_bucket{{le="{bound:.6g}"}} {cum}')
            lines.append(f'{m}_bucket{{le="+Inf"}} {n}')
            lines.append(f"{m}_sum {_prom_value(h.total)}")
            lines.append(f"{m}_count {n}")
        return "\n".join(lines) + "\n"


# ------------------------------------------------------------- validation --

_METRIC_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_VALUE = r"(?:[+-]?[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?|\+Inf|-Inf|NaN)"
_TYPE_LINE = re.compile(rf"^# TYPE ({_METRIC_NAME}) "
                        r"(counter|gauge|histogram|summary|untyped)$")
_SAMPLE_LINE = re.compile(
    rf"^({_METRIC_NAME})"
    rf"(?:\{{le=\"({_VALUE})\"\}})? ({_VALUE})$")


def validate_exposition(text: str) -> None:
    """Strict structural check of a text exposition-format page.

    Raises ``ValueError`` on: a line matching neither the TYPE nor the
    sample grammar (bad charset, malformed value — Python's ``nan``/
    ``inf`` spellings included), a duplicate TYPE line for one metric,
    ``le`` buckets out of ascending order, or a bucket series missing its
    ``+Inf`` terminator.  Deliberately stricter than a scraper needs —
    this is the round-trip oracle for :meth:`Metrics.render_prometheus`.
    """
    if not text.endswith("\n"):
        raise ValueError("exposition page must end with a newline")
    typed: set = set()
    le_seen: Dict[str, float] = {}
    for ln, line in enumerate(text.splitlines(), 1):
        if not line:
            continue
        t = _TYPE_LINE.match(line)
        if t:
            if t.group(1) in typed:
                raise ValueError(f"line {ln}: duplicate TYPE for "
                                 f"{t.group(1)}")
            typed.add(t.group(1))
            continue
        if line.startswith("#"):
            continue   # HELP / comment lines are free-form
        s = _SAMPLE_LINE.match(line)
        if s is None:
            raise ValueError(f"line {ln}: malformed sample: {line!r}")
        name, le, _val = s.group(1), s.group(2), s.group(3)
        if le is not None:
            prev = le_seen.get(name)
            cur = float(le)   # float() parses '+Inf'/'-Inf'/'NaN' natively
            if math.isnan(cur):
                raise ValueError(f"line {ln}: NaN le bucket")
            if prev is not None and not cur > prev:
                raise ValueError(f"line {ln}: le buckets not ascending "
                                 f"for {name}")
            le_seen[name] = cur
    for name, top in le_seen.items():
        if top != math.inf:
            raise ValueError(f"bucket series {name} missing +Inf")
