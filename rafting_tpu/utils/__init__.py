"""Utilities: metrics/observability for the node runtime."""

from .metrics import Histogram, Metrics

__all__ = ["Metrics", "Histogram"]
