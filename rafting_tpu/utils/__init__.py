"""Utilities: metrics/observability for the node runtime."""

from .metrics import (
    Histogram, Metrics, escape_label_value, validate_exposition,
)
from .tracelog import TraceLog

__all__ = ["Metrics", "Histogram", "TraceLog", "escape_label_value",
           "validate_exposition"]
