"""State-machine SPI and the apply dispatcher.

The user plug-point of the framework: a :class:`RaftMachine` per group
applies committed commands and supports checkpoint/recover — the contract
of the reference's RaftMachine interface (curioloop/rafting
command/RaftMachine.java:12-63) and MachineProvider SPI
(command/spi/MachineProvider.java:9-13).  The :class:`ApplyDispatcher`
consumes the device commit frontier and drives machines in log order —
the vectorized analog of RaftRoutine.commitState/applyEntry/applyCommand
(context/RaftRoutine.java:224-306).
"""

from .spi import Checkpoint, MachineProvider, RaftMachine  # noqa: F401
from .file_machine import FileMachine, FileMachineProvider  # noqa: F401
from .kv_machine import KVMachine, KVMachineProvider  # noqa: F401
from .dispatch import ApplyDispatcher  # noqa: F401
