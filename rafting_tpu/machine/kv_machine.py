"""KVMachine: an in-memory key-value state machine with file checkpoints.

The "real application" example machine: commands are simple serialized
ops (set/del), checkpoints dump the dict to a file.  Used by examples and
as the substrate under the admin meta-group's MVCC engine.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, Optional

from .spi import Checkpoint


class KVMachine:
    """Commands: JSON bytes {"op": "set"|"del", "k": str, "v": any}."""

    applies_empty = True   # election no-ops advance last_applied, no-op op

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.data: Dict[str, Any] = {}
        self._last_applied = 0
        if os.path.exists(path):
            with open(path) as f:
                dump = json.load(f)
            self.data = dump["data"]
            self._last_applied = dump["index"]

    def last_applied(self) -> int:
        return self._last_applied

    def apply(self, index: int, payload: bytes) -> Any:
        assert index == self._last_applied + 1
        if not payload:
            # Election-win no-op (machine/spi.py: empty commands are
            # harmless by contract).
            self._last_applied = index
            return None
        cmd = json.loads(payload)
        op = cmd.get("op")
        result = None
        if op == "set":
            self.data[cmd["k"]] = cmd["v"]
            result = cmd["v"]
        elif op == "del":
            result = self.data.pop(cmd["k"], None)
        elif op == "get":
            result = self.data.get(cmd["k"])
        self._last_applied = index
        return result

    def read(self, payload: bytes) -> Any:
        """Linearizable query (machine/spi.py read SPI): same JSON command
        vocabulary as apply, restricted to the read-only op — served off
        the log by the read plane once the apply frontier covers the
        quorum-confirmed ReadIndex."""
        cmd = json.loads(payload)
        if cmd.get("op") != "get":
            raise ValueError(f"read supports op=get only, got {cmd.get('op')!r}")
        return self.data.get(cmd["k"])

    def _dump(self, path: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"index": self._last_applied, "data": self.data}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def checkpoint(self, must_include: int) -> Checkpoint:
        assert self._last_applied >= must_include
        self._prune_ckpts()
        p = f"{self.path}.ckpt.{self._last_applied}"
        self._dump(p)
        return Checkpoint(path=p, index=self._last_applied)

    def _prune_ckpts(self) -> None:
        for p in glob.glob(f"{self.path}.ckpt.*"):
            try:
                os.unlink(p)
            except OSError:
                pass

    def recover(self, checkpoint: Checkpoint) -> None:
        with open(checkpoint.path) as f:
            dump = json.load(f)
        self.data = dump["data"]
        self._last_applied = dump["index"]
        self._dump(self.path)

    def close(self) -> None:
        self._dump(self.path)

    def destroy(self) -> None:
        self._prune_ckpts()
        for p in (self.path, self.path + ".tmp"):
            if os.path.exists(p):
                os.unlink(p)


class KVMachineProvider:
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def bootstrap(self, group: int) -> KVMachine:
        return KVMachine(os.path.join(self.root, f"kv_{group}.json"))
