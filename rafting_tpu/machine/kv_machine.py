"""KVMachine: an in-memory key-value state machine with file checkpoints.

The "real application" example machine: commands are simple serialized
ops (set/del), checkpoints dump the dict to a file.  Used by examples and
as the substrate under the admin meta-group's MVCC engine.

Since the transaction plane (runtime/txn.py) this machine is also the
reference 2PC PARTICIPANT and COORDINATOR substrate.  The txn command
vocabulary rides the ordinary replicated log — prepare/commit/abort are
just payloads, so participant durability and ordering come from Raft
itself, not from any side channel:

* participant ops — ``txn_prepare`` buffers a write-intent (the ops are
  NOT applied; their keys are locked under the txn id with a wall-clock
  deadline), ``txn_commit`` replays the buffered ops atomically and
  releases the locks, ``txn_abort`` drops the intent.  All three are
  idempotent, and commit/abort for an unknown txn are safe no-ops (the
  done-ledger records them so the invariant checker can tell a
  duplicate from a phantom).
* coordinator ops — ``txn_begin`` allocates a replicated, monotone txn
  id and records the participant set + deadline; ``txn_decide`` records
  COMMIT or ABORT with FIRST-WRITER-WINS semantics (a later conflicting
  decision returns the winner instead of flipping), which is what makes
  recovery races safe: whoever replicates the decision first — the live
  coordinator driver or a deadline-expiry resolver — wins, and everyone
  else converges on that answer.

Intent visibility: buffered intent ops touch ``self.intents`` only, so
both read paths (``get`` via apply and the :meth:`read` SPI) naturally
serve committed state — an uncommitted transaction is invisible, full
stop.  Plain single-key ops deliberately BYPASS the lock table (they
stay lock-free and last-writer-wins against txn commit order in the
log); transactional and plain traffic should use disjoint keyspaces,
which the transfer workloads do.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, List, Optional

from .spi import Checkpoint

# Ops a txn intent may buffer (replayed verbatim at commit).
_TXN_OPS = ("set", "del", "add", "incr")


class KVMachine:
    """Commands: JSON bytes {"op": "set"|"del"|"add"|"incr", "k": str, "v": any}
    plus the txn vocabulary in the module docstring.

    ``add`` appends to a list value — the chaos workload's observable-
    duplicate op: a client retry that double-applies shows up as two
    list elements, which the linearizability checker can then judge
    (testkit/linz.py).  ``incr`` adds a number to a counter (missing
    key counts as 0) — the bank-transfer workload's balance op.

    ``stale_reads=True`` is a TEST-ONLY defect knob: linearizable reads
    return each key's PREVIOUS value — the classic stale-read bug a
    correct ReadIndex/lease plane exists to prevent.  It proves the
    checker has teeth (tests/test_chaos.py drives it through the real
    read plane and demands a counterexample)."""

    applies_empty = True   # election no-ops advance last_applied, no-op op

    def __init__(self, path: str, stale_reads: bool = False,
                 group: int = -1):
        self.path = path
        self.stale_reads = stale_reads
        self.group = group
        self._prev: Dict[str, Any] = {}   # per-key previous value
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.data: Dict[str, Any] = {}
        # -- txn participant state (all checkpointed) --------------------
        self.intents: Dict[str, dict] = {}   # tid -> {ops, deadline, coord}
        self.locks: Dict[str, str] = {}      # key -> holding tid
        self.txn_done: Dict[str, str] = {}   # tid -> final disposition
        # -- txn coordinator state (this group AS the coordinator) -------
        self.txns: Dict[str, dict] = {}      # tid -> {parts, deadline, decision}
        self.txn_seq = 0                     # replicated monotone id counter
        self._last_applied = 0
        if os.path.exists(path):
            with open(path) as f:
                dump = json.load(f)
            self._load(dump)

    def _load(self, dump: dict) -> None:
        self.data = dump["data"]
        self._last_applied = dump["index"]
        # Pre-txn checkpoints lack these keys (backward compatible).
        self.intents = dump.get("intents", {})
        self.locks = dump.get("locks", {})
        self.txn_done = dump.get("txn_done", {})
        self.txns = dump.get("txns", {})
        self.txn_seq = dump.get("txn_seq", 0)

    def last_applied(self) -> int:
        return self._last_applied

    # -- plain op application (shared by direct apply and commit replay) --

    def _apply_op(self, cmd: dict) -> Any:
        op = cmd.get("op")
        if op == "set":
            self._prev[cmd["k"]] = self.data.get(cmd["k"])
            self.data[cmd["k"]] = cmd["v"]
            return cmd["v"]
        if op == "add":
            cur = self.data.get(cmd["k"])
            self._prev[cmd["k"]] = list(cur) if cur is not None else None
            lst = self.data.setdefault(cmd["k"], [])
            lst.append(cmd["v"])
            return len(lst)
        if op == "incr":
            cur = self.data.get(cmd["k"], 0)
            self._prev[cmd["k"]] = self.data.get(cmd["k"])
            self.data[cmd["k"]] = cur + cmd["v"]
            return self.data[cmd["k"]]
        if op == "del":
            self._prev[cmd["k"]] = self.data.get(cmd["k"])
            return self.data.pop(cmd["k"], None)
        if op == "get":
            return self.data.get(cmd["k"])
        return None

    def apply(self, index: int, payload: bytes) -> Any:
        assert index == self._last_applied + 1
        if not payload:
            # Election-win no-op (machine/spi.py: empty commands are
            # harmless by contract).
            self._last_applied = index
            return None
        cmd = json.loads(payload)
        op = cmd.get("op")
        if op in ("txn_prepare", "txn_commit", "txn_abort",
                  "txn_begin", "txn_decide"):
            result = self._apply_txn(op, cmd)
        else:
            result = self._apply_op(cmd)
        self._last_applied = index
        return result

    # -- 2PC vocabulary ----------------------------------------------------

    def _apply_txn(self, op: str, cmd: dict) -> Any:
        if op == "txn_prepare":
            return self._txn_prepare(cmd)
        if op == "txn_commit":
            return self._txn_finalize(cmd["txn"], "commit")
        if op == "txn_abort":
            return self._txn_finalize(cmd["txn"], "abort")
        if op == "txn_begin":
            return self._txn_begin(cmd)
        return self._txn_decide(cmd)

    def _txn_prepare(self, cmd: dict) -> dict:
        tid = cmd["txn"]
        done = self.txn_done.get(tid)
        if done is not None:
            # Already finalized here (a resolver beat a slow prepare, or
            # a retried prepare landed after commit).  Never re-lock.
            return {"prepared": False, "decision": done}
        if tid in self.intents:
            return {"prepared": True, "dup": True}
        ops = cmd.get("ops") or []
        for o in ops:
            if o.get("op") not in _TXN_OPS:
                return {"prepared": False,
                        "error": f"bad txn op {o.get('op')!r}"}
        for o in ops:
            holder = self.locks.get(o["k"])
            if holder is not None and holder != tid:
                # Immediate-conflict abort (no waiting => no deadlock).
                # Even a past-deadline holder is NOT stolen here: only a
                # replicated txn_abort may release it, so the resolver's
                # coordinator query stays the single source of truth.
                return {"prepared": False, "conflict": o["k"],
                        "holder": holder}
        self.intents[tid] = {"ops": ops,
                             "deadline": float(cmd.get("deadline", 0.0)),
                             "coord": int(cmd.get("coord", -1))}
        for o in ops:
            self.locks[o["k"]] = tid
        return {"prepared": True}

    def _txn_finalize(self, tid: str, decision: str) -> dict:
        prior = self.txn_done.get(tid)
        if prior is not None:
            # Idempotent; a conflicting retry reports the winner (never
            # flips — the coordinator's first-writer-wins decision is
            # what both callers replayed from).
            return {"done": prior, "applied": False,
                    "flip": prior != decision and not prior.startswith(decision)}
        intent = self.intents.pop(tid, None)
        if intent is not None:
            for o in intent["ops"]:
                if self.locks.get(o["k"]) == tid:
                    del self.locks[o["k"]]
            if decision == "commit":
                for o in intent["ops"]:
                    self._apply_op(o)
            self.txn_done[tid] = decision
            return {"done": decision, "applied": decision == "commit"}
        # No intent: a commit here would mean effects were LOST (the
        # prepare never replicated before the decision) — record it
        # distinctly so testkit/invariants.py can flag phantoms; aborts
        # without intents are the normal presumed-abort path.
        self.txn_done[tid] = "commit-noop" if decision == "commit" else "abort"
        return {"done": self.txn_done[tid], "applied": False}

    def _txn_begin(self, cmd: dict) -> dict:
        seq = self.txn_seq
        self.txn_seq += 1
        tid = f"x{self.group}.{seq}"
        self.txns[tid] = {"parts": list(cmd.get("parts") or []),
                          "deadline": float(cmd.get("deadline", 0.0)),
                          "decision": None}
        return {"txn": tid, "parts": self.txns[tid]["parts"]}

    def _txn_decide(self, cmd: dict) -> dict:
        tid = cmd["txn"]
        decision = cmd["decision"]
        assert decision in ("commit", "abort"), decision
        rec = self.txns.get(tid)
        if rec is None:
            # Decision for a txn this coordinator never began: a resolver
            # racing a begin that never replicated.  Recording it is safe
            # — nobody can have been told "commit" for an unbegun txn.
            rec = self.txns[tid] = {"parts": [], "deadline": 0.0,
                                    "decision": None}
        if rec["decision"] is None:
            rec["decision"] = decision
            return {"txn": tid, "decision": decision, "won": True}
        return {"txn": tid, "decision": rec["decision"], "won": False}

    # -- txn plane accessors (tick thread = machine single-writer) --------

    def expired_intents(self, now: float) -> List[dict]:
        """Intents whose deadline passed: the recovery sweep's input.
        Called on the tick thread, same single-writer as apply."""
        if not self.intents:
            return []
        return [{"txn": tid, "coord": rec["coord"],
                 "deadline": rec["deadline"]}
                for tid, rec in self.intents.items()
                if rec["deadline"] <= now]

    def txn_decision(self, tid: str) -> Optional[str]:
        rec = self.txns.get(tid)
        return rec["decision"] if rec else None

    def read(self, payload: bytes) -> Any:
        """Linearizable query (machine/spi.py read SPI): same JSON command
        vocabulary as apply, restricted to the read-only ops — served off
        the log by the read plane once the apply frontier covers the
        quorum-confirmed ReadIndex."""
        cmd = json.loads(payload)
        op = cmd.get("op")
        if op == "txn_status":
            # In-doubt recovery query against the coordinator group's
            # replicated decision log (runtime/txn.py resolver).
            tid = cmd["txn"]
            rec = self.txns.get(tid)
            return {"txn": tid, "known": rec is not None,
                    "decision": rec["decision"] if rec else None,
                    "parts": rec["parts"] if rec else []}
        if op != "get":
            raise ValueError(f"read supports op=get|txn_status only, "
                             f"got {op!r}")
        if self.stale_reads:
            # Injected defect (see class docstring): serve the previous
            # value, violating linearizability on purpose.
            return self._prev.get(cmd["k"], self.data.get(cmd["k"]))
        return self.data.get(cmd["k"])

    def _dump(self, path: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"index": self._last_applied, "data": self.data,
                       "intents": self.intents, "locks": self.locks,
                       "txn_done": self.txn_done, "txns": self.txns,
                       "txn_seq": self.txn_seq}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def checkpoint(self, must_include: int) -> Checkpoint:
        assert self._last_applied >= must_include
        self._prune_ckpts()
        p = f"{self.path}.ckpt.{self._last_applied}"
        self._dump(p)
        return Checkpoint(path=p, index=self._last_applied)

    def _prune_ckpts(self) -> None:
        for p in glob.glob(f"{self.path}.ckpt.*"):
            try:
                os.unlink(p)
            except OSError:
                pass

    def recover(self, checkpoint: Checkpoint) -> None:
        with open(checkpoint.path) as f:
            dump = json.load(f)
        self._load(dump)
        self._dump(self.path)

    def close(self) -> None:
        self._dump(self.path)

    def destroy(self) -> None:
        self._prune_ckpts()
        for p in (self.path, self.path + ".tmp"):
            if os.path.exists(p):
                os.unlink(p)


class KVMachineProvider:
    def __init__(self, root: str, stale_reads: bool = False):
        self.root = root
        self.stale_reads = stale_reads
        os.makedirs(root, exist_ok=True)

    def bootstrap(self, group: int) -> KVMachine:
        return KVMachine(os.path.join(self.root, f"kv_{group}.json"),
                         stale_reads=self.stale_reads, group=group)
