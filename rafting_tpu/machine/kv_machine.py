"""KVMachine: an in-memory key-value state machine with file checkpoints.

The "real application" example machine: commands are simple serialized
ops (set/del), checkpoints dump the dict to a file.  Used by examples and
as the substrate under the admin meta-group's MVCC engine.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, Optional

from .spi import Checkpoint


class KVMachine:
    """Commands: JSON bytes {"op": "set"|"del"|"add", "k": str, "v": any}.

    ``add`` appends to a list value — the chaos workload's observable-
    duplicate op: a client retry that double-applies shows up as two
    list elements, which the linearizability checker can then judge
    (testkit/linz.py).

    ``stale_reads=True`` is a TEST-ONLY defect knob: linearizable reads
    return each key's PREVIOUS value — the classic stale-read bug a
    correct ReadIndex/lease plane exists to prevent.  It proves the
    checker has teeth (tests/test_chaos.py drives it through the real
    read plane and demands a counterexample)."""

    applies_empty = True   # election no-ops advance last_applied, no-op op

    def __init__(self, path: str, stale_reads: bool = False):
        self.path = path
        self.stale_reads = stale_reads
        self._prev: Dict[str, Any] = {}   # per-key previous value
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.data: Dict[str, Any] = {}
        self._last_applied = 0
        if os.path.exists(path):
            with open(path) as f:
                dump = json.load(f)
            self.data = dump["data"]
            self._last_applied = dump["index"]

    def last_applied(self) -> int:
        return self._last_applied

    def apply(self, index: int, payload: bytes) -> Any:
        assert index == self._last_applied + 1
        if not payload:
            # Election-win no-op (machine/spi.py: empty commands are
            # harmless by contract).
            self._last_applied = index
            return None
        cmd = json.loads(payload)
        op = cmd.get("op")
        result = None
        if op == "set":
            self._prev[cmd["k"]] = self.data.get(cmd["k"])
            self.data[cmd["k"]] = cmd["v"]
            result = cmd["v"]
        elif op == "add":
            cur = self.data.get(cmd["k"])
            self._prev[cmd["k"]] = list(cur) if cur is not None else None
            lst = self.data.setdefault(cmd["k"], [])
            lst.append(cmd["v"])
            result = len(lst)
        elif op == "del":
            self._prev[cmd["k"]] = self.data.get(cmd["k"])
            result = self.data.pop(cmd["k"], None)
        elif op == "get":
            result = self.data.get(cmd["k"])
        self._last_applied = index
        return result

    def read(self, payload: bytes) -> Any:
        """Linearizable query (machine/spi.py read SPI): same JSON command
        vocabulary as apply, restricted to the read-only op — served off
        the log by the read plane once the apply frontier covers the
        quorum-confirmed ReadIndex."""
        cmd = json.loads(payload)
        if cmd.get("op") != "get":
            raise ValueError(f"read supports op=get only, got {cmd.get('op')!r}")
        if self.stale_reads:
            # Injected defect (see class docstring): serve the previous
            # value, violating linearizability on purpose.
            return self._prev.get(cmd["k"], self.data.get(cmd["k"]))
        return self.data.get(cmd["k"])

    def _dump(self, path: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"index": self._last_applied, "data": self.data}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def checkpoint(self, must_include: int) -> Checkpoint:
        assert self._last_applied >= must_include
        self._prune_ckpts()
        p = f"{self.path}.ckpt.{self._last_applied}"
        self._dump(p)
        return Checkpoint(path=p, index=self._last_applied)

    def _prune_ckpts(self) -> None:
        for p in glob.glob(f"{self.path}.ckpt.*"):
            try:
                os.unlink(p)
            except OSError:
                pass

    def recover(self, checkpoint: Checkpoint) -> None:
        with open(checkpoint.path) as f:
            dump = json.load(f)
        self.data = dump["data"]
        self._last_applied = dump["index"]
        self._dump(self.path)

    def close(self) -> None:
        self._dump(self.path)

    def destroy(self) -> None:
        self._prune_ckpts()
        for p in (self.path, self.path + ".tmp"):
            if os.path.exists(p):
                os.unlink(p)


class KVMachineProvider:
    def __init__(self, root: str, stale_reads: bool = False):
        self.root = root
        self.stale_reads = stale_reads
        os.makedirs(root, exist_ok=True)

    def bootstrap(self, group: int) -> KVMachine:
        return KVMachine(os.path.join(self.root, f"kv_{group}.json"),
                         stale_reads=self.stale_reads)
