"""SPI contracts: RaftMachine, MachineProvider, Checkpoint."""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Protocol, runtime_checkable


@dataclasses.dataclass(frozen=True)
class Checkpoint:
    """A durable state-machine snapshot: file path + the log index it
    includes (reference RaftMachine.Checkpoint, command/RaftMachine.java:18-28)."""
    path: str
    index: int


@runtime_checkable
class RaftMachine(Protocol):
    """Per-group replicated state machine (command/RaftMachine.java:12-63).

    Contract:
    * :meth:`apply` is called exactly once per committed index, in index
      order, starting at ``last_applied() + 1``.  It must be atomic: apply
      fully or raise (a raise halts the group's apply frontier; the
      dispatcher retries later — reference RetryCommandException semantics,
      support/anomaly/RetryCommandException.java:10-25).
    * ``applies_empty`` (class attribute, default False): committed
      payloads may be EMPTY (``b""``) — a freshly elected leader appends
      one empty no-op entry (Raft §8 liveness, core/step.py phase 3).  A
      machine that sets ``applies_empty = True`` opts into seeing them
      (apply it as a harmless command, return anything) and keeps the
      strictly contiguous index stream.  WITHOUT the opt-in the
      dispatcher short-circuits empty payloads — the machine never sees
      them, its ``last_applied`` may lag the group frontier by trailing
      no-ops, and the index stream it observes has gaps at election
      no-ops (still strictly increasing).  This protects third-party
      machines that unconditionally parse payloads (e.g. ``json.loads``)
      from freezing their group on every election; the dispatcher logs
      loudly (once) when it engages.  Every in-tree machine opts in.
    * :meth:`checkpoint` produces a durable snapshot whose index is at
      least ``must_include`` (may block; called off the apply path).
    * :meth:`recover` atomically replaces state from a checkpoint.
    * :meth:`apply_batch` (optional — the dispatcher falls back to
      per-entry :meth:`apply` when absent): apply a CONTIGUOUS run of
      committed entries starting at ``start_index`` and return their
      results in order.  May return a SHORTER list than the input if an
      entry fails — the machine must have applied exactly the returned
      prefix; the dispatcher then retries the failing entry through
      :meth:`apply` for full diagnostics.  Lets a machine amortize
      per-entry overhead (one lock/transaction/fsync per batch — the
      analog of the reference's batched applyCommand loop over a commit
      advance, context/RaftRoutine.java:261-306).  CAUTION: a subclass
      overriding :meth:`apply` on a base that defines ``apply_batch``
      must override ``apply_batch`` too, or the dispatcher's batch path
      will bypass the override.
    * :meth:`apply_run` (optional, preferred over ``apply_batch`` when
      present): the ARENA variant — payload bytes arrive as contiguous
      buffer pieces plus a uint32 length vector instead of a per-entry
      list, so a machine that can consume slices (or ignores payloads)
      pays ZERO per-entry materialization.  Same shorter-prefix failure
      contract as ``apply_batch``, and the same caution about
      overriding ``apply``.
    * :meth:`read` (optional): serve a LINEARIZABLE QUERY against current
      machine state without going through the log (the read plane,
      core/step.py phase 8b: the runtime only calls this once the group's
      apply frontier covers the query's quorum-confirmed ReadIndex).
      Must not mutate state.  Called on the tick thread (the same
      single-writer thread as ``apply``), so no extra locking is needed.
      A machine WITHOUT ``read`` still gets linearizable reads: the
      runtime resolves the read future with the ReadIndex itself (the
      linearization point), which callers can pair with their own state
      access.
    * :meth:`expired_intents` (optional): 2PC participant hook.  A
      machine that implements the transaction vocabulary (see
      machine/kv_machine.py: ``txn_prepare`` buffers a write-intent
      under key locks with a wall-clock deadline; ``txn_commit`` /
      ``txn_abort`` finalize it — all replicated as ordinary log
      payloads, so the machine needs NO extra durability) exposes
      ``expired_intents(now) -> [{"txn", "coord", "deadline"}, ...]``
      so the runtime's recovery sweep (runtime/txn.py, driven off the
      tick loop on the leader) can find intents whose coordinator went
      quiet and resolve them by querying the coordinator group's
      replicated decision log.  Called on the tick thread (machine
      single-writer); must not mutate state and should be O(1) when no
      intents are live.  Machines without the hook simply never
      participate in cross-group transactions.  Contract obligations
      for implementers: prepare/commit/abort must be IDEMPOTENT
      (recovery replays them), commit/abort for an unknown txn must be
      safe no-ops, a finalized txn must never re-lock, and buffered
      intents must be INVISIBLE to both read paths (apply-side reads
      and this SPI's :meth:`read`) until commit.
    """

    applies_empty: bool = False

    def last_applied(self) -> int: ...

    def apply(self, index: int, payload: bytes) -> Any: ...

    def checkpoint(self, must_include: int) -> Checkpoint: ...

    def recover(self, checkpoint: Checkpoint) -> None: ...

    def close(self) -> None: ...

    def destroy(self) -> None: ...


class MachineProvider(Protocol):
    """Factory for per-group machines (command/spi/MachineProvider.java:9-13)."""

    def bootstrap(self, group: int) -> RaftMachine: ...
