"""ApplyDispatcher: drives state machines from the device commit frontier.

The vectorized analog of the reference's apply loop
(RaftRoutine.commitState/applyEntry/applyCommand,
context/RaftRoutine.java:224-306): after each device tick the runtime
hands the dispatcher the committed-index frontier for all groups; the
dispatcher applies any newly committed entries in order, completes client
promises, and reports apply progress (fed back to the device `applied`
lanes and into the snapshot maintain policy).

Halt/resume mirrors the restore dance (RaftRoutine.restoreCheckpoint
commitVersion CAS MACHINE_HALT, context/RaftRoutine.java:482-541): while a
group's snapshot is being installed its applies are frozen, then resumed
at the recovered frontier.
"""

from __future__ import annotations

import logging
from concurrent.futures import Future
from typing import Callable, Dict, Optional

import numpy as np

from .spi import MachineProvider, RaftMachine

log = logging.getLogger(__name__)


class ApplyDispatcher:
    def __init__(self, provider: MachineProvider, payload_fn,
                 on_applied: Optional[Callable[[int, int], None]] = None,
                 payload_window_fn=None):
        """payload_fn(group, index) -> bytes | None (usually LogStore.payload).
        payload_window_fn(group, start, n) -> [bytes|None]: batched variant
        (LogStore.payloads_window) — the apply loop fetches each group's
        newly committed window in one call when provided.

        on_applied(group, new_last_applied): progress hook (maintain policy).
        """
        self._provider = provider
        self._payload = payload_fn
        self._payload_window = payload_window_fn
        self._machines: Dict[int, RaftMachine] = {}
        self._halted: Dict[int, bool] = {}
        # Promises keyed group -> {index -> Future}: the apply loop skips
        # promise bookkeeping entirely for groups with none registered
        # (every group on a follower node), and abort scans one group's
        # map, not every promise on the node.
        self._promises: Dict[int, Dict[int, Future]] = {}
        self._on_applied = on_applied
        self._retry_counts: Dict[tuple, int] = {}
        # Numpy mirror of every machine's last_applied: advance() visits
        # only lanes whose commit frontier moved past it, so per-tick cost
        # scales with progress, not with total group count (VERDICT r1 #8).
        # Lazily sized from the first commit array; always <= the machine's
        # true last_applied is the invariant that makes skipping safe.
        self._applied_arr: Optional[np.ndarray] = None

    def _applied_mirror(self, n: int) -> np.ndarray:
        a = self._applied_arr
        if a is None or len(a) < n:
            a = np.zeros(n, np.int64)
            for g, m in self._machines.items():
                if g < n:
                    a[g] = m.last_applied()
            self._applied_arr = a
        return a

    def machine(self, g: int) -> RaftMachine:
        m = self._machines.get(g)
        if m is None:
            m = self._machines[g] = self._provider.bootstrap(g)
            if self._applied_arr is not None and g < len(self._applied_arr):
                self._applied_arr[g] = m.last_applied()
        return m

    def applied(self, g: int) -> int:
        return self.machine(g).last_applied()

    # -- client promises ----------------------------------------------------

    def register_promise(self, g: int, index: int, fut: Future) -> None:
        """A client command was accepted at (g, index); complete its future
        with the apply result (reference: RaftContext promise map keyed by
        EntryKey, context/RaftContext.java:223-237)."""
        self._promises.setdefault(g, {})[index] = fut

    def abort_promises(self, g: int, err: Exception) -> None:
        """Leadership lost: fail outstanding promises (reference
        Leader ctor abortPromise, context/RaftContext.java:165-187)."""
        pg = self._promises.pop(g, None)
        if pg:
            for f in pg.values():
                if not f.done():
                    f.set_exception(err)

    # -- snapshot halt/resume ------------------------------------------------

    def halt(self, g: int) -> None:
        self._halted[g] = True

    def unhalt(self, g: int) -> None:
        """Abort a halt without a recover (failed install)."""
        self._halted[g] = False

    def drop_machine(self, g: int, destroy: bool = False) -> None:
        """Forget a group's machine (group closed/destroyed; reference
        destroyContext, context/ContextManager.java:139-167)."""
        m = self._machines.pop(g, None)
        if m is not None:
            (m.destroy if destroy else m.close)()
        self._halted.pop(g, None)
        if self._applied_arr is not None and g < len(self._applied_arr):
            self._applied_arr[g] = 0
        for key in [k for k in self._retry_counts if k[0] == g]:
            del self._retry_counts[key]

    def resume_from(self, g: int, checkpoint) -> None:
        """Install a snapshot into the machine and resume applies.

        Promises at or below the checkpoint index can never be completed by
        an apply (the machine jumps over them), so they are aborted — their
        commands committed cluster-wide but the result is unobservable here.
        """
        self.machine(g).recover(checkpoint)
        if self._applied_arr is not None and g < len(self._applied_arr):
            self._applied_arr[g] = self.machine(g).last_applied()
        pg = self._promises.get(g)
        if pg:
            for idx in [i for i in pg if i <= checkpoint.index]:
                f = pg.pop(idx)
                if not f.done():
                    f.set_exception(RuntimeError(
                        "entry applied via snapshot; result unavailable"))
        self._halted[g] = False

    # -- the apply loop -----------------------------------------------------

    def advance(self, commit: np.ndarray,
                groups: Optional[np.ndarray] = None,
                max_per_group: int = 0) -> None:
        """Apply newly committed entries.  `commit` is the [G] frontier;
        `groups` optionally restricts which lanes are live (active mask or
        index list).  `max_per_group` bounds work per call (0 = no bound)."""
        mirror = self._applied_mirror(len(commit))
        behind = commit > mirror[:len(commit)]
        if groups is None:
            gs = np.nonzero(behind)[0]
        elif groups.dtype == bool:
            gs = np.nonzero(groups & behind)[0]
        else:
            gs = groups
        retries = self._retry_counts
        for g in gs:
            g = int(g)
            if self._halted.get(g):
                continue
            m = self.machine(g)
            apply_fn = m.apply
            pg = self._promises.get(g)
            target = int(commit[g])
            before = m.last_applied()
            idx = before + 1
            hi = target if max_per_group <= 0 \
                else min(target, idx + max_per_group - 1)
            # Probe the first index before prefetching the window: a group
            # whose frontier is far ahead of its local store (snapshot
            # pending) must cost one lookup per tick, not one per missing
            # entry.  The probe's hit is cached, so no duplicate work.
            window = None
            if (self._payload_window is not None and hi >= idx
                    and self._payload(g, idx) is not None):
                window = self._payload_window(g, idx, hi - idx + 1)
            # Fast path: machines exposing apply_batch (SPI, spi.py) take
            # the locally-available contiguous prefix in ONE call; a short
            # return (failed entry) falls through to the per-entry loop,
            # which retries it with full diagnostics.
            batch_fn = getattr(m, "apply_batch", None)
            if window is not None and batch_fn is not None:
                n_have = 0
                for p in window:
                    if p is None:
                        break
                    n_have += 1
                if n_have:
                    try:
                        results = batch_fn(idx, window[:n_have])
                    except Exception as e:
                        # A raising apply_batch must not kill the whole
                        # tick (the per-entry path catches and retries).
                        # The machine may have applied a prefix before
                        # raising: resync from its own frontier, then let
                        # the per-entry loop below retry the failing
                        # entry with full diagnostics.
                        log.warning("apply_batch failed g=%d idx=%d: %s "
                                    "(falling back to per-entry)", g, idx, e)
                        results = []
                    if pg:
                        for k, r in enumerate(results):
                            fut = pg.pop(idx + k, None)
                            if fut is not None and not fut.done():
                                fut.set_result(r)
                    if retries:
                        for k in range(len(results)):
                            retries.pop((g, idx + k), None)
                    idx += len(results)
                    la = m.last_applied()
                    if la >= idx:
                        # The machine advanced past the reported results
                        # (mid-batch failure after a partial apply, or a
                        # contract violation): those entries DID apply but
                        # their results are unobservable.  Their promises
                        # must not hang forever — fail them explicitly,
                        # like the snapshot-jump path (resume_from).
                        if pg:
                            for i in [i for i in pg if idx <= i <= la]:
                                fut = pg.pop(i)
                                if not fut.done():
                                    fut.set_exception(RuntimeError(
                                        "entry applied; result unavailable"
                                        " (apply_batch failed mid-batch)"))
                        if retries:
                            for key in [k for k in retries
                                        if k[0] == g and idx <= k[1] <= la]:
                                del retries[key]
                        idx = la + 1
            while idx <= hi:
                payload = (window[idx - before - 1] if window is not None
                           else self._payload(g, idx))
                if payload is None:
                    # Frontier ahead of locally stored entries (e.g. device
                    # committed via snapshot milestone); the machine must
                    # catch up via recover, not apply.
                    break
                try:
                    result = apply_fn(idx, payload)
                except Exception as e:
                    # Retry next round (reference RetryCommandException,
                    # RaftRoutine.java:288-300).  A deterministic failure
                    # freezes the group's apply frontier on purpose —
                    # skipping a committed entry would diverge replicas —
                    # but escalate so the operator sees a stuck group.
                    n = retries[(g, idx)] = retries.get((g, idx), 0) + 1
                    lvl = log.error if n in (10, 100) or n % 1000 == 0 \
                        else log.warning
                    lvl("apply failed g=%d idx=%d (attempt %d): %s",
                        g, idx, n, e)
                    break
                if retries:
                    retries.pop((g, idx), None)
                if pg:
                    fut = pg.pop(idx, None)
                    if fut is not None and not fut.done():
                        fut.set_result(result)
                idx += 1
            # Mirror tracks true machine progress; on a payload gap or a
            # failed apply it simply stays behind and the lane is revisited
            # next tick.
            mirror[g] = idx - 1 if idx - 1 > before else before
            if self._on_applied is not None and idx - 1 > before:
                self._on_applied(g, idx - 1)

    def applied_frontier(self, n_groups: int) -> np.ndarray:
        out = np.zeros(n_groups, np.int32)
        a = self._applied_arr
        if a is not None and len(a) >= n_groups:
            return a[:n_groups].astype(np.int32)
        for g, m in self._machines.items():
            if g < n_groups:
                out[g] = m.last_applied()
        return out

    def close(self) -> None:
        for m in self._machines.values():
            m.close()
        self._machines.clear()
