"""ApplyDispatcher: drives state machines from the device commit frontier.

The vectorized analog of the reference's apply loop
(RaftRoutine.commitState/applyEntry/applyCommand,
context/RaftRoutine.java:224-306): after each device tick the runtime
hands the dispatcher the committed-index frontier for all groups; the
dispatcher applies any newly committed entries in order, completes client
promises, and reports apply progress (fed back to the device `applied`
lanes and into the snapshot maintain policy).

Halt/resume mirrors the restore dance (RaftRoutine.restoreCheckpoint
commitVersion CAS MACHINE_HALT, context/RaftRoutine.java:482-541): while a
group's snapshot is being installed its applies are frozen, then resumed
at the recovered frontier.
"""

from __future__ import annotations

import bisect
import logging
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional

import numpy as np

from .spi import MachineProvider, RaftMachine
from ..utils.latency import APPLIED as _APPLIED

log = logging.getLogger(__name__)


class _SingleSink:
    """Adapts a plain Future to the promise-sink protocol (the
    ``register_promise`` compatibility path and internal single-command
    promises): ``_complete(k, result)`` / ``_fail(err)``."""

    __slots__ = ("fut",)

    def __init__(self, fut: Future):
        self.fut = fut

    def _complete(self, k: int, result) -> None:
        if not self.fut.done():
            self.fut.set_result(result)

    def _fail(self, err: Exception) -> None:
        if not self.fut.done():
            self.fut.set_exception(err)


class _Range:
    """One registered promise range: entries [start, start+n) of a group
    map to sink slots [k0, k0+n).  Mutated in place as applies consume the
    prefix (ranges only ever shrink from the front — applies are
    contiguous — or get failed wholesale)."""

    __slots__ = ("start", "n", "sink", "k0")

    def __init__(self, start: int, n: int, sink, k0: int):
        self.start = start
        self.n = n
        self.sink = sink
        self.k0 = k0


class ApplyDispatcher:
    def __init__(self, provider: MachineProvider, payload_fn,
                 on_applied: Optional[Callable[[int, int], None]] = None,
                 payload_window_fn=None, payload_runs_fn=None):
        """payload_fn(group, index) -> bytes | None (usually LogStore.payload).
        payload_window_fn(group, start, n) -> [bytes|None]: batched variant
        (LogStore.payloads_window) — the apply loop fetches each group's
        newly committed window in one call when provided.
        payload_runs_fn(group, start, n) -> (pieces, lens) | None: the
        arena variant (LogStore.payload_runs) feeding machines that
        implement ``apply_run`` with buffer slices — zero per-entry
        materialization on the apply hot path.

        on_applied(group, new_last_applied): progress hook (maintain policy).
        """
        self._provider = provider
        self._payload = payload_fn
        self._payload_window = payload_window_fn
        self._payload_runs = payload_runs_fn
        self._machines: Dict[int, RaftMachine] = {}
        self._halted: Dict[int, bool] = {}
        # Promises keyed group -> sorted list of _Range records: a whole
        # accepted client BATCH registers as ONE range (start, n, sink)
        # instead of n dict entries — promise bookkeeping cost per tick is
        # O(ranges touched), not O(entries) (the per-entry Future dict was
        # ~15% of the durable tick at 32k groups).  The apply loop skips
        # bookkeeping entirely for groups with none registered (every
        # group on a follower node), and abort scans one group's list.
        self._promises: Dict[int, List[_Range]] = {}
        self._on_applied = on_applied
        self._retry_counts: Dict[tuple, int] = {}
        # Empty-payload (election no-op) guard: machines that do not set
        # ``applies_empty = True`` (machine/spi.py) never see empty
        # payloads — the dispatcher skips them and records the highest
        # skipped index per group here, so the apply frontier keeps
        # advancing past no-ops the machine's own last_applied cannot
        # cover.  Invariant: _skip_hi[g], when present, is an index the
        # dispatcher fully processed (applied or skipped) up to.
        self._skip_hi: Dict[int, int] = {}
        self._warned_empty: set = set()
        # Per-group short-circuit tally behind the warning above — the
        # runtime surfaces the sum as the ``empty_apply_skips`` gauge so
        # a lagging last_applied stays diagnosable after the once-per-
        # class log line scrolled away.  Keyed by group so the striped
        # workers' disjoint masks never race an increment.
        self._empty_skip_n: Dict[int, int] = {}
        # Numpy mirror of every machine's last_applied: advance() visits
        # only lanes whose commit frontier moved past it, so per-tick cost
        # scales with progress, not with total group count (VERDICT r1 #8).
        # Lazily sized from the first commit array; always <= the machine's
        # true last_applied is the invariant that makes skipping safe.
        self._applied_arr: Optional[np.ndarray] = None

    @property
    def empty_skips(self) -> int:
        """Total election no-ops short-circuited for machines without the
        ``applies_empty`` opt-in (machine/spi.py) — surfaced by the
        runtime as the ``empty_apply_skips`` gauge."""
        return sum(self._empty_skip_n.values())

    def _applied_mirror(self, n: int) -> np.ndarray:
        a = self._applied_arr
        if a is None or len(a) < n:
            a = np.zeros(n, np.int64)
            for g, m in self._machines.items():
                if g < n:
                    a[g] = m.last_applied()
            self._applied_arr = a
        return a

    def machine(self, g: int) -> RaftMachine:
        m = self._machines.get(g)
        if m is None:
            m = self._machines[g] = self._provider.bootstrap(g)
            if self._applied_arr is not None and g < len(self._applied_arr):
                self._applied_arr[g] = m.last_applied()
        return m

    def applied(self, g: int) -> int:
        return self.machine(g).last_applied()

    # -- client promises ----------------------------------------------------

    def register_promise(self, g: int, index: int, fut: Future) -> None:
        """A client command was accepted at (g, index); complete its future
        with the apply result (reference: RaftContext promise map keyed by
        EntryKey, context/RaftContext.java:223-237)."""
        self.register_promise_range(g, index, 1, _SingleSink(fut), 0)

    def register_promise_range(self, g: int, start: int, n: int,
                               sink, k0: int) -> None:
        """Register a whole accepted span in one record: entries
        [start, start+n) complete sink slots [k0, k0+n).  ``sink`` speaks
        ``_complete(k, result)`` / ``_fail(err)`` (BatchSubmit / _SingleSink).
        Ranges are kept sorted by start; within one leadership accepts are
        monotonic so the common case is an append."""
        lst = self._promises.setdefault(g, [])
        r = _Range(start, n, sink, k0)
        if lst and lst[-1].start >= start:
            bisect.insort(lst, r, key=lambda x: x.start)
        else:
            lst.append(r)

    def _complete_run(self, g: int, lo: int, results: list) -> None:
        """Entries [lo, lo+len(results)) applied with these results:
        complete every overlapping promise slot.  Ranges are contiguous
        and the apply frontier moves contiguously, so overlaps consume
        range PREFIXES; a consumed range is dropped, a partial one shrinks
        in place."""
        lst = self._promises.get(g)
        if not lst:
            return
        hi = lo + len(results) - 1
        keep: List[_Range] = []
        for r in lst:
            end = r.start + r.n - 1
            if end < lo or r.start > hi:
                keep.append(r)
                continue
            a, b = max(r.start, lo), min(end, hi)
            comp = r.sink._complete
            base_k = r.k0 + (a - r.start)
            base_r = a - lo
            sp = getattr(r.sink, "span", None)   # sampled lifecycle span
            if sp is not None and base_k <= sp.k <= base_k + (b - a):
                # Stamped BEFORE the completion loop: the batch's ack
                # stamp fires inside _complete when its last slot lands,
                # and applied must precede acked (utils/latency.py).
                sp.mark(_APPLIED)
            for j in range(b - a + 1):
                comp(base_k + j, results[base_r + j])
            if b < end:
                # suffix survives (apply stopped mid-range)
                taken = b - r.start + 1
                r.start += taken
                r.n -= taken
                r.k0 += taken
                keep.append(r)
            # a > r.start cannot leave a live prefix: applies are
            # contiguous from the frontier, so any slot below `a` was
            # already consumed (its range shrank past it).
        if keep:
            self._promises[g] = keep
        else:
            del self._promises[g]

    def _fail_span(self, g: int, lo: int, hi: int, err: Exception) -> None:
        """Entries in [lo, hi] can never deliver a result (snapshot jump /
        mid-batch apply divergence): fail their sinks.  A sink failed here
        reports which slots already completed (BatchAbortedError contract);
        slots above `hi` stay registered so later applies still record
        their results into the (already failed) batch — harmless, and it
        mirrors the old per-entry map, which also kept them."""
        lst = self._promises.get(g)
        if not lst:
            return
        keep: List[_Range] = []
        for r in lst:
            end = r.start + r.n - 1
            if end < lo or r.start > hi:
                keep.append(r)
                continue
            r.sink._fail(err)
            if end > hi:
                taken = hi - r.start + 1
                r.start += taken
                r.n -= taken
                r.k0 += taken
                keep.append(r)
        if keep:
            self._promises[g] = keep
        else:
            del self._promises[g]

    def abort_promises(self, g: int, err: Exception) -> None:
        """Leadership lost: fail outstanding promises (reference
        Leader ctor abortPromise, context/RaftContext.java:165-187)."""
        lst = self._promises.pop(g, None)
        if lst:
            for r in lst:
                r.sink._fail(err)

    # -- snapshot halt/resume ------------------------------------------------

    def halt(self, g: int) -> None:
        self._halted[g] = True

    def unhalt(self, g: int) -> None:
        """Abort a halt without a recover (failed install)."""
        self._halted[g] = False

    def drop_machine(self, g: int, destroy: bool = False) -> None:
        """Forget a group's machine (group closed/destroyed; reference
        destroyContext, context/ContextManager.java:139-167)."""
        m = self._machines.pop(g, None)
        if m is not None:
            (m.destroy if destroy else m.close)()
        self._halted.pop(g, None)
        self._skip_hi.pop(g, None)
        if self._applied_arr is not None and g < len(self._applied_arr):
            self._applied_arr[g] = 0
        for key in [k for k in self._retry_counts if k[0] == g]:
            del self._retry_counts[key]

    def resume_from(self, g: int, checkpoint) -> None:
        """Install a snapshot into the machine and resume applies.

        Promises at or below the checkpoint index can never be completed by
        an apply (the machine jumps over them), so they are aborted — their
        commands committed cluster-wide but the result is unobservable here.
        """
        self.machine(g).recover(checkpoint)
        if self._applied_arr is not None and g < len(self._applied_arr):
            self._applied_arr[g] = self.machine(g).last_applied()
        if self._skip_hi.get(g, 0) <= checkpoint.index:
            self._skip_hi.pop(g, None)
        self._fail_span(g, 0, checkpoint.index, RuntimeError(
            "entry applied via snapshot; result unavailable"))
        self._halted[g] = False

    # -- the apply loop -----------------------------------------------------

    def warm_mirror(self, n: int) -> None:
        """Materialize the applied-frontier mirror for ``n`` groups on the
        CALLING thread.  The striped host tier calls this once from the
        orchestrator before fanning ``advance`` out to stripe workers —
        lazy creation inside concurrent advance() calls would race the
        full-array build."""
        self._applied_mirror(n)

    def advance(self, commit: np.ndarray,
                groups: Optional[np.ndarray] = None,
                max_per_group: int = 0) -> None:
        """Apply newly committed entries.  `commit` is the [G] frontier;
        `groups` optionally restricts which lanes are live (active mask or
        index list).  `max_per_group` bounds work per call (0 = no bound).

        Stripe-sliced calls (striped host tier) pass a pre-sliced index
        view: disjoint group sets make concurrent advance() calls safe —
        every structure here (machines, promises, skip ledger, the mirror's
        per-element writes) is keyed or indexed by group.  An index list is
        intersected with the behind mask exactly like a bool mask, so a
        stripe view costs no applies for already-caught-up groups."""
        mirror = self._applied_mirror(len(commit))
        behind = commit > mirror[:len(commit)]
        if groups is None:
            gs = np.nonzero(behind)[0]
        elif groups.dtype == bool:
            gs = np.nonzero(groups & behind)[0]
        else:
            groups = np.asarray(groups, np.int64)
            gs = groups[behind[groups]]
        retries = self._retry_counts
        for g in gs:
            g = int(g)
            if self._halted.get(g):
                continue
            m = self.machine(g)
            apply_fn = m.apply
            applies_empty = bool(getattr(m, "applies_empty", False))
            has_promises = g in self._promises
            target = int(commit[g])
            before = m.last_applied()
            if not applies_empty:
                # Resume past no-ops this machine never saw (spi.py
                # empty-payload opt-out): the dispatcher's skip ledger
                # extends the machine's own frontier.
                sk = self._skip_hi.get(g, 0)
                if sk > before:
                    before = sk
            idx = before + 1
            hi = target if max_per_group <= 0 \
                else min(target, idx + max_per_group - 1)
            # Probe the first index before prefetching the window: a group
            # whose frontier is far ahead of its local store (snapshot
            # pending) must cost one lookup per tick, not one per missing
            # entry.  The probe's hit is cached, so no duplicate work.
            window = None
            results = None
            probe_ok = (hi >= idx and self._payload(g, idx) is not None)
            # Fastest path: an arena-capable machine (apply_run, SPI)
            # takes the whole window as buffer pieces — no per-entry
            # bytes anywhere (payload materialization for applies was
            # ~25% of the durable tick once staging went arena).
            run_fn = getattr(m, "apply_run", None)
            if probe_ok and run_fn is not None \
                    and self._payload_runs is not None:
                pr = self._payload_runs(g, idx, hi - idx + 1)
                if pr is not None and not applies_empty \
                        and (np.asarray(pr[1]) == 0).any():
                    # Window holds an election no-op the machine must not
                    # see: route through the windowed/per-entry paths,
                    # which skip it (spi.py applies_empty contract).
                    pr = None
                if pr is not None:
                    try:
                        results = run_fn(idx, pr[0], pr[1])
                    except Exception as e:
                        log.warning("apply_run failed g=%d idx=%d: %s "
                                    "(falling back)", g, idx, e)
                        # An empty result list (NOT None) routes through
                        # the shared resync block below: the machine may
                        # have applied a prefix before raising, and
                        # falling straight into apply_batch at the stale
                        # idx would re-apply it (double apply).
                        results = []
            # Fast path: machines exposing apply_batch (SPI, spi.py) take
            # the locally-available contiguous prefix in ONE call; a short
            # return (failed entry) falls through to the per-entry loop,
            # which retries it with full diagnostics.
            if results is None:
                if probe_ok and self._payload_window is not None:
                    window = self._payload_window(g, idx, hi - idx + 1)
                batch_fn = getattr(m, "apply_batch", None)
                if window is not None and batch_fn is not None:
                    n_have = 0
                    for p in window:
                        # Stop the batch at an election no-op the machine
                        # opted out of seeing; the per-entry loop below
                        # skips it and carries on.
                        if p is None or (not p and not applies_empty):
                            break
                        n_have += 1
                    if n_have:
                        try:
                            results = batch_fn(idx, window[:n_have])
                        except Exception as e:
                            # A raising batch apply must not kill the whole
                            # tick (the per-entry path catches and retries).
                            # The machine may have applied a prefix before
                            # raising: resync from its own frontier, then
                            # let the per-entry loop below retry the failing
                            # entry with full diagnostics.
                            log.warning("apply_batch failed g=%d idx=%d: %s "
                                        "(falling back to per-entry)",
                                        g, idx, e)
                            results = []
            if results is not None:
                if has_promises and results:
                    self._complete_run(g, idx, results)
                if retries:
                    for k in range(len(results)):
                        retries.pop((g, idx + k), None)
                idx += len(results)
                la = m.last_applied()
                if la >= idx:
                    # The machine advanced past the reported results
                    # (mid-batch failure after a partial apply, or a
                    # contract violation): those entries DID apply but
                    # their results are unobservable.  Their promises
                    # must not hang forever — fail them explicitly,
                    # like the snapshot-jump path (resume_from).
                    self._fail_span(g, idx, la, RuntimeError(
                        "entry applied; result unavailable"
                        " (batch apply failed mid-batch)"))
                    if retries:
                        for key in [k for k in retries
                                    if k[0] == g and idx <= k[1] <= la]:
                            del retries[key]
                    idx = la + 1
            while idx <= hi:
                payload = (window[idx - before - 1] if window is not None
                           else self._payload(g, idx))
                if payload is None:
                    # Frontier ahead of locally stored entries (e.g. device
                    # committed via snapshot milestone); the machine must
                    # catch up via recover, not apply.
                    break
                if not payload and not applies_empty:
                    # Election no-op (Raft §8) short-circuited for a
                    # machine without the spi.py opt-in: the machine never
                    # sees the empty command, the dispatcher's skip ledger
                    # carries the frontier over it, and any (unusual)
                    # client promise on an empty command completes None.
                    key = type(m).__name__
                    if key not in self._warned_empty:
                        self._warned_empty.add(key)
                        log.warning(
                            "machine %s (group %d) does not opt into "
                            "empty-payload applies (applies_empty=False); "
                            "short-circuiting election no-op at index %d "
                            "— set applies_empty=True on the machine to "
                            "receive empty commands (machine/spi.py)",
                            key, g, idx)
                    if has_promises:
                        self._complete_run(g, idx, [None])
                    self._skip_hi[g] = idx
                    self._empty_skip_n[g] = self._empty_skip_n.get(g, 0) + 1
                    idx += 1
                    continue
                try:
                    result = apply_fn(idx, payload)
                except Exception as e:
                    # Retry next round (reference RetryCommandException,
                    # RaftRoutine.java:288-300).  A deterministic failure
                    # freezes the group's apply frontier on purpose —
                    # skipping a committed entry would diverge replicas —
                    # but escalate so the operator sees a stuck group.
                    n = retries[(g, idx)] = retries.get((g, idx), 0) + 1
                    lvl = log.error if n in (10, 100) or n % 1000 == 0 \
                        else log.warning
                    lvl("apply failed g=%d idx=%d (attempt %d): %s",
                        g, idx, n, e)
                    break
                if retries:
                    retries.pop((g, idx), None)
                if has_promises:
                    self._complete_run(g, idx, [result])
                idx += 1
            # Mirror tracks true machine progress; on a payload gap or a
            # failed apply it simply stays behind and the lane is revisited
            # next tick.
            mirror[g] = idx - 1 if idx - 1 > before else before
            if self._on_applied is not None and idx - 1 > before:
                self._on_applied(g, idx - 1)

    def applied_frontier(self, n_groups: int) -> np.ndarray:
        out = np.zeros(n_groups, np.int32)
        a = self._applied_arr
        if a is not None and len(a) >= n_groups:
            return a[:n_groups].astype(np.int32)
        for g, m in self._machines.items():
            if g < n_groups:
                out[g] = m.last_applied()
        return out

    def close(self) -> None:
        for m in self._machines.values():
            m.close()
        self._machines.clear()
