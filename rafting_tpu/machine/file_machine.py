"""FileMachine: the cross-implementation correctness oracle.

Re-creation of the reference's test fixture machine (curioloop/rafting
test cluster/cmd/FileMachine.java:14-142): every committed command appends
an ``index:line`` row to a text file, so two replicas are correct iff
their files are byte-identical — the reference's whole-system oracle
(README.md:28-33).  Checkpoint = file copy under the archive dir
(FileMachine.java:87-104); recover validates that the checkpoint is a
prefix-extension of current state before replacing it
(FileMachine.java:121-131).
"""

from __future__ import annotations

import glob
import os
import shutil
from typing import Any, Optional

from .spi import Checkpoint


class FileMachine:
    # Opt into election no-ops (machine/spi.py): an empty command appends
    # an 'index:' line, keeping replica files byte-identical incl. no-ops.
    applies_empty = True

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a+")
        self._last_applied = self._count_lines()
        # One startup sweep for crash leftovers; afterwards checkpoints are
        # tracked in memory (a glob per checkpoint scans the whole shared
        # machines dir — O(groups) per call, O(groups^2) per tick at scale).
        self._prune_ckpts()
        self._last_ckpt: Optional[str] = None

    def _count_lines(self) -> int:
        """last_applied = index of the final line (reference counts lines,
        FileMachine.java:27-31; here lines carry their index explicitly)."""
        self._f.seek(0)
        last = 0
        for line in self._f:
            head, _, _ = line.partition(":")
            if head.isdigit():
                last = int(head)
        self._f.seek(0, os.SEEK_END)
        return last

    def last_applied(self) -> int:
        return self._last_applied

    def apply(self, index: int, payload: bytes) -> Any:
        assert index == self._last_applied + 1, \
            f"apply out of order: {index} after {self._last_applied}"
        # Escape newlines/backslashes so one committed entry is always one
        # physical line — the invariant _count_lines and recover depend on.
        line = (payload.decode("utf-8", "replace")
                .replace("\\", "\\\\").replace("\n", "\\n"))
        self._f.write(f"{index}:{line}\n")
        self._f.flush()
        self._last_applied = index
        return index

    def apply_batch(self, start_index: int, payloads) -> list:
        """Batched apply (SPI fast path, spi.py): all lines in one write +
        one flush instead of one syscall pair per entry."""
        assert start_index == self._last_applied + 1, \
            f"apply out of order: {start_index} after {self._last_applied}"
        lines = []
        for k, payload in enumerate(payloads):
            line = (payload.decode("utf-8", "replace")
                    .replace("\\", "\\\\").replace("\n", "\\n"))
            lines.append(f"{start_index + k}:{line}\n")
        self._f.write("".join(lines))
        self._f.flush()
        self._last_applied = start_index + len(payloads) - 1
        return list(range(start_index, start_index + len(payloads)))

    def checkpoint(self, must_include: int) -> Checkpoint:
        assert self._last_applied >= must_include
        os.fsync(self._f.fileno())
        if self._last_ckpt:
            try:
                os.unlink(self._last_ckpt)
            except OSError:
                pass
        tmp = f"{self.path}.ckpt.{self._last_applied}"
        shutil.copyfile(self.path, tmp)
        self._last_ckpt = tmp
        return Checkpoint(path=tmp, index=self._last_applied)

    def _prune_ckpts(self) -> None:
        for p in glob.glob(f"{self.path}.ckpt.*"):
            try:
                os.unlink(p)
            except OSError:
                pass

    def recover(self, checkpoint: Checkpoint) -> None:
        # Prefix validation (reference FileMachine.java:121-131): current
        # state must be a prefix of the checkpoint or vice versa; a
        # divergent file means the oracle caught an inconsistency.
        with open(checkpoint.path, "r") as src:
            new_lines = src.readlines()
        self._f.seek(0)
        cur_lines = self._f.readlines()
        common = min(len(new_lines), len(cur_lines))
        if new_lines[:common] != cur_lines[:common]:
            raise AssertionError(
                f"snapshot diverges from local state at {self.path}")
        self._f.close()
        shutil.copyfile(checkpoint.path, self.path)
        self._f = open(self.path, "a+")
        self._last_applied = checkpoint.index

    def close(self) -> None:
        self._f.close()

    def destroy(self) -> None:
        self._f.close()
        self._prune_ckpts()
        if os.path.exists(self.path):
            os.unlink(self.path)

    def lines(self):
        self._f.seek(0)
        out = self._f.readlines()
        self._f.seek(0, os.SEEK_END)
        return out


class FileMachineProvider:
    """One file per group under a root dir (reference
    cluster/cmd/FileMachineProvider.java:13-40)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def bootstrap(self, group: int) -> FileMachine:
        return FileMachine(os.path.join(self.root, f"group_{group}.txt"))
