"""MemoryLogStore: a non-durable LogStoreSPI implementation.

The in-memory counterpart of the segmented WAL (the reference ships its log
SPI precisely so a user can swap the storage tier, command/spi/
StateLoader.java:8-12): same staging/sync/read/recovery contract, no disk.
``sync`` is a no-op — a crash loses everything, which is exactly the point
for unit tests, ephemeral groups and benchmarks that want to isolate the
engine from fsync cost.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class MemoryLogStore:
    def __init__(self, path: str = "", segment_bytes: int = 0):
        # Constructor shape-compatible with LogStore so factories can swap
        # the class; both args are ignored.
        self._entries: Dict[int, Dict[int, Tuple[int, bytes]]] = {}
        self._stable: Dict[int, Tuple[int, int]] = {}
        self._floor: Dict[int, Tuple[int, int]] = {}
        self._tail: Dict[int, int] = {}

    # -- staging writes ------------------------------------------------------

    def append_entries(self, g: int, start: int, terms: Sequence[int],
                       payloads: Sequence[bytes]) -> None:
        ge = self._entries.setdefault(g, {})
        for k, (t, p) in enumerate(zip(terms, payloads)):
            ge[start + k] = (int(t), p)
        self._tail[g] = max(self._tail.get(g, 0), start + len(terms) - 1)

    def append_batch(self, groups: Sequence[int], idxs: Sequence[int],
                     terms: Sequence[int], payloads: Sequence[bytes]) -> None:
        for g, i, t, p in zip(groups, idxs, terms, payloads):
            g, i = int(g), int(i)
            self._entries.setdefault(g, {})[i] = (int(t), p)
            if i > self._tail.get(g, 0):
                self._tail[g] = i

    def truncate_to(self, g: int, tail: int) -> None:
        ge = self._entries.get(g)
        if ge:
            for k in [k for k in ge if k > tail]:
                del ge[k]
        if self._tail.get(g, 0) > tail:
            self._tail[g] = tail

    def put_stable(self, g: int, term: int, ballot: int) -> None:
        self._stable[g] = (int(term), int(ballot))

    def set_floor(self, g: int, index: int, term: int) -> None:
        if index <= self.floor(g):
            return
        self._floor[g] = (int(index), int(term))
        ge = self._entries.get(g)
        if ge:
            for k in [k for k in ge if k <= index]:
                del ge[k]
        self._tail[g] = max(self._tail.get(g, 0), index)

    def reset_group(self, g: int) -> None:
        self._entries.pop(g, None)
        self._stable.pop(g, None)
        self._floor.pop(g, None)
        self._tail.pop(g, None)

    def sync(self) -> None:
        pass

    def checkpoint(self) -> None:
        pass

    # -- GC: nothing to reclaim ---------------------------------------------

    def should_gc(self, ratio: float = 4.0, min_bytes: int = 8 << 20) -> bool:
        return False

    def gc_begin(self) -> int:
        return -1

    def gc_rewrite(self) -> int:
        return -1

    def gc_finish(self) -> int:
        return -1

    def gc_abort(self) -> None:
        pass

    def segment_count(self) -> int:
        return 0

    # -- reads ---------------------------------------------------------------

    def payload(self, g: int, idx: int) -> Optional[bytes]:
        e = self._entries.get(g, {}).get(idx)
        return None if e is None else e[1]

    def payload_batch(self, g: int, start: int, n: int) -> List[bytes]:
        return [b"" if p is None else p
                for p in self.payloads_window(g, start, n)]

    def payloads_window(self, g: int, start: int, n: int
                        ) -> List[Optional[bytes]]:
        ge = self._entries.get(g, {})
        return [None if (e := ge.get(i)) is None else e[1]
                for i in range(start, start + n)]

    def entry_term(self, g: int, idx: int) -> int:
        e = self._entries.get(g, {}).get(idx)
        return -1 if e is None else e[0]

    def stable(self, g: int) -> Optional[Tuple[int, int]]:
        return self._stable.get(g)

    def tail(self, g: int) -> int:
        return self._tail.get(g, 0)

    def floor(self, g: int) -> int:
        return self._floor.get(g, (0, 0))[0]

    def floor_term(self, g: int) -> int:
        return self._floor.get(g, (0, 0))[1]

    # -- crash recovery ------------------------------------------------------

    def export_state(self, G: int, L: int) -> Dict[str, np.ndarray]:
        out = {
            "has_stable": np.zeros(G, np.int32),
            "stable_term": np.zeros(G, np.int64),
            "ballot": np.zeros(G, np.int64),
            "floor": np.zeros(G, np.int64),
            "floor_term": np.zeros(G, np.int64),
            "tail": np.zeros(G, np.int64),
            "live_count": np.zeros(G, np.int64),
            "ring": np.zeros((G, L), np.int32),
        }
        for g, (t, b) in self._stable.items():
            if g < G:
                out["has_stable"][g] = 1
                out["stable_term"][g] = t
                out["ballot"][g] = b
        for g, (i, t) in self._floor.items():
            if g < G:
                out["floor"][g] = i
                out["floor_term"][g] = t
        for g, t in self._tail.items():
            if g < G:
                out["tail"][g] = t
        for g, ge in self._entries.items():
            if g >= G:
                continue
            floor = int(out["floor"][g])
            tail = int(out["tail"][g])
            n = 0
            for idx, (t, _) in ge.items():
                if floor < idx <= tail:
                    out["ring"][g, idx % L] = t
                    n += 1
            out["live_count"][g] = n
        return out

    def close(self) -> None:
        pass
