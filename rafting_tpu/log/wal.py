"""WAL storage engine binding: native C++ backend with a Python fallback.

The native engine lives in ``native/wal.cpp`` (see its header comment for
the record format and recovery semantics).  It is compiled on first use
with the system toolchain and loaded through ctypes — the binding style
this environment supports (no pybind11).  ``PyWal`` reimplements the same
contract in pure Python for platforms without a compiler; both backends
read/write the identical on-disk format (cross-checked in
tests/test_wal.py).

Durability contract (the ack-after-fsync rule every engine here obeys):
``append_*``/``truncate``/``milestone``/``append_stable`` only STAGE
records — nothing is durable, and the caller must not acknowledge
anything that depends on a staged record, until :meth:`sync` returns.
One ``sync`` is the fsync barrier covering every record staged before it;
the node runtime releases RPC replies and completes client futures only
behind that barrier (persist-before-reply, amortized over all groups),
and the pipelined runtime additionally feeds the post-barrier durable
tail back into the device scan so an un-fsynced range can never be
self-acked into a commit quorum (core/types.py HostInbox.durable_tail).

``ShardedWal`` stripes groups over S independent engines (group ->
shard ``g % S``), each with its own segment files and fsync: a tick's
appends land as one arena call per moved stripe and ``sync`` issues the
S fsyncs in parallel from a small worker pool with a single barrier
join — the barrier completes only when EVERY shard's fsync has, so the
ack-after-fsync contract is unchanged.  The stripe count is pinned in a
``wal_shards.json`` meta file at creation; reopening honors the pinned
value, so recovery can never silently read a half-striped directory.
"""

from __future__ import annotations

import ctypes
import errno as _errno
import os
import struct
import subprocess
import threading
import time
import zlib
from typing import Dict, Optional

from ..utils import iofault


class WalSyncError(IOError):
    """The durability barrier failed in a NON-RETRIABLE way: fsync error,
    torn/short write, or any write failure other than disk-full.

    ``shards`` carries the poisoned engine ids — those engines are
    fail-stop: a failed fsync is never retried on the same fd (the page
    cache may have dropped the dirty pages that failed to reach the
    device, so a later "successful" fsync would be a lie — the
    PostgreSQL fsyncgate lesson).  An EMPTY ``shards`` means a global
    transient (e.g. the ConfMeta sidecar flush) with no engine poisoned:
    the caller may skip the tick and retry at the next barrier.
    ``nospace`` lists any shards that simultaneously hit ENOSPC in the
    same barrier (mixed-failure merge)."""

    def __init__(self, msg: str, shards=(), nospace=()):
        super().__init__(msg)
        self.shards = tuple(shards)
        self.nospace = tuple(nospace)


class WalNoSpace(IOError):
    """The barrier failed with ENOSPC on ``shards`` — RETRIABLE: each
    engine rewound its segment to the last good offset and KEPT its
    staged buffer, so a later barrier retries the flush once space
    frees.  Callers respond with admission backpressure, not
    quarantine."""

    def __init__(self, msg: str, shards=()):
        super().__init__(msg)
        self.shards = tuple(shards)


# Uniform injectable-fault vocabulary across both engines (native op codes).
# "fsync"/"write" fail the guarded call with `value` as errno (0 -> EIO);
# "short" persists only `value` bytes of the staged buffer then poisons;
# "delay" sleeps `value` microseconds at each sync barrier (a level, not a
# countdown — clear by setting 0).
_FAULT_OPS = {"fsync": 1, "write": 2, "short": 3, "delay": 4}


def _merge_wal_errors(excs):
    """Collapse per-shard barrier failures into ONE taxonomy exception:
    non-taxonomy errors win verbatim; otherwise poisoned shards and
    ENOSPC shards are unioned, with WalSyncError taking precedence (a
    barrier that poisoned anything is non-retriable as a whole)."""
    excs = [e for e in excs if e is not None]
    if not excs:
        return None
    for e in excs:
        if not isinstance(e, (WalSyncError, WalNoSpace)):
            return e
    poisoned, nospace = [], []
    for e in excs:
        if isinstance(e, WalSyncError):
            poisoned.extend(e.shards)
            nospace.extend(e.nospace)
        else:
            nospace.extend(e.shards)
    msg = "; ".join(str(e) for e in excs[:4])
    if poisoned:
        return WalSyncError(msg, sorted(set(poisoned)), sorted(set(nospace)))
    return WalNoSpace(msg, sorted(set(nospace)))


_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_SRC = os.path.join(_NATIVE_DIR, "wal.cpp")
_SO = os.path.join(_NATIVE_DIR, "libwal.so")
_build_lock = threading.Lock()
_lib = None
_build_err: Optional[str] = None


def _build_native() -> Optional[str]:
    """Compile the native engine if missing/stale; return error or None."""
    try:
        if (os.path.exists(_SO)
                and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)):
            return None
        r = subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-pthread",
             _SRC, "-o", _SO],
            capture_output=True, text=True, timeout=120)
        if r.returncode != 0:
            return r.stderr[-2000:]
        return None
    except Exception as e:  # toolchain absent
        return str(e)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_err
    with _build_lock:
        if _lib is not None:
            return _lib
        _build_err = _build_native()
        if _build_err is not None:
            return None
        lib = ctypes.CDLL(_SO)
        lib.wal_open.restype = ctypes.c_void_p
        lib.wal_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.wal_close.argtypes = [ctypes.c_void_p]
        lib.wal_append_entry.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint64, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_uint32]
        lib.wal_append_stable.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int64, ctypes.c_int64]
        lib.wal_truncate.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint64]
        lib.wal_milestone.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint64, ctypes.c_int64]
        lib.wal_reset.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
        lib.wal_sync.argtypes = [ctypes.c_void_p]
        lib.wal_sync.restype = ctypes.c_int
        for f, res in [("wal_tail", ctypes.c_int64),
                       ("wal_floor", ctypes.c_int64),
                       ("wal_floor_term", ctypes.c_int64),
                       ("wal_entry_term", ctypes.c_int64),
                       ("wal_entry_len", ctypes.c_int64)]:
            fn = getattr(lib, f)
            fn.restype = res
            fn.argtypes = ([ctypes.c_void_p, ctypes.c_uint32]
                           + ([ctypes.c_uint64] if "entry" in f else []))
        lib.wal_stable.restype = ctypes.c_int
        lib.wal_stable.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
        lib.wal_entry_payload.restype = ctypes.c_int64
        lib.wal_entry_payload.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint64]
        lib.wal_checkpoint.argtypes = [ctypes.c_void_p]
        lib.wal_checkpoint.restype = ctypes.c_int
        lib.wal_segment_count.argtypes = [ctypes.c_void_p]
        lib.wal_segment_count.restype = ctypes.c_uint64
        lib.wal_total_bytes.argtypes = [ctypes.c_void_p]
        lib.wal_total_bytes.restype = ctypes.c_uint64
        lib.wal_live_bytes.argtypes = [ctypes.c_void_p]
        lib.wal_live_bytes.restype = ctypes.c_uint64
        lib.wal_export_state.restype = ctypes.c_uint64
        lib.wal_export_state.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint32,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p]
        lib.wal_append_entries.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_char_p,
            ctypes.c_void_p, ctypes.c_void_p]
        lib.wal_gc_begin.argtypes = [ctypes.c_void_p]
        lib.wal_gc_begin.restype = ctypes.c_int
        lib.wal_gc_rewrite.argtypes = [ctypes.c_void_p]
        lib.wal_gc_rewrite.restype = ctypes.c_int64
        lib.wal_gc_finish.argtypes = [ctypes.c_void_p]
        lib.wal_gc_finish.restype = ctypes.c_int
        lib.wal_gc_abort.argtypes = [ctypes.c_void_p]
        lib.wal_gc_abort.restype = None
        lib.wal_error.argtypes = [ctypes.c_void_p]
        lib.wal_error.restype = ctypes.c_char_p
        # Native host tier (hasattr-guarded so a stale prebuilt .so still
        # serves the classic surface — callers probe can_stage_native).
        if hasattr(lib, "wal_stage_and_sync"):
            lib.wal_stage_and_sync.restype = ctypes.c_int
            lib.wal_stage_and_sync.argtypes = (
                [ctypes.POINTER(ctypes.c_void_p), ctypes.c_uint32,
                 ctypes.c_uint32]
                + [ctypes.c_void_p] * 13
                + [ctypes.c_int, ctypes.POINTER(ctypes.c_double),
                   ctypes.POINTER(ctypes.c_double)])
            lib.wal_pack_ae.restype = ctypes.c_int64
            lib.wal_pack_ae.argtypes = [
                ctypes.POINTER(ctypes.c_void_p), ctypes.c_uint32,
                ctypes.c_uint32, ctypes.c_uint64, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))]
            lib.wal_buf_free.restype = None
            lib.wal_buf_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        # Injectable fault table (hasattr-guarded like the host tier: a
        # stale prebuilt .so still serves the classic surface).
        if hasattr(lib, "wal_fault_set"):
            lib.wal_fault_set.restype = ctypes.c_int
            lib.wal_fault_set.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_int64, ctypes.c_int64]
            lib.wal_fault_clear.restype = None
            lib.wal_fault_clear.argtypes = [ctypes.c_void_p]
            lib.wal_poisoned.restype = ctypes.c_int
            lib.wal_poisoned.argtypes = [ctypes.c_void_p]
            lib.wal_last_errno.restype = ctypes.c_int
            lib.wal_last_errno.argtypes = [ctypes.c_void_p]
        # Per-stripe instrumentation export (hasattr-guarded like the host
        # tier: a stale prebuilt .so still serves the classic surface).
        if hasattr(lib, "wal_stats"):
            lib.wal_stats.restype = None
            lib.wal_stats.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        _lib = lib
        return lib


# wal_stats() export order — one schema for both engines (and the merged
# ShardedWal view): cumulative ns spent staging / fsyncing / packing, bytes
# staged, and call counts.  Counters never reset; consumers keep the last
# snapshot and fold deltas into the metrics registry.
WAL_STAT_KEYS = ("stage_ns", "fsync_ns", "pack_ns", "bytes",
                 "stage_calls", "fsync_calls", "pack_calls")


def native_available() -> bool:
    return _load() is not None


def native_host_available() -> bool:
    """True when the loaded .so exports the native host tier entry points
    (wal_stage_and_sync / wal_pack_ae)."""
    lib = _load()
    return lib is not None and hasattr(lib, "wal_stage_and_sync")


def _shard_split(n_shards: int, g_arr, cols):
    """Stable-sort rows by WAL stripe (``g % S``) into the CSR layout the
    native tier consumes: (sorted group column, sorted value columns,
    ``row_off[S+1]``).  The STABLE sort preserves the staging path's
    per-group ascending contiguous runs within each shard — the property
    the engine's hinted-emplace hot loop relies on."""
    import numpy as np
    stripe = g_arr % np.uint32(n_shards)
    order = np.argsort(stripe, kind="stable")
    sorted_stripe = stripe[order]
    row_off = np.ascontiguousarray(
        np.searchsorted(sorted_stripe, np.arange(n_shards + 1)), np.uint64)
    return (np.ascontiguousarray(g_arr[order]),
            [np.ascontiguousarray(c[order]) for c in cols],
            row_off)


def _native_stage_and_sync(handles, n_shards, engines, workers, sync,
                           groups, idxs, terms, ptrs, lens,
                           trunc_g, trunc_from,
                           floor_g, floor_idx, floor_term):
    """One ctypes crossing for a whole tick's durable work: entries (by raw
    payload pointer), truncations and milestones are split per stripe and
    handed to wal_stage_and_sync, which stages and fsyncs every shard with
    real OS threads (the GIL is released for the duration of the call).
    Returns ``(stage_s, fsync_s)`` — max per-worker wall times."""
    import numpy as np
    lib = _load()
    asc = np.ascontiguousarray
    eg, (ei, et, ep, el), eoff = _shard_split(
        n_shards, asc(groups, np.uint32),
        [asc(idxs, np.uint64), asc(terms, np.int64),
         asc(ptrs, np.uint64), asc(lens, np.uint32)])
    tg, (tf,), toff = _shard_split(
        n_shards, asc(trunc_g, np.uint32), [asc(trunc_from, np.uint64)])
    fg, (fi, ft), foff = _shard_split(
        n_shards, asc(floor_g, np.uint32),
        [asc(floor_idx, np.uint64), asc(floor_term, np.int64)])
    st = ctypes.c_double()
    fs = ctypes.c_double()
    ptr = lambda a: a.ctypes.data_as(ctypes.c_void_p)
    rc = lib.wal_stage_and_sync(
        handles, n_shards, max(1, int(workers)),
        ptr(eoff), ptr(eg), ptr(ei), ptr(et), ptr(ep), ptr(el),
        ptr(toff), ptr(tg), ptr(tf),
        ptr(foff), ptr(fg), ptr(fi), ptr(ft),
        1 if sync else 0, ctypes.byref(st), ctypes.byref(fs))
    if rc != 0:
        msg = "; ".join(e.error() for e in engines if e.error()) or "unknown"
        bad = [getattr(e, "shard_id", k) for k, e in enumerate(engines)
               if e.poisoned]
        nosp = [getattr(e, "shard_id", k) for k, e in enumerate(engines)
                if not e.poisoned and e.last_errno == _errno.ENOSPC]
        if bad:
            raise WalSyncError(f"wal_stage_and_sync: {msg}", bad, nosp)
        if nosp:
            raise WalNoSpace(f"wal_stage_and_sync: {msg}", nosp)
        raise WalSyncError(f"wal_stage_and_sync: {msg}", ())
    return float(st.value), float(fs.value)


def _native_pack_ae(handles, n_shards, workers, cols, starts, ns):
    """Native AppendEntries blob pack: returns ``(ok_mask, blob)`` where
    ``blob`` is byte-identical to the Python packer's lens-vector +
    payload concatenation for the kept columns, or ``None`` on failure
    (caller falls back to the Python pack loop)."""
    import numpy as np
    lib = _load()
    c = np.ascontiguousarray(cols, np.uint32)
    s = np.ascontiguousarray(starts, np.uint64)
    n = np.ascontiguousarray(ns, np.uint32)
    nc = int(len(c))
    ok = np.ones(nc, np.uint8)
    out = ctypes.POINTER(ctypes.c_uint8)()
    ptr = lambda a: a.ctypes.data_as(ctypes.c_void_p)
    total = lib.wal_pack_ae(handles, n_shards, max(1, int(workers)), nc,
                            ptr(c), ptr(s), ptr(n), ptr(ok),
                            ctypes.byref(out))
    if total < 0:
        return None
    try:
        blob = ctypes.string_at(out, total) if total else b""
    finally:
        if out:
            lib.wal_buf_free(out)
    return ok.astype(bool), blob


class _NativeWal:
    shard_id = 0  # ShardedWal pins the true stripe id per engine

    def __init__(self, path: str, segment_bytes: int):
        self._lib = _load()
        assert self._lib is not None
        self._h = self._lib.wal_open(path.encode(), segment_bytes)
        if not self._h:
            raise IOError(f"wal_open failed for {path}")
        self._handles = (ctypes.c_void_p * 1)(self._h)

    def error(self) -> str:
        if not self._h:
            return ""
        return (self._lib.wal_error(self._h) or b"").decode(
            "utf-8", "replace")

    # -- injectable fault table (testkit/faultfs) ----------------------
    def set_fault(self, op: str, after: int = 0, value: int = 0) -> None:
        if not hasattr(self._lib, "wal_fault_set"):
            raise RuntimeError("native fault table unavailable (stale .so)")
        if value == 0 and op in ("fsync", "write"):
            value = _errno.EIO
        self._lib.wal_fault_set(self._h, _FAULT_OPS[op], int(after),
                                int(value))

    def clear_faults(self) -> None:
        if self._h and hasattr(self._lib, "wal_fault_clear"):
            self._lib.wal_fault_clear(self._h)

    @property
    def poisoned(self) -> bool:
        if not self._h or not hasattr(self._lib, "wal_poisoned"):
            return False
        return bool(self._lib.wal_poisoned(self._h))

    @property
    def last_errno(self) -> int:
        if not self._h or not hasattr(self._lib, "wal_last_errno"):
            return 0
        return int(self._lib.wal_last_errno(self._h))

    def stats(self) -> Dict[str, int]:
        """Cumulative per-stripe instrumentation (WAL_STAT_KEYS), read
        zero-copy from the engine's atomic counters."""
        if not self._h or not hasattr(self._lib, "wal_stats"):
            return dict.fromkeys(WAL_STAT_KEYS, 0)
        out = (ctypes.c_uint64 * len(WAL_STAT_KEYS))()
        self._lib.wal_stats(self._h, out)
        return dict(zip(WAL_STAT_KEYS, (int(v) for v in out)))

    def _raise_sync_error(self):
        msg = self.error() or "wal_sync failed"
        if self.last_errno == _errno.ENOSPC and not self.poisoned:
            raise WalNoSpace(msg, (self.shard_id,))
        raise WalSyncError(msg, (self.shard_id,))

    @property
    def can_stage_native(self) -> bool:
        return native_host_available()

    def stage_and_sync(self, groups, idxs, terms, ptrs, lens,
                       trunc_g, trunc_from, floor_g, floor_idx, floor_term,
                       *, workers: int = 1, sync: bool = True):
        """Single-shard native host tier: see _native_stage_and_sync."""
        return _native_stage_and_sync(
            self._handles, 1, [self], workers, sync,
            groups, idxs, terms, ptrs, lens,
            trunc_g, trunc_from, floor_g, floor_idx, floor_term)

    def pack_ae(self, cols, starts, ns, *, workers: int = 1):
        if not self.can_stage_native:
            return None
        return _native_pack_ae(self._handles, 1, workers, cols, starts, ns)

    def close(self):
        if self._h:
            self._lib.wal_close(self._h)
            self._h = None

    def append_entry(self, g, idx, term, payload: bytes):
        self._lib.wal_append_entry(self._h, g, idx, term, payload,
                                   len(payload))

    def append_stable(self, g, term, ballot):
        self._lib.wal_append_stable(self._h, g, term, ballot)

    def truncate(self, g, frm):
        self._lib.wal_truncate(self._h, g, frm)

    def milestone(self, g, idx, term):
        self._lib.wal_milestone(self._h, g, idx, term)

    def reset(self, g):
        self._lib.wal_reset(self._h, g)

    def sync(self):
        if self._lib.wal_sync(self._h) != 0:
            self._raise_sync_error()

    def tail(self, g):
        return self._lib.wal_tail(self._h, g)

    def floor(self, g):
        return self._lib.wal_floor(self._h, g)

    def floor_term(self, g):
        return self._lib.wal_floor_term(self._h, g)

    def stable(self, g):
        t = ctypes.c_int64()
        b = ctypes.c_int64()
        if self._lib.wal_stable(self._h, g, ctypes.byref(t), ctypes.byref(b)):
            return int(t.value), int(b.value)
        return None

    def entry_term(self, g, idx):
        return self._lib.wal_entry_term(self._h, g, idx)

    def entry_payload(self, g, idx) -> Optional[bytes]:
        n = self._lib.wal_entry_len(self._h, g, idx)
        if n < 0:
            return None
        buf = ctypes.create_string_buffer(n)
        got = self._lib.wal_entry_payload(self._h, g, idx, buf, n)
        if got != n:
            return None
        return buf.raw[:n]

    def checkpoint(self):
        if self._lib.wal_checkpoint(self._h) != 0:
            raise IOError("wal_checkpoint failed")

    def gc_begin(self) -> int:
        return int(self._lib.wal_gc_begin(self._h))

    def gc_rewrite(self) -> int:
        return int(self._lib.wal_gc_rewrite(self._h))

    def gc_finish(self) -> int:
        return int(self._lib.wal_gc_finish(self._h))

    def gc_abort(self) -> None:
        self._lib.wal_gc_abort(self._h)

    def segment_count(self):
        return int(self._lib.wal_segment_count(self._h))

    def total_bytes(self):
        return int(self._lib.wal_total_bytes(self._h))

    def live_bytes(self):
        return int(self._lib.wal_live_bytes(self._h))

    def export_state(self, G: int, L: int) -> dict:
        """Bulk boot-time restore: one native call fills all per-group
        arrays + the [G, L] entry-term ring (wal_export_state)."""
        out = _export_arrays(G, L)
        ptr = lambda a: a.ctypes.data_as(ctypes.c_void_p)
        self._lib.wal_export_state(
            self._h, G, L, ptr(out["stable_term"]), ptr(out["ballot"]),
            ptr(out["has_stable"]), ptr(out["floor"]),
            ptr(out["floor_term"]), ptr(out["tail"]),
            ptr(out["live_count"]), ptr(out["ring"]))
        return out

    def append_batch(self, groups, idxs, terms, payloads) -> None:
        """Append many (group, idx, term, payload) records in one native
        call: payload bytes are concatenated host-side so the ctypes
        boundary is crossed once per tick, not once per entry."""
        import numpy as np
        n = len(groups)
        if n == 0:
            return
        lens = np.fromiter((len(p) for p in payloads), np.uint32, n)
        offs = np.zeros(n, np.uint64)
        offs[1:] = np.cumsum(lens[:-1], dtype=np.uint64)
        self.append_arena(groups, idxs, terms, b"".join(payloads), offs, lens)

    def append_arena(self, groups, idxs, terms, blob: bytes, offs,
                     lens) -> None:
        """Arena variant: the caller already holds payload bytes as ONE
        contiguous blob with per-entry offsets/lengths (the staging path's
        native currency) — pointers cross ctypes directly, nothing is
        re-joined or re-measured."""
        import numpy as np
        n = len(lens)
        if n == 0:
            return
        g_arr = np.ascontiguousarray(groups, np.uint32)
        i_arr = np.ascontiguousarray(idxs, np.uint64)
        t_arr = np.ascontiguousarray(terms, np.int64)
        o_arr = np.ascontiguousarray(offs, np.uint64)
        l_arr = np.ascontiguousarray(lens, np.uint32)
        ptr = lambda a: a.ctypes.data_as(ctypes.c_void_p)
        self._lib.wal_append_entries(
            self._h, n, ptr(g_arr), ptr(i_arr), ptr(t_arr), blob,
            ptr(o_arr), ptr(l_arr))


_MAGIC = 0x52574131
_ENTRY, _STABLE, _TRUNCATE, _MILESTONE, _RESET = 1, 2, 3, 4, 5


def _export_arrays(G: int, L: int) -> dict:
    """The shared export_state output schema — ONE definition so the two
    engines (and restore_raft_state, which depends on the exact defaults,
    e.g. ballot=-1 masked by has_stable) cannot drift."""
    import numpy as np
    return {
        "stable_term": np.zeros(G, np.int64),
        "ballot": np.full(G, -1, np.int64),
        "has_stable": np.zeros(G, np.uint8),
        "floor": np.zeros(G, np.int64),
        "floor_term": np.zeros(G, np.int64),
        "tail": np.zeros(G, np.int64),
        "live_count": np.zeros(G, np.int64),
        "ring": np.zeros((G, L), np.int32),
    }


class _PyGroup:
    __slots__ = ("tail", "floor", "floor_term", "stable", "entries")

    def __init__(self):
        self.tail = 0
        self.floor = 0
        self.floor_term = 0
        self.stable = None  # (term, ballot)
        self.entries: Dict[int, tuple] = {}  # idx -> (term, payload)

    def drop_suffix(self, frm):
        for i in [i for i in self.entries if i >= frm]:
            del self.entries[i]
        self.tail = min(self.tail, frm - 1)

    def drop_prefix(self, upto):
        for i in [i for i in self.entries if i <= upto]:
            del self.entries[i]


def _apply_record(groups: Dict[int, "_PyGroup"], body: bytes) -> None:
    """Apply one record body to a group map (shared by live replay and the
    GC worker's private replay)."""
    def G(g):
        return groups.setdefault(g, _PyGroup())
    t = body[0]
    if t == _ENTRY:
        g, idx, term, plen = struct.unpack_from("<IQQI", body, 1)
        gs = G(g)
        gs.drop_suffix(idx)
        gs.entries[idx] = (_signed(term), bytes(body[25:25 + plen]))
        gs.tail = idx
    elif t == _STABLE:
        g, term, ballot = struct.unpack_from("<IQQ", body, 1)
        G(g).stable = (_signed(term), _signed(ballot))
    elif t == _TRUNCATE:
        g, frm = struct.unpack_from("<IQ", body, 1)
        G(g).drop_suffix(frm)
    elif t == _MILESTONE:
        g, idx, term = struct.unpack_from("<IQQ", body, 1)
        gs = G(g)
        # `>=` (not `>`): re-applying the current milestone must be a full
        # state no-op incl. drop_prefix/tail-raise — the GC crash window
        # replays stale frozen segments AFTER the compacted base.
        if idx >= gs.floor:
            gs.floor, gs.floor_term = idx, _signed(term)
            gs.drop_prefix(idx)
            gs.tail = max(gs.tail, gs.floor)
    elif t == _RESET:
        (g,) = struct.unpack_from("<I", body, 1)
        groups.pop(g, None)


def _replay_file(path: str, groups: Dict[int, "_PyGroup"],
                 fix_tail: bool = True) -> None:
    with open(path, "rb") as f:
        data = f.read()
    off, n = 0, len(data)
    while off + 12 <= n:
        magic, blen, crc = struct.unpack_from("<III", data, off)
        if magic != _MAGIC or off + 12 + blen > n:
            break
        body = data[off + 12: off + 12 + blen]
        if zlib.crc32(body) != crc:
            break
        _apply_record(groups, body)
        off += 12 + blen
    if fix_tail and off < n:
        with open(path, "r+b") as f:
            f.truncate(off)


def _live_records(groups: Dict[int, "_PyGroup"]) -> bytes:
    """Framed compacted records for a group map (the GC base segment)."""
    out = bytearray()

    def emit(body: bytes):
        out.extend(struct.pack("<III", _MAGIC, len(body), zlib.crc32(body)))
        out.extend(body)

    for g, gs in groups.items():
        if gs.stable is not None:
            t, b = gs.stable
            emit(struct.pack("<BIQQ", _STABLE, g, t & M64, b & M64))
        if gs.floor > 0:
            emit(struct.pack("<BIQQ", _MILESTONE, g, gs.floor,
                             gs.floor_term & M64))
        for idx in sorted(gs.entries):
            term, payload = gs.entries[idx]
            emit(struct.pack("<BIQQI", _ENTRY, g, idx, term & M64,
                             len(payload)) + payload)
    return bytes(out)


class PyWal:
    """Pure-Python engine, byte-compatible with the native one."""

    def __init__(self, path: str, segment_bytes: int = 64 << 20):
        self.dir = path
        self.segment_bytes = segment_bytes
        os.makedirs(path, exist_ok=True)
        try:
            os.unlink(os.path.join(path, "gc.tmp"))  # crashed mid-GC: re-derivable
        except OSError:
            pass
        self.groups: Dict[int, _PyGroup] = {}
        segs = sorted(int(f[:8]) for f in os.listdir(path)
                      if f.endswith(".wal") and f[:8].isdigit())
        for sid in segs:
            self._replay(sid)
        self._segs = segs or [0]
        self._sid = self._segs[-1]
        self._f = open(self._seg_path(self._sid), "ab")
        self._buf = bytearray()
        self._gc = None  # {"frozen": [ids], "rewritten": bool}
        # Failure latches + injectable fault table, mirroring the native
        # engine: staging never raises — errors latch here and surface at
        # the sync barrier; `poisoned` is fail-stop for the engine's life.
        self.shard_id = 0
        self.poisoned = False
        self.last_errno = 0
        self._err = ""
        self._faults: Dict[str, list] = {}  # op -> [after, value]
        self._sync_delay_us = 0
        # Same stats schema as the native engine (WAL_STAT_KEYS).
        # stage_ns stays 0 here: Python staging is interleaved with the
        # caller's own loop, so a per-record clock read would measure the
        # clock, not the work; bytes/calls and fsync timing are exact.
        self._stat = dict.fromkeys(WAL_STAT_KEYS, 0)

    def _seg_path(self, sid):
        return os.path.join(self.dir, f"{sid:08d}.wal")

    def _g(self, g) -> _PyGroup:
        return self.groups.setdefault(g, _PyGroup())

    def _replay(self, sid):
        _replay_file(self._seg_path(sid), self.groups)

    def error(self) -> str:
        return self._err

    def set_fault(self, op: str, after: int = 0, value: int = 0) -> None:
        """Arm an injected fault: same op vocabulary and countdown
        semantics as the native engine's wal_fault_set (after=N fires on
        the (N+1)-th guarded call, then disarms)."""
        assert op in _FAULT_OPS
        if op == "delay":
            self._sync_delay_us = int(value)
            return
        if value == 0 and op in ("fsync", "write"):
            value = _errno.EIO
        self._faults[op] = [int(after), int(value)]

    def clear_faults(self) -> None:
        """Disarm pending countdowns; does NOT heal `poisoned`."""
        self._faults.clear()
        self._sync_delay_us = 0

    def _fault_fire(self, op: str):
        f = self._faults.get(op)
        if f is None:
            return None
        if f[0] == 0:
            del self._faults[op]
            return f[1]
        f[0] -= 1
        return None

    def _emit(self, body: bytes):
        self._buf += struct.pack("<III", _MAGIC, len(body), zlib.crc32(body))
        self._buf += body
        self._stat["bytes"] += 12 + len(body)
        self._stat["stage_calls"] += 1
        if self._f.tell() + len(self._buf) >= self.segment_bytes:
            if not self._flush():
                return  # failure surfaces at the sync barrier
            try:
                os.fsync(self._f.fileno())
            except OSError as e:
                self._latch(e)
                self.poisoned = True  # never retry fsync on a failed fd
                return
            self._f.close()
            self._sid += 1
            self._segs.append(self._sid)
            self._f = open(self._seg_path(self._sid), "wb")

    def _latch(self, e: OSError) -> None:
        self._err = str(e)
        self.last_errno = e.errno or _errno.EIO

    def _flush(self) -> bool:
        """Write the staged buffer; never raises — failures latch and
        surface at the barrier.  ENOSPC rewinds the segment to the last
        good offset and KEEPS the buffer (retriable); any other failure
        poisons the engine."""
        if self.poisoned:
            return False
        if not self._buf:
            return True
        good = self._f.tell()
        try:
            keep = self._fault_fire("short")
            if keep is not None:
                keep = max(0, min(int(keep), len(self._buf)))
                self._f.write(self._buf[:keep])
                self._f.flush()
                raise iofault.TornWrite(keep)
            inj = self._fault_fire("write")
            if inj is not None:
                raise OSError(int(inj), os.strerror(int(inj)))
            self._f.write(self._buf)
            self._f.flush()
        except OSError as e:
            self._latch(e)
            if self.last_errno == _errno.ENOSPC:
                try:
                    self._f.seek(good)
                    self._f.truncate(good)
                except OSError:
                    self.poisoned = True
            else:
                self.poisoned = True
            return False
        self._buf = bytearray()
        return True

    # -- same surface as _NativeWal ------------------------------------
    def append_entry(self, g, idx, term, payload: bytes):
        gs = self._g(g)
        gs.drop_suffix(idx)
        gs.entries[idx] = (term, bytes(payload))
        gs.tail = idx
        self._emit(struct.pack("<BIQQI", _ENTRY, g, idx, term & M64,
                               len(payload)) + payload)

    def append_stable(self, g, term, ballot):
        self._g(g).stable = (term, ballot)
        self._emit(struct.pack("<BIQQ", _STABLE, g, term & M64, ballot & M64))

    def truncate(self, g, frm):
        self._g(g).drop_suffix(frm)
        self._emit(struct.pack("<BIQ", _TRUNCATE, g, frm))

    def milestone(self, g, idx, term):
        gs = self._g(g)
        if idx >= gs.floor:  # mirror _apply_record's replay semantics
            gs.floor, gs.floor_term = idx, term
            gs.drop_prefix(idx)
            gs.tail = max(gs.tail, gs.floor)
        self._emit(struct.pack("<BIQQ", _MILESTONE, g, idx, term & M64))

    def reset(self, g):
        """Group destroyed: forget the lane's entire durable state."""
        self.groups.pop(g, None)
        self._emit(struct.pack("<BI", _RESET, g))

    def _raise_sync_error(self):
        msg = self._err or "wal_sync failed"
        if self.last_errno == _errno.ENOSPC and not self.poisoned:
            raise WalNoSpace(msg, (self.shard_id,))
        raise WalSyncError(msg, (self.shard_id,))

    def sync(self):
        if self.poisoned:
            self._raise_sync_error()
        # Timed from here (incl. injected sync delays) to mirror the
        # native engine's wal_sync stats window.
        _t0 = time.perf_counter()
        if self._sync_delay_us > 0:
            time.sleep(self._sync_delay_us / 1e6)
        if not self._flush():
            self._raise_sync_error()
        try:
            inj = self._fault_fire("fsync")
            if inj is not None:
                raise OSError(int(inj), "injected fsync failure")
            self._f.flush()
            os.fsync(self._f.fileno())
        except OSError as e:
            self._latch(e)
            self.poisoned = True
            self._raise_sync_error()
        self._stat["fsync_ns"] += int((time.perf_counter() - _t0) * 1e9)
        self._stat["fsync_calls"] += 1

    def stats(self) -> Dict[str, int]:
        return dict(self._stat)

    def tail(self, g):
        return self.groups[g].tail if g in self.groups else 0

    def floor(self, g):
        return self.groups[g].floor if g in self.groups else 0

    def floor_term(self, g):
        return self.groups[g].floor_term if g in self.groups else 0

    def stable(self, g):
        return self.groups[g].stable if g in self.groups else None

    def entry_term(self, g, idx):
        gs = self.groups.get(g)
        if gs is None:
            return -1
        if idx == gs.floor:
            return gs.floor_term
        e = gs.entries.get(idx)
        return e[0] if e else -1

    def entry_payload(self, g, idx):
        gs = self.groups.get(g)
        e = gs.entries.get(idx) if gs else None
        return e[1] if e else None

    # -- three-phase GC (begin/finish on the tick thread, rewrite on a
    # worker; same contract as the native engine's wal_gc_*) ------------

    def gc_begin(self) -> int:
        if self._gc is not None:
            return -1
        if not self._flush():
            return -1  # latched failure surfaces at the sync barrier
        try:
            os.fsync(self._f.fileno())
        except OSError as e:
            self._latch(e)
            self.poisoned = True
            return -1
        self._f.close()
        frozen = list(self._segs)
        self._sid += 1
        self._segs.append(self._sid)
        self._f = open(self._seg_path(self._sid), "wb")
        self._gc = {"frozen": frozen, "rewritten": False}
        return len(frozen)

    def gc_rewrite(self) -> int:
        """Worker-thread safe: replays the frozen FILES into a private map
        (never touches self.groups / self._buf) and writes the compacted
        base to gc.tmp."""
        gc = self._gc
        if gc is None or gc["rewritten"]:
            return -1
        priv: Dict[int, _PyGroup] = {}
        for sid in gc["frozen"]:
            _replay_file(self._seg_path(sid), priv, fix_tail=False)
        blob = _live_records(priv)
        tmp = os.path.join(self.dir, "gc.tmp")
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        gc["rewritten"] = True
        return len(blob)

    def gc_finish(self) -> int:
        gc = self._gc
        if gc is None or not gc["rewritten"]:
            return -1
        frozen = gc["frozen"]
        base = frozen[0]
        os.replace(os.path.join(self.dir, "gc.tmp"), self._seg_path(base))
        # Make the rename durable BEFORE the unlinks: without the directory
        # fsync, POSIX may persist the unlinks but not the rename, losing
        # every live record that lived in frozen[1:].
        dfd = os.open(self.dir, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        for sid in frozen[1:]:
            try:
                os.unlink(self._seg_path(sid))
            except OSError:
                pass
        self._segs = [base] + [s for s in self._segs if s not in frozen]
        self._gc = None
        return 0

    def gc_abort(self) -> None:
        try:
            os.unlink(os.path.join(self.dir, "gc.tmp"))
        except OSError:
            pass
        self._gc = None

    def checkpoint(self):
        if self._gc is not None:
            raise IOError("checkpoint refused: three-phase GC pending")
        if not self._flush():
            self._raise_sync_error()
        os.fsync(self._f.fileno())
        self._f.close()
        old = list(self._segs)
        self._sid += 1
        new_id = self._sid
        self._segs = [new_id]
        self._f = open(self._seg_path(new_id), "wb")
        # Same serialization as the GC base (one definition, no drift).
        self._buf += _live_records(self.groups)
        self.sync()
        for sid in old:
            if sid not in self._segs:
                os.unlink(self._seg_path(sid))

    def segment_count(self):
        return len(self._segs)

    def export_state(self, G: int, L: int) -> dict:
        """Bulk boot-time restore (same contract as the native engine's
        wal_export_state; loops only over live groups)."""
        out = _export_arrays(G, L)
        for g, gs in self.groups.items():
            if g >= G:
                continue
            if gs.stable is not None:
                out["stable_term"][g], out["ballot"][g] = gs.stable
                out["has_stable"][g] = 1
            out["floor"][g] = gs.floor
            out["floor_term"][g] = gs.floor_term
            out["tail"][g] = gs.tail
            cnt = 0
            for idx, (term, _) in gs.entries.items():
                if gs.floor < idx <= gs.tail:
                    out["ring"][g, idx % L] = term
                    cnt += 1
            out["live_count"][g] = cnt
        return out

    def append_batch(self, groups, idxs, terms, payloads) -> None:
        for g, i, t, p in zip(groups, idxs, terms, payloads):
            self.append_entry(int(g), int(i), int(t), p)

    def append_arena(self, groups, idxs, terms, blob, offs, lens) -> None:
        """Arena variant (same contract as the native engine's): slices the
        blob per entry — the Python engine is the no-compiler fallback, so
        per-entry cost is acceptable here."""
        mv = memoryview(blob)
        for g, i, t, o, ln in zip(groups, idxs, terms, offs, lens):
            o = int(o)
            self.append_entry(int(g), int(i), int(t), bytes(mv[o:o + int(ln)]))

    def total_bytes(self):
        total = len(self._buf) + self._f.tell()
        for sid in self._segs[:-1]:
            try:
                total += os.path.getsize(self._seg_path(sid))
            except OSError:
                pass
        return total

    def live_bytes(self):
        # Mirrors the native accounting: frame (12) + record body sizes.
        live = 0
        for gs in self.groups.values():
            if gs.stable is not None:
                live += 12 + 21
            if gs.floor > 0:
                live += 12 + 21
            for term, payload in gs.entries.values():
                live += 12 + 25 + len(payload)
        return live

    def close(self):
        try:
            self._flush()
            os.fsync(self._f.fileno())
        except OSError:
            pass  # closing a poisoned/failing engine must not raise
        self._f.close()


M64 = (1 << 64) - 1


def _signed(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


class ConfMeta:
    """Durable membership-config sidecar: the WAL meta file that lets
    recovery restore the §6 active voter set.

    The engine's conf ring (core/types.py LogState.conf) carries one
    packed config word per live config entry; the WAL proper persists
    entry (term, payload) only — config entries travel with EMPTY
    payloads like the §8 no-op.  This sidecar records, per group, every
    LIVE config entry's (index, word) plus the config as of the
    compaction floor, so ``restore_raft_state`` rebuilds the conf ring
    and base_conf exactly.  Maintained write-through by the LogStore
    (put/truncate/set_floor/reset mirror the entry paths) and flushed —
    atomic tmp+rename+fsync — inside the store's ``sync()`` barrier, so
    a config is durable before any RPC built on it leaves the node.
    Config changes are rare; the whole file is a few entries per group
    that ever reconfigured, and a flush only happens on change."""

    def __init__(self, path: str):
        import json
        self.path = path
        self._g: dict = {}       # g -> {"floor": word, "entries": {idx: w}}
        self._dirty = False
        try:
            with open(path) as f:
                doc = json.load(f)
            for g, ent in doc.get("groups", {}).items():
                self._g[int(g)] = {
                    "floor": int(ent.get("floor", 0)),
                    "entries": {int(i): int(w)
                                for i, w in ent.get("entries", {}).items()},
                }
        except (OSError, ValueError):
            pass

    def _ent(self, g: int) -> dict:
        ent = self._g.get(g)
        if ent is None:
            ent = self._g[g] = {"floor": 0, "entries": {}}
        return ent

    def put(self, g: int, idx: int, word: int) -> None:
        ent = self._ent(g)
        # Overwrite semantics like the WAL itself: an append at idx kills
        # any recorded config entries at >= idx (they were truncated).
        for i in [i for i in ent["entries"] if i > idx]:
            del ent["entries"][i]
        ent["entries"][idx] = word
        self._dirty = True

    def truncate(self, g: int, tail: int) -> None:
        ent = self._g.get(g)
        if not ent:
            return
        drop = [i for i in ent["entries"] if i > tail]
        for i in drop:
            del ent["entries"][i]
        if drop:
            self._dirty = True

    def set_floor(self, g: int, index: int, conf_word: int = 0) -> None:
        """Fold config entries at/under the new floor into the floor word
        (the latest one wins — it IS the config as of ``index``).  A
        nonzero ``conf_word`` then pins the floor config explicitly (the
        snapshot-install path: the offered milestone's config is the
        config AS OF ``index``, newer than or equal to any folded
        entry)."""
        ent = self._g.get(g)
        if ent is None:
            if not conf_word:
                return
            ent = self._ent(g)
        folded = [i for i in sorted(ent["entries"]) if i <= index]
        for i in folded:
            ent["floor"] = ent["entries"].pop(i)
        if conf_word:
            ent["floor"] = int(conf_word)
        if folded or conf_word:
            self._dirty = True

    def reset(self, g: int) -> None:
        if self._g.pop(g, None) is not None:
            self._dirty = True

    def export(self) -> dict:
        """{g: (floor_word, {idx: word})} for recovery (groups that ever
        reconfigured only)."""
        return {g: (ent["floor"], dict(ent["entries"]))
                for g, ent in self._g.items()}

    def flush(self) -> None:
        if not self._dirty:
            return
        import json
        doc = {"groups": {str(g): {"floor": ent["floor"],
                                   "entries": {str(i): w for i, w
                                               in ent["entries"].items()}}
                          for g, ent in self._g.items()}}
        tmp = self.path + ".tmp"
        try:
            iofault.check("conf.flush", self.path)
            with open(tmp, "w") as f:
                json.dump(doc, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except OSError as e:
            # Global transient, nothing poisoned: the dirty flag stays
            # set, so the next barrier retries the whole flush (tmp file
            # writes are idempotent).
            raise WalSyncError(f"conf flush: {e}", ()) from e
        # The rename itself must be durable before the caller's barrier
        # completes (same rule as the WAL GC swap): fsync the directory.
        try:
            dfd = os.open(os.path.dirname(self.path) or ".", os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass
        self._dirty = False


_SHARD_META = "wal_shards.json"


class ShardedWal:
    """S independent WAL engines keyed by group stripe (``g % S``).

    Same surface as ``_NativeWal``/``PyWal``.  Groups are disjoint across
    shards, so every per-group operation routes to exactly one engine and
    recovery is the union of per-shard replays (torn-tail truncation runs
    per shard file, as ever).  ``sync`` fans the S fsyncs out to a worker
    pool and joins them — one barrier, S spindles' worth of parallelism.
    """

    def __init__(self, path: str, segment_bytes: int, shards: int, *,
                 force_python: bool = False):
        from concurrent.futures import ThreadPoolExecutor

        assert shards >= 1
        self.dir = path
        self.n_shards = shards
        os.makedirs(path, exist_ok=True)
        self.engines = []
        for k in range(shards):
            sub = os.path.join(path, f"shard{k:02d}")
            if not force_python and native_available():
                eng = _NativeWal(sub, segment_bytes)
            else:
                eng = PyWal(sub, segment_bytes)
            eng.shard_id = k  # barrier failures carry true stripe ids
            self.engines.append(eng)
        self._pool = ThreadPoolExecutor(
            max_workers=min(shards, 8),
            thread_name_prefix="wal-fsync") if shards > 1 else None
        self._gc_active = [False] * shards
        # Raw engine handles for the native host tier (one ctypes call
        # staging every shard) — only when EVERY shard is native.
        self._handles = None
        if all(isinstance(e, _NativeWal) for e in self.engines):
            self._handles = (ctypes.c_void_p * shards)(
                *[e._h for e in self.engines])

    def _e(self, g):
        return self.engines[g % self.n_shards]

    @property
    def can_stage_native(self) -> bool:
        return self._handles is not None and native_host_available()

    def stage_and_sync(self, groups, idxs, terms, ptrs, lens,
                       trunc_g, trunc_from, floor_g, floor_idx, floor_term,
                       *, workers: int = 1, sync: bool = True):
        """Stage a whole tick's entries/truncations/milestones across every
        shard — and fsync them — in ONE native call with real OS threads
        (worker k owns shards ``s % W == k``, the striped pool's ownership
        map, so per-shard record order and segment bytes are identical to
        the Python paths).  Returns ``(stage_s, fsync_s)``."""
        return _native_stage_and_sync(
            self._handles, self.n_shards, self.engines, workers, sync,
            groups, idxs, terms, ptrs, lens,
            trunc_g, trunc_from, floor_g, floor_idx, floor_term)

    def pack_ae(self, cols, starts, ns, *, workers: int = 1):
        """Native AppendEntries payload-blob pack over the shards' own
        entry indexes; ``None`` when the native tier is unavailable."""
        if not self.can_stage_native:
            return None
        return _native_pack_ae(self._handles, self.n_shards, workers,
                               cols, starts, ns)

    # -- staging (routes to one shard) ---------------------------------
    def append_entry(self, g, idx, term, payload: bytes):
        self._e(g).append_entry(g, idx, term, payload)

    def append_stable(self, g, term, ballot):
        self._e(g).append_stable(g, term, ballot)

    def truncate(self, g, frm):
        self._e(g).truncate(g, frm)

    def milestone(self, g, idx, term):
        self._e(g).milestone(g, idx, term)

    def reset(self, g):
        self._e(g).reset(g)

    def append_batch(self, groups, idxs, terms, payloads) -> None:
        import numpy as np
        n = len(groups)
        if n == 0:
            return
        lens = np.fromiter((len(p) for p in payloads), np.uint32, n)
        offs = np.zeros(n, np.uint64)
        offs[1:] = np.cumsum(lens[:-1], dtype=np.uint64)
        self.append_arena(groups, idxs, terms, b"".join(payloads), offs, lens)

    def append_arena(self, groups, idxs, terms, blob, offs, lens) -> None:
        """One arena call per MOVED stripe: the shared blob crosses into
        each engine with that stripe's (group, idx, term, off, len)
        columns — offsets stay absolute into the caller's blob, so no
        bytes are copied or re-joined on the split."""
        import numpy as np
        n = len(lens)
        if n == 0:
            return
        g_arr = np.ascontiguousarray(groups, np.uint32)
        i_arr = np.ascontiguousarray(idxs, np.uint64)
        t_arr = np.ascontiguousarray(terms, np.int64)
        o_arr = np.ascontiguousarray(offs, np.uint64)
        l_arr = np.ascontiguousarray(lens, np.uint32)
        stripe = g_arr % np.uint32(self.n_shards)
        for k in np.unique(stripe).tolist():
            m = stripe == k
            self.engines[k].append_arena(
                g_arr[m], i_arr[m], t_arr[m], blob, o_arr[m], l_arr[m])

    # -- the durability barrier ----------------------------------------
    def sync(self):
        """Parallel fsync across shards with a single barrier join:
        returns only when EVERY shard is durable (any failure raises —
        a partially durable barrier must never be acknowledged)."""
        if self._pool is None:
            self.engines[0].sync()
            return
        futs = [self._pool.submit(e.sync) for e in self.engines]
        errs = []
        for f in futs:
            try:
                f.result()
            except Exception as e:  # join ALL before raising
                errs.append(e)
        err = _merge_wal_errors(errs)
        if err is not None:
            raise err

    def sync_shards(self, shard_ids) -> None:
        """Fsync only the given shard engines, inline on the calling
        thread — the striped host tier's durability barrier: each worker
        owns a disjoint set of shards end-to-end (staging AND fsync), so
        no cross-thread coordination or pool handoff is needed.  Syncs
        EVERY requested shard before raising the merged failure (the
        caller must not acknowledge the tick, but healthy shards still
        become durable)."""
        errs = []
        for k in shard_ids:
            try:
                self.engines[k].sync()
            except Exception as e:
                errs.append(e)
        err = _merge_wal_errors(errs)
        if err is not None:
            raise err

    # -- injectable fault table (testkit/faultfs) ----------------------
    def set_fault(self, op: str, after: int = 0, value: int = 0,
                  shard: int = 0) -> None:
        self.engines[shard % self.n_shards].set_fault(op, after, value)

    def clear_faults(self) -> None:
        for e in self.engines:
            e.clear_faults()

    def poisoned_shards(self):
        return [k for k, e in enumerate(self.engines) if e.poisoned]

    # -- instrumentation -----------------------------------------------
    def stats(self) -> Dict[str, int]:
        """Sum of per-stripe engine stats (WAL_STAT_KEYS)."""
        out = dict.fromkeys(WAL_STAT_KEYS, 0)
        for e in self.engines:
            for k, v in e.stats().items():
                out[k] += v
        return out

    def stats_per_stripe(self):
        """Per-stripe stats, index-aligned with the engine list."""
        return [e.stats() for e in self.engines]

    # -- per-group reads -----------------------------------------------
    def tail(self, g):
        return self._e(g).tail(g)

    def floor(self, g):
        return self._e(g).floor(g)

    def floor_term(self, g):
        return self._e(g).floor_term(g)

    def stable(self, g):
        return self._e(g).stable(g)

    def entry_term(self, g, idx):
        return self._e(g).entry_term(g, idx)

    def entry_payload(self, g, idx):
        return self._e(g).entry_payload(g, idx)

    # -- maintenance / GC ----------------------------------------------
    def checkpoint(self):
        for e in self.engines:
            e.checkpoint()

    def gc_begin(self) -> int:
        """Begin on every shard; -1 (and full rollback) unless ALL shards
        enter the frozen state — a half-begun GC would desynchronize the
        runtime's single three-phase state machine."""
        begun = []
        for k, e in enumerate(self.engines):
            if e.gc_begin() < 0:
                for j in begun:
                    self.engines[j].gc_abort()
                    self._gc_active[j] = False
                return -1
            begun.append(k)
            self._gc_active[k] = True
        return len(begun)

    def gc_rewrite(self) -> int:
        total = 0
        for k, e in enumerate(self.engines):
            if not self._gc_active[k]:
                continue
            r = e.gc_rewrite()
            if r < 0:
                return -1
            total += r
        return total

    def gc_finish(self) -> int:
        rc = 0
        for k, e in enumerate(self.engines):
            if not self._gc_active[k]:
                continue
            r = e.gc_finish()
            if r != 0:
                rc = r
            else:
                self._gc_active[k] = False
        return rc

    def gc_abort(self) -> None:
        for k, e in enumerate(self.engines):
            e.gc_abort()
            self._gc_active[k] = False

    def segment_count(self):
        return sum(e.segment_count() for e in self.engines)

    def total_bytes(self):
        return sum(e.total_bytes() for e in self.engines)

    def live_bytes(self):
        return sum(e.live_bytes() for e in self.engines)

    def export_state(self, G: int, L: int) -> dict:
        """Merged boot-time restore: shards hold disjoint group stripes,
        so the union is a per-stripe masked copy of each shard's export."""
        import numpy as np
        out = _export_arrays(G, L)
        gi = np.arange(G)
        for k, e in enumerate(self.engines):
            ex = e.export_state(G, L)
            m = (gi % self.n_shards) == k
            for name, arr in out.items():
                arr[m] = ex[name][m]
        return out

    def close(self):
        for e in self.engines:
            e.close()
        if self._pool is not None:
            self._pool.shutdown(wait=True)


def _pin_shards(path: str, requested: int) -> int:
    """Resolve the stripe count for a WAL directory: a pinned meta wins
    (recovery must read the layout that was written); a legacy flat
    directory with segments is S=1; otherwise pin the requested count."""
    import json
    meta = os.path.join(path, _SHARD_META)
    try:
        with open(meta) as f:
            return max(1, int(json.load(f)["shards"]))
    except (OSError, ValueError, KeyError):
        pass
    try:
        has_flat = any(f.endswith(".wal") for f in os.listdir(path))
    except OSError:
        has_flat = False
    if has_flat:
        return 1
    if requested > 1:
        os.makedirs(path, exist_ok=True)
        tmp = meta + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"shards": requested}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, meta)
    return requested


def WalStore(path: str, segment_bytes: int = 64 << 20, *,
             force_python: bool = False, shards: int = 1):
    """Open a WAL store at `path`, preferring the native engine.

    ``shards`` > 1 stripes groups over that many independent engines
    (``ShardedWal``); the count is pinned in the directory's meta file,
    so a restart recovers with the layout the data was written under
    regardless of what the caller asks for."""
    shards = _pin_shards(path, shards)
    if shards > 1:
        return ShardedWal(path, segment_bytes, shards,
                          force_python=force_python)
    if not force_python and native_available():
        return _NativeWal(path, segment_bytes)
    return PyWal(path, segment_bytes)
