"""Durable host log tier for the TPU Multi-Raft node.

Device HBM holds only entry *terms* (the consensus metadata the kernels
need); this package owns the bytes: a native C++ segmented WAL engine
(:mod:`wal`) journaling all groups of a node with one fsync per tick, and
the :class:`LogStore` facade (:mod:`store`) that the node runtime drives —
the TPU-native replacement for the reference's per-group RocksDB stores
(curioloop/rafting command/storage/RocksLog.java) and StableLock records
(support/StableLock.java).
"""

from .wal import WalStore, native_available  # noqa: F401
from .store import LogStore  # noqa: F401
from .spi import LogStoreSPI  # noqa: F401
from .memstore import MemoryLogStore  # noqa: F401
