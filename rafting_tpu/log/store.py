"""LogStore: the host-side log facade the node runtime drives each tick.

Responsibilities (mapping to the reference's storage contracts):

* durable entry payloads + terms  — RaftLog.newEntry/append
  (command/RaftLog.java:11-134, command/storage/RocksLog.java:82-196)
* suffix truncation on conflict   — RaftLog.truncate (RocksLog.java:219-225)
* compaction floor ("epoch")      — RaftLog.flush (RocksLog.java:228-242)
* durable (term, ballot)          — StableLock (support/StableLock.java:69-80)
* milestone (snapshot index/term) — StableLock milestone (82-91)
* crash recovery → device state   — RaftContext.initialize restore path
  (context/RaftContext.java:91-113)

The tick protocol (enforced by the node runtime): all writes implied by a
device step are staged, then ONE :meth:`sync` makes them durable *before*
any RPC produced by that step leaves the node — the reference's
persist-before-reply rule (context/member/RaftMember.java:25,
RocksLog.flushWal after append) amortized over every group at once.

A bounded in-memory payload cache keeps the replication hot path
(leader batch fetch) off the WAL read path; entries below the compaction
floor are pruned as the floor advances.
"""

from __future__ import annotations

from bisect import bisect_right
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import os

from ..transport.codec import PayloadRun
from .wal import ConfMeta, WalStore


class LogStore:
    def __init__(self, path: str, segment_bytes: int = 64 << 20, *,
                 force_python: bool = False, shards: int = 1):
        """``shards`` > 1 stripes groups over that many independent WAL
        engines (log/wal.py ShardedWal): appends land as one arena call
        per moved stripe and :meth:`sync` fsyncs the stripes in parallel
        behind a single barrier.  The count is pinned in the directory at
        creation, so recovery always reads the written layout."""
        self.wal = WalStore(path, segment_bytes, force_python=force_python,
                            shards=shards)
        # Membership sidecar (§6 durable config): live config entries +
        # floor config per group, flushed inside sync()'s barrier.
        self.conf = ConfMeta(os.path.join(path, "conf_meta.json"))
        # group -> ([run starts], [PayloadRun]) sorted by start: the hot
        # mirror of the live window as contiguous arena runs — the same
        # currency the wire codec and the staging path speak, so cache
        # maintenance is O(runs touched) and reads for the replication/
        # apply windows are buffer slices, never per-entry dict ops (the
        # per-entry bytes cache was ~15% of the durable tick at 32k).
        # Keyed per group so floor/truncate/reset maintenance touches only
        # that group's runs.
        self._cache: Dict[int, Tuple[List[int], List[PayloadRun]]] = {}
        # last durable (term, ballot) per group, to skip no-op stable writes
        self._stable: Dict[int, tuple] = {}
        self._durable_tail: Dict[int, int] = {}

    # -- the run cache -------------------------------------------------------

    # Trim re-materialization thresholds: a milestone/truncate trim slices
    # a run's offs/lens but keeps ``buf`` — which may be (a view into) a
    # whole 64MB MSGS frame or staging arena.  When the surviving entries
    # cover under 1/_COMPACT_RATIO of the pinned frame and the frame is
    # big enough to matter, the remainder is copied into a compact buffer
    # so one cached entry can no longer pin a frame-sized allocation
    # (ADVICE r5: resident-memory inflation at 100k groups with mixed
    # progress).
    _COMPACT_MIN_FRAME = 1 << 16
    _COMPACT_RATIO = 4

    @staticmethod
    def _frame_bytes(buf) -> int:
        """True pinned size: a memoryview keeps its WHOLE exporter alive,
        so the slice length understates what the cache is holding."""
        if isinstance(buf, memoryview):
            base = buf.obj
            if base is not None:
                try:
                    return memoryview(base).nbytes
                except TypeError:
                    pass
            return buf.nbytes
        return len(buf)

    @classmethod
    def _maybe_compact(cls, run: PayloadRun) -> PayloadRun:
        """Re-materialize a trimmed run into a compact private buffer when
        it covers a small fraction of the frame it pins."""
        n = len(run.lens)
        if not n:
            # A fully trimmed run must not keep its (possibly frame-sized)
            # exporter alive through the buf reference.
            return PayloadRun(run.start, b"", run.offs[:0], run.lens[:0])
        frame = cls._frame_bytes(run.buf)
        if frame < cls._COMPACT_MIN_FRAME:
            return run
        live = int(run.offs[n - 1]) + int(run.lens[n - 1]) - int(run.offs[0])
        if live * cls._COMPACT_RATIO >= frame:
            return run
        return PayloadRun(run.start, bytes(run.piece(0, n)),
                          run.offs - run.offs[0], run.lens)

    def _add_run(self, g: int, run: PayloadRun) -> None:
        """Insert a freshly written run (overwrite semantics: any cached
        entry at >= run.start dies first, mirroring the WAL's replay)."""
        if not len(run.lens):
            return   # empty runs have no overwrite effect
        starts, runs = self._cache.setdefault(g, ([], []))
        while starts and starts[-1] >= run.start:
            starts.pop()
            runs.pop()
        if runs and runs[-1].end >= run.start:
            r = runs[-1]
            keep = run.start - r.start
            # Compact like every other trim site: an overwrite that lops a
            # run down to a sliver must not leave the sliver pinning a
            # frame-sized buffer (ROADMAP carry-forward, log/store.py:55).
            runs[-1] = self._maybe_compact(
                PayloadRun(r.start, r.buf, r.offs[:keep], r.lens[:keep]))
        starts.append(run.start)
        runs.append(run)

    def _run_at(self, g: int, idx: int) -> Optional[PayloadRun]:
        ent = self._cache.get(g)
        if not ent:
            return None
        starts, runs = ent
        i = bisect_right(starts, idx) - 1
        if i < 0:
            return None
        r = runs[i]
        return r if r.end >= idx else None

    def _backfill(self, g: int, idx: int, payload: bytes) -> None:
        """Cache a WAL read as a one-entry run WITHOUT the overwrite
        semantics of _add_run (a backfill of an OLD index must never
        evict newer cached runs).  Skipped if anything already covers or
        collides at the insertion point — the WAL stays authoritative."""
        starts, runs = self._cache.setdefault(g, ([], []))
        i = bisect_right(starts, idx)
        if i > 0 and runs[i - 1].end >= idx:
            return                      # already covered
        starts.insert(i, idx)
        runs.insert(i, PayloadRun.single(idx, payload))

    # -- staging writes (durable after sync()) ------------------------------

    def append_entries(self, g: int, start: int, terms: Sequence[int],
                       payloads: Sequence[bytes]) -> None:
        """Write entries [start, start+len) (overwrite semantics)."""
        if not len(payloads):
            return   # a degenerate empty run must not evict cached suffix
        for k, (t, p) in enumerate(zip(terms, payloads)):
            self.wal.append_entry(g, start + k, int(t), p)
        self._add_run(g, PayloadRun.from_payloads(start, list(payloads)))
        self._durable_tail[g] = max(self._durable_tail.get(g, 0),
                                    start + len(terms) - 1)

    def append_batch(self, groups: Sequence[int], idxs: Sequence[int],
                     terms: Sequence[int], payloads: Sequence[bytes]) -> None:
        """Stage a whole tick's appends across all groups in one engine
        call (native: one ctypes crossing; the batching analog of the
        reference's group-commit WAL flush, RocksLog flushWal after a
        batch, command/storage/RocksLog.java:87,195).  Cache maintenance
        is bulked per same-group contiguous RUN; non-contiguous batches
        remain correct (runs just get shorter)."""
        self.wal.append_batch(groups, idxs, terms, payloads)
        n = len(groups)
        start = 0
        while start < n:
            g = int(groups[start])
            i0 = int(idxs[start])
            end = start + 1
            # extend while same group AND contiguous indices
            while (end < n and groups[end] == g
                   and int(idxs[end]) == i0 + (end - start)):
                end += 1
            self._add_run(g, PayloadRun.from_payloads(
                i0, list(payloads[start:end])))
            hi = i0 + (end - start) - 1
            if hi > self._durable_tail.get(g, 0):
                self._durable_tail[g] = hi
            start = end

    def append_spans(self, spans: Sequence[tuple]) -> None:
        """The arena fast path (VERDICT r4 #2): stage a whole tick's
        appends as contiguous spans ``(g, start, piece, lens_u32,
        terms)`` — pieces are buffer slices whose entries sit
        back-to-back; ``terms`` is an int64 vector (adoption) or a plain
        int (own submissions, all at the leader's term).  The global
        arena's metadata is assembled with vector ops over the span
        HEADERS (np.repeat / one global cumsum) — per-span Python is
        three tight loop bodies, per-ENTRY Python is zero — and ONE
        native call writes everything; the cache records each span as a
        run sharing slices of the same global offset vector."""
        n_spans = len(spans)
        counts = np.empty(n_spans, np.int64)
        gs_v = np.empty(n_spans, np.int64)
        starts_v = np.empty(n_spans, np.int64)
        j = 0
        for sp in spans:
            gs_v[j] = sp[0]
            starts_v[j] = sp[1]
            counts[j] = len(sp[3])
            j += 1
        ends = np.cumsum(counts)
        total = int(ends[-1])
        span_pos = ends - counts           # flat start offset of each span
        g_all = np.repeat(gs_v, counts).astype(np.uint32)
        i_all = (np.arange(total, dtype=np.int64)
                 + np.repeat(starts_v - span_pos, counts)).astype(np.uint64)
        lens_all = np.empty(total, np.uint32)
        t_all = np.empty(total, np.int64)
        pos = 0
        for sp in spans:
            cnt = len(sp[3])
            sl = slice(pos, pos + cnt)
            lens_all[sl] = sp[3]
            t_all[sl] = sp[4]              # scalar or vector, both C-speed
            pos += cnt
        offs_all = np.zeros(total, np.uint64)
        if total > 1:
            np.cumsum(lens_all[:-1].astype(np.uint64), out=offs_all[1:])
        pos = 0
        dt = self._durable_tail
        for sp in spans:
            g, start = sp[0], sp[1]
            cnt = len(sp[3])
            offs = offs_all[pos:pos + cnt] - offs_all[pos]
            self._add_run(g, PayloadRun(start, sp[2], offs, sp[3]))
            pos += cnt
            tail_new = start + cnt - 1
            if tail_new > dt.get(g, 0):
                dt[g] = tail_new
        self.wal.append_arena(
            g_all, i_all, t_all,
            b"".join(sp[2] for sp in spans), offs_all, lens_all)

    # -- native host tier ----------------------------------------------------

    @property
    def can_stage_native(self) -> bool:
        """True when the WAL backend exposes the native host tier (every
        shard is a native engine and the .so exports wal_stage_and_sync)."""
        return bool(getattr(self.wal, "can_stage_native", False))

    def stage_and_sync(self, spans: Sequence[tuple],
                       trunc_gs, trunc_tails,
                       floor_gs, floor_idxs, floor_terms, *,
                       workers: int = 1, sync: bool = True):
        """Native-tier variant of the tick's span/truncate/floor staging +
        fsync: ONE ctypes call stages every shard with real OS threads.

        Spans use :meth:`append_spans`'s currency; truncations are
        ``truncate_to`` rows the CALLER pre-filtered with the same
        durable-tail guard (the record emitted is ``truncate(g, tail+1)``);
        floors are ``set_floor`` rows (the wal-floor guard is re-checked
        here).  Python-side effects — membership sidecar, payload-run
        cache, durable-tail map — are applied in the exact order of the
        serial path; only the WAL record staging and the fsync barrier
        cross into C.  Entry payloads are handed over as raw per-span base
        pointers (``spans`` must stay alive for the duration of the call).
        Returns ``(stage_s, fsync_s)``."""
        n_spans = len(spans)
        counts = np.empty(n_spans, np.int64)
        gs_v = np.empty(n_spans, np.int64)
        starts_v = np.empty(n_spans, np.int64)
        base_ptrs = np.empty(n_spans, np.uint64)
        j = 0
        for sp in spans:
            gs_v[j] = sp[0]
            starts_v[j] = sp[1]
            counts[j] = len(sp[3])
            base_ptrs[j] = np.frombuffer(sp[2], np.uint8).ctypes.data
            j += 1
        total = int(counts.sum()) if n_spans else 0
        ends = np.cumsum(counts)
        span_pos = ends - counts
        g_all = np.repeat(gs_v, counts).astype(np.uint32)
        i_all = (np.arange(total, dtype=np.int64)
                 + np.repeat(starts_v - span_pos, counts)).astype(np.uint64)
        lens_all = np.empty(total, np.uint32)
        t_all = np.empty(total, np.int64)
        pos = 0
        for sp in spans:
            cnt = len(sp[3])
            sl = slice(pos, pos + cnt)
            lens_all[sl] = sp[3]
            t_all[sl] = sp[4]
            pos += cnt
        offs_all = np.zeros(total, np.uint64)
        if total > 1:
            np.cumsum(lens_all[:-1].astype(np.uint64), out=offs_all[1:])
        # Per-entry payload ADDRESSES: span base pointer + offset within
        # the span — the native side reads the arena views in place, no
        # blob join, no copy.
        ptr_all = (np.repeat(base_ptrs, counts)
                   + (offs_all - np.repeat(offs_all[span_pos]
                                           if n_spans else offs_all,
                                           counts)))
        # Python-side bookkeeping in serial-path order: runs first
        # (append), then truncations, then floors.
        pos = 0
        dt = self._durable_tail
        for sp in spans:
            g, start = sp[0], sp[1]
            cnt = len(sp[3])
            offs = offs_all[pos:pos + cnt] - offs_all[pos]
            self._add_run(g, PayloadRun(start, sp[2], offs, sp[3]))
            pos += cnt
            tail_new = start + cnt - 1
            if tail_new > dt.get(g, 0):
                dt[g] = tail_new
        t_from = np.asarray(trunc_tails, np.uint64) + np.uint64(1)
        for g, tail in zip(np.asarray(trunc_gs).tolist(),
                           np.asarray(trunc_tails).tolist()):
            g, tail = int(g), int(tail)
            self.conf.truncate(g, tail)
            dt[g] = tail
            self._trim_cache_tail(g, tail)
        f_keep = []
        for k, (g, index) in enumerate(zip(np.asarray(floor_gs).tolist(),
                                           np.asarray(floor_idxs).tolist())):
            g, index = int(g), int(index)
            self.conf.set_floor(g, index, 0)
            if index <= self.wal.floor(g):
                continue   # same guard as set_floor: no record staged
            f_keep.append(k)
            self._trim_cache_floor(g, index)
            dt[g] = max(dt.get(g, 0), index)
        f_keep = np.asarray(f_keep, np.int64)
        f_gs = np.asarray(floor_gs, np.uint32)[f_keep]
        f_idx = np.asarray(floor_idxs, np.uint64)[f_keep]
        f_term = np.asarray(floor_terms, np.int64)[f_keep]
        return self.wal.stage_and_sync(
            g_all, i_all, t_all, ptr_all, lens_all,
            np.asarray(trunc_gs, np.uint32), t_from,
            f_gs, f_idx, f_term, workers=workers, sync=sync)

    def pack_ae_blob(self, cols, starts, ns, *, workers: int = 1):
        """Native AppendEntries blob pack (codec payload_blob_fn hook):
        ``(ok_mask, blob)`` or None when the native tier is unavailable
        (codec falls back to its Python per-column loop)."""
        pack = getattr(self.wal, "pack_ae", None)
        if pack is None:
            return None
        return pack(cols, starts, ns, workers=workers)

    def put_conf(self, g: int, idx: int, word: int) -> None:
        """Record a config entry (§6 membership plane) so recovery can
        rebuild the conf ring; durable at the next sync()."""
        self.conf.put(g, idx, word)

    def conf_overwrite(self, g: int, start: int) -> None:
        """Mirror an entry overwrite at ``start`` into the membership
        sidecar: recorded config entries at >= start die (the WAL's
        replay drops that suffix, and a conflicting adoption may replace
        a config entry with an ordinary one)."""
        self.conf.truncate(g, start - 1)

    def conf_export(self) -> dict:
        """{g: (floor_word, {idx: word})} — recovery input."""
        return self.conf.export()

    def _trim_cache_tail(self, g: int, tail: int) -> None:
        """Drop cached entries above ``tail`` (suffix truncation)."""
        ent = self._cache.get(g)
        if ent:
            starts, runs = ent
            while starts and starts[-1] > tail:
                starts.pop()
                runs.pop()
            if runs and runs[-1].end > tail:
                r = runs[-1]
                keep = tail - r.start + 1
                runs[-1] = self._maybe_compact(
                    PayloadRun(r.start, r.buf, r.offs[:keep],
                               r.lens[:keep]))

    def _trim_cache_floor(self, g: int, index: int) -> None:
        """Drop cached entries at/under ``index`` (compaction floor)."""
        ent = self._cache.get(g)
        if ent:
            starts, runs = ent
            drop = 0
            while drop < len(runs) and runs[drop].end <= index:
                drop += 1
            if drop:
                del starts[:drop]
                del runs[:drop]
            if runs and runs[0].start <= index:
                r = runs[0]
                k = index + 1 - r.start
                runs[0] = self._maybe_compact(
                    PayloadRun(index + 1, r.buf, r.offs[k:], r.lens[k:]))
                starts[0] = index + 1

    def truncate_to(self, g: int, tail: int) -> None:
        """Ensure the durable suffix beyond `tail` dies (conflict/snapshot
        discard).  No-op if the durable tail is already <= tail."""
        self.conf.truncate(g, tail)
        if self._durable_tail.get(g, self.wal.tail(g)) > tail:
            self.wal.truncate(g, tail + 1)
            self._durable_tail[g] = tail
            self._trim_cache_tail(g, tail)

    def put_stable(self, g: int, term: int, ballot: int) -> None:
        if self._stable.get(g) == (term, ballot):
            return
        self.wal.append_stable(g, term, ballot)
        self._stable[g] = (term, ballot)

    def put_stable_batch(self, groups, terms, ballots) -> None:
        """Stage many (term, ballot) records in one store call (the
        runtime's change-detected sweep hands over every moved lane at
        once; steady state is an empty call)."""
        st = self._stable
        append = self.wal.append_stable
        for g, t, b in zip(groups, terms, ballots):
            g, t, b = int(g), int(t), int(b)
            if st.get(g) == (t, b):
                continue
            append(g, t, b)
            st[g] = (t, b)

    def set_floor(self, g: int, index: int, term: int,
                  conf_word: int = 0) -> None:
        """Raise the compaction floor (snapshot milestone).  ``conf_word``
        (nonzero) additionally pins the config AS OF the milestone — the
        snapshot-install path passes the offer's config; ordinary
        compaction folds the group's own recorded entries instead."""
        self.conf.set_floor(g, index, conf_word)
        if index <= self.wal.floor(g):
            return
        self.wal.milestone(g, index, term)
        self._trim_cache_floor(g, index)
        self._durable_tail[g] = max(self._durable_tail.get(g, 0), index)

    def reset_group(self, g: int) -> None:
        """Forget a destroyed group's entire durable state (entries, stable
        record, milestone) so a future group can reuse the lane from
        scratch (the reference deletes the group's RocksDB dir,
        command/storage/RocksStateLoader.java:48-59)."""
        self.wal.reset(g)
        self.conf.reset(g)
        self._cache.pop(g, None)
        self._stable.pop(g, None)
        self._durable_tail.pop(g, None)

    def sync(self) -> None:
        """The durability barrier: one fsync covering all staged writes
        (the membership sidecar flushes inside the same barrier)."""
        self.conf.flush()
        self.wal.sync()

    def sync_stripes(self, stripes) -> None:
        """Fsync only the given WAL stripes (striped host tier: each
        worker barriers exactly the shards it staged).  The membership
        sidecar is NOT flushed here — it is a single global file, so the
        orchestrator flushes it once per tick before any ack leaves
        (conf-bearing ticks take the serial host path entirely)."""
        ss = getattr(self.wal, "sync_shards", None)
        if ss is not None:
            ss(stripes)
        else:
            self.wal.sync()

    @property
    def n_stripes(self) -> int:
        """How many independently fsync-able WAL stripes back this store
        (1 for an unsharded WAL) — the striped host tier's worker-count
        ceiling."""
        return int(getattr(self.wal, "n_shards", 1))

    def conf_flush(self) -> None:
        """Flush the membership sidecar alone (striped host tier: the
        orchestrator's share of the durability barrier)."""
        self.conf.flush()

    # -- injectable fault table (testkit/faultfs) ----------------------
    def set_fault(self, op: str, after: int = 0, value: int = 0,
                  shard: int = 0) -> None:
        """Arm an injected I/O fault on one WAL stripe (unsharded WALs
        have exactly stripe 0) — see log/wal.py _FAULT_OPS."""
        if hasattr(self.wal, "n_shards"):
            self.wal.set_fault(op, after, value, shard=shard)
        else:
            assert shard == 0
            self.wal.set_fault(op, after, value)

    def clear_faults(self) -> None:
        self.wal.clear_faults()

    def poisoned_stripes(self):
        """Stripe ids whose engines latched a fail-stop fault."""
        ps = getattr(self.wal, "poisoned_shards", None)
        if ps is not None:
            return ps()
        return [0] if getattr(self.wal, "poisoned", False) else []

    def checkpoint(self) -> None:
        """Rewrite live state, dropping dead segments (synchronous GC —
        test/offline use; the runtime uses the three-phase path below)."""
        self.wal.checkpoint()

    def should_gc(self, ratio: float = 4.0, min_bytes: int = 8 << 20) -> bool:
        """GC trigger: disk footprint exceeds ``min_bytes`` AND ``ratio`` x
        the live set (the reference reclaims continuously via RocksDB
        deleteRange, RocksLog.java:228-242; a segmented WAL reclaims by
        rewriting the live set, so the trigger ratio bounds disk at
        ~ratio x live)."""
        total = self.wal.total_bytes()
        if total < min_bytes:
            return False
        return total > ratio * max(self.wal.live_bytes(), 1)

    def maybe_gc(self, ratio: float = 4.0, min_bytes: int = 8 << 20) -> bool:
        """Synchronous trigger-then-checkpoint (tests/offline tools)."""
        if self.should_gc(ratio, min_bytes):
            self.wal.checkpoint()
            return True
        return False

    # Three-phase GC: begin/finish on the owning (tick) thread — both
    # bounded, memory-only plus a rename/unlink — with the live-set rewrite
    # on a worker thread (VERDICT r2 #6: the synchronous checkpoint was a
    # multi-second tick stall at scale).
    def gc_begin(self) -> int:
        return self.wal.gc_begin()

    def gc_rewrite(self) -> int:
        return self.wal.gc_rewrite()

    def gc_finish(self) -> int:
        return self.wal.gc_finish()

    def gc_abort(self) -> None:
        self.wal.gc_abort()

    def segment_count(self) -> int:
        return int(self.wal.segment_count())

    # -- reads ---------------------------------------------------------------

    def payload(self, g: int, idx: int) -> Optional[bytes]:
        r = self._run_at(g, idx)
        if r is not None:
            return r.entry(idx - r.start)
        p = self.wal.entry_payload(g, idx)
        if p is not None:
            # Cache the miss: a laggard catch-up re-reads the same window
            # every tick until the follower advances — one WAL read per
            # entry, not one per entry per tick.
            self._backfill(g, idx, p)
        return p

    def payload_batch(self, g: int, start: int, n: int) -> List[bytes]:
        return [b"" if p is None else p
                for p in self.payloads_window(g, start, n)]

    def payloads_window(self, g: int, start: int, n: int
                        ) -> List[Optional[bytes]]:
        """Payloads for [start, start+n) with None where absent — run
        lookups amortized over the window (the replication pack and apply
        paths call this once per window instead of once per entry).  WAL
        reads only run for the (rare) cache misses."""
        out: List[Optional[bytes]] = [None] * n
        idx = start
        while idx < start + n:
            r = self._run_at(g, idx)
            if r is None:
                p = self.wal.entry_payload(g, idx)
                if p is not None:
                    self._backfill(g, idx, p)
                out[idx - start] = p
                idx += 1
                continue
            k = idx - r.start
            m = min(r.end, start + n - 1) - idx + 1
            mv = memoryview(r.buf)
            offs, lens = r.offs, r.lens
            for j in range(m):
                a = int(offs[k + j])
                out[idx - start + j] = bytes(mv[a:a + int(lens[k + j])])
            idx += m
        return out

    def payload_runs(self, g: int, start: int, n: int):
        """Zero-copy window read: ``(pieces, lens)`` where pieces are
        contiguous buffer slices covering entries [start, start+n) in
        order and lens is the uint32 length vector — the wire pack path
        consumes this with no per-entry work.  Cache misses fall back to
        WAL reads (as one-entry pieces); returns None iff an entry is
        truly absent (caller drops the column, same loss semantics as
        ever)."""
        pieces: List = []
        len_parts: List[np.ndarray] = []
        idx = start
        while idx < start + n:
            r = self._run_at(g, idx)
            if r is None:
                p = self.wal.entry_payload(g, idx)
                if p is None:
                    return None
                self._backfill(g, idx, p)
                pieces.append(p)
                len_parts.append(np.asarray([len(p)], np.uint32))
                idx += 1
                continue
            k = idx - r.start
            m = min(r.end, start + n - 1) - idx + 1
            pieces.append(r.piece(k, m))
            len_parts.append(r.lens[k:k + m])
            idx += m
        lens = (len_parts[0] if len(len_parts) == 1
                else np.concatenate(len_parts))
        return pieces, lens

    def entry_term(self, g: int, idx: int) -> int:
        return int(self.wal.entry_term(g, idx))

    def export_state(self, G: int, L: int):
        """Bulk crash-recovery export (LogStoreSPI contract): one engine
        call fills every per-group array + the term ring."""
        return self.wal.export_state(G, L)

    def stable(self, g: int):
        return self.wal.stable(g)

    def tail(self, g: int) -> int:
        return int(self.wal.tail(g))

    def floor(self, g: int) -> int:
        return int(self.wal.floor(g))

    def floor_term(self, g: int) -> int:
        return int(self.wal.floor_term(g))

    def close(self) -> None:
        self.wal.close()


def restore_raft_state(cfg, node_id: int, store: LogStore, seed: int = 0):
    """Rebuild the device RaftState from the durable store after a crash.

    Follows the reference's restore order (RaftContext.initialize,
    context/RaftContext.java:91-113): stable (term, ballot) first, then the
    log window above the milestone floor.  commitIndex is NOT persisted —
    it is rediscovered from leaderCommit traffic, exactly like the
    reference's volatile commitIndex (RocksLog.java:50, 92-109) — except
    entries at/below the floor, which are committed by definition.
    """
    import jax.numpy as jnp

    from ..core.types import NIL, boot_conf_word, init_state

    state = init_state(cfg, node_id, seed=seed)
    G, L = cfg.n_groups, cfg.log_slots
    # One bulk export call instead of an O(G*L) Python walk (VERDICT r1
    # #8); the native engine fills every per-group array + the term ring
    # in C (wal_export_state).  Works against any LogStoreSPI store.
    ex = store.export_state(G, L)
    term = np.where(ex["has_stable"] > 0, ex["stable_term"], 0) \
        .astype(np.int32)
    voted = np.where(ex["has_stable"] > 0, ex["ballot"], NIL) \
        .astype(np.int32)
    base = ex["floor"].astype(np.int32)
    base_term = ex["floor_term"].astype(np.int32)
    last = np.maximum(ex["tail"], ex["floor"]).astype(np.int32)
    commit = ex["floor"].astype(np.int32)
    ring = ex["ring"]
    # Contiguity check without a per-entry walk: live_count must equal the
    # window size.  A gap above the floor (inconsistent WAL) falls back to
    # the slow scan for just that group.
    expected = (last.astype(np.int64) - base.astype(np.int64))
    suspect = np.nonzero(ex["live_count"] != expected)[0]
    for g in suspect.tolist():
        ring[g] = 0
        last[g] = base[g]
        for idx in range(int(base[g]) + 1, int(ex["tail"][g]) + 1):
            t = store.entry_term(g, idx)
            if t < 0:
                break
            ring[g, idx % L] = t
            last[g] = idx
        # Repair the durable store to the adopted tail: entries above the
        # gap are unreachable to the engine, and leaving them in the WAL
        # would let a later contiguous re-append resurrect stale
        # terms/payloads on the NEXT recovery (the runtime's truncation
        # change-detection assumes durable tail == device tail at boot).
        if int(ex["tail"][g]) > int(last[g]):
            store.truncate_to(g, int(last[g]))
    if len(suspect):
        store.sync()
    # Membership restore (§6 durable config): rebuild the conf ring from
    # the WAL's membership sidecar — live config entries back into their
    # ring slots, the floor config into base_conf.  Entries the WAL
    # truncated after their last sidecar write are dropped by the window
    # bound; a store without the sidecar (LogStoreSPI products) boots the
    # full-voter config, exactly like a fresh lane.
    cring = np.zeros((G, L), np.int32)
    bconf = np.full(G, boot_conf_word(cfg), np.int32)
    # The derived-config cache lanes (RaftState.conf_idx/conf_word) must
    # match latest_conf(log, last) at boot — rebuilt here alongside the
    # ring.
    conf_idx = np.zeros(G, np.int32)
    conf_word = bconf.copy()
    conf_export = getattr(store, "conf_export", None)
    if conf_export is not None:
        for g, (floor_word, entries) in conf_export().items():
            if g >= G:
                continue
            if floor_word:
                bconf[g] = floor_word
            for idx, word in sorted(entries.items()):
                if base[g] < idx <= last[g]:
                    cring[g, idx % L] = word
                    conf_idx[g], conf_word[g] = idx, word
                elif idx <= base[g]:
                    bconf[g] = word
            if conf_idx[g] == 0:
                conf_word[g] = bconf[g]
    return state.replace(
        conf_idx=jnp.asarray(conf_idx), conf_word=jnp.asarray(conf_word),
        term=jnp.asarray(term), voted_for=jnp.asarray(voted),
        commit=jnp.asarray(commit),
        log=state.log.replace(
            term=jnp.asarray(ring), conf=jnp.asarray(cring),
            base=jnp.asarray(base),
            base_term=jnp.asarray(base_term),
            base_conf=jnp.asarray(bconf), last=jnp.asarray(last)),
        next_idx=jnp.asarray(np.broadcast_to(last[:, None] + 1,
                                             (G, cfg.n_peers)).copy()),
        send_next=jnp.asarray(np.broadcast_to(last[:, None] + 1,
                                              (G, cfg.n_peers)).copy()),
    )
