"""LogStore: the host-side log facade the node runtime drives each tick.

Responsibilities (mapping to the reference's storage contracts):

* durable entry payloads + terms  — RaftLog.newEntry/append
  (command/RaftLog.java:11-134, command/storage/RocksLog.java:82-196)
* suffix truncation on conflict   — RaftLog.truncate (RocksLog.java:219-225)
* compaction floor ("epoch")      — RaftLog.flush (RocksLog.java:228-242)
* durable (term, ballot)          — StableLock (support/StableLock.java:69-80)
* milestone (snapshot index/term) — StableLock milestone (82-91)
* crash recovery → device state   — RaftContext.initialize restore path
  (context/RaftContext.java:91-113)

The tick protocol (enforced by the node runtime): all writes implied by a
device step are staged, then ONE :meth:`sync` makes them durable *before*
any RPC produced by that step leaves the node — the reference's
persist-before-reply rule (context/member/RaftMember.java:25,
RocksLog.flushWal after append) amortized over every group at once.

A bounded in-memory payload cache keeps the replication hot path
(leader batch fetch) off the WAL read path; entries below the compaction
floor are pruned as the floor advances.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from .wal import WalStore


class LogStore:
    def __init__(self, path: str, segment_bytes: int = 64 << 20, *,
                 force_python: bool = False):
        self.wal = WalStore(path, segment_bytes, force_python=force_python)
        # group -> {index -> payload bytes}; hot mirror of the live window.
        # Keyed per group so floor/truncate/reset maintenance scans only
        # that group's window, never the whole node's cache (a flat dict
        # made set_floor O(total cache) per group — O(G^2) per tick under
        # dense load).
        self._cache: Dict[int, Dict[int, bytes]] = {}
        # last durable (term, ballot) per group, to skip no-op stable writes
        self._stable: Dict[int, tuple] = {}
        self._durable_tail: Dict[int, int] = {}

    # -- staging writes (durable after sync()) ------------------------------

    def append_entries(self, g: int, start: int, terms: Sequence[int],
                       payloads: Sequence[bytes]) -> None:
        """Write entries [start, start+len) (overwrite semantics)."""
        gc = self._cache.setdefault(g, {})
        for k, (t, p) in enumerate(zip(terms, payloads)):
            idx = start + k
            self.wal.append_entry(g, idx, int(t), p)
            gc[idx] = p
        self._durable_tail[g] = max(self._durable_tail.get(g, 0),
                                    start + len(terms) - 1)

    def append_batch(self, groups: Sequence[int], idxs: Sequence[int],
                     terms: Sequence[int], payloads: Sequence[bytes]) -> None:
        """Stage a whole tick's appends across all groups in one engine
        call (native: one ctypes crossing; the batching analog of the
        reference's group-commit WAL flush, RocksLog flushWal after a
        batch, command/storage/RocksLog.java:87,195).

        Cache maintenance is bulked per same-group RUN (the runtime stages
        each group's entries contiguously): one dict resolution + one
        C-speed ``update`` per run instead of per-entry Python — the
        per-entry loop here was ~15% of the durable tick under dense load.
        Non-contiguous batches remain correct (runs just get shorter)."""
        self.wal.append_batch(groups, idxs, terms, payloads)
        n = len(groups)
        start = 0
        while start < n:
            g = int(groups[start])
            end = start + 1
            while end < n and groups[end] == g:
                end += 1
            run = [int(i) for i in idxs[start:end]]
            self._cache.setdefault(g, {}).update(
                zip(run, payloads[start:end]))
            hi = max(run)
            if hi > self._durable_tail.get(g, 0):
                self._durable_tail[g] = hi
            start = end

    def truncate_to(self, g: int, tail: int) -> None:
        """Ensure the durable suffix beyond `tail` dies (conflict/snapshot
        discard).  No-op if the durable tail is already <= tail."""
        if self._durable_tail.get(g, self.wal.tail(g)) > tail:
            self.wal.truncate(g, tail + 1)
            self._durable_tail[g] = tail
            gc = self._cache.get(g)
            if gc:
                for k in [k for k in gc if k > tail]:
                    del gc[k]

    def put_stable(self, g: int, term: int, ballot: int) -> None:
        if self._stable.get(g) == (term, ballot):
            return
        self.wal.append_stable(g, term, ballot)
        self._stable[g] = (term, ballot)

    def set_floor(self, g: int, index: int, term: int) -> None:
        """Raise the compaction floor (snapshot milestone)."""
        if index <= self.wal.floor(g):
            return
        self.wal.milestone(g, index, term)
        gc = self._cache.get(g)
        if gc:
            for k in [k for k in gc if k <= index]:
                del gc[k]
        self._durable_tail[g] = max(self._durable_tail.get(g, 0), index)

    def reset_group(self, g: int) -> None:
        """Forget a destroyed group's entire durable state (entries, stable
        record, milestone) so a future group can reuse the lane from
        scratch (the reference deletes the group's RocksDB dir,
        command/storage/RocksStateLoader.java:48-59)."""
        self.wal.reset(g)
        self._cache.pop(g, None)
        self._stable.pop(g, None)
        self._durable_tail.pop(g, None)

    def sync(self) -> None:
        """The durability barrier: one fsync covering all staged writes."""
        self.wal.sync()

    def checkpoint(self) -> None:
        """Rewrite live state, dropping dead segments (synchronous GC —
        test/offline use; the runtime uses the three-phase path below)."""
        self.wal.checkpoint()

    def should_gc(self, ratio: float = 4.0, min_bytes: int = 8 << 20) -> bool:
        """GC trigger: disk footprint exceeds ``min_bytes`` AND ``ratio`` x
        the live set (the reference reclaims continuously via RocksDB
        deleteRange, RocksLog.java:228-242; a segmented WAL reclaims by
        rewriting the live set, so the trigger ratio bounds disk at
        ~ratio x live)."""
        total = self.wal.total_bytes()
        if total < min_bytes:
            return False
        return total > ratio * max(self.wal.live_bytes(), 1)

    def maybe_gc(self, ratio: float = 4.0, min_bytes: int = 8 << 20) -> bool:
        """Synchronous trigger-then-checkpoint (tests/offline tools)."""
        if self.should_gc(ratio, min_bytes):
            self.wal.checkpoint()
            return True
        return False

    # Three-phase GC: begin/finish on the owning (tick) thread — both
    # bounded, memory-only plus a rename/unlink — with the live-set rewrite
    # on a worker thread (VERDICT r2 #6: the synchronous checkpoint was a
    # multi-second tick stall at scale).
    def gc_begin(self) -> int:
        return self.wal.gc_begin()

    def gc_rewrite(self) -> int:
        return self.wal.gc_rewrite()

    def gc_finish(self) -> int:
        return self.wal.gc_finish()

    def gc_abort(self) -> None:
        self.wal.gc_abort()

    def segment_count(self) -> int:
        return int(self.wal.segment_count())

    # -- reads ---------------------------------------------------------------

    def payload(self, g: int, idx: int) -> Optional[bytes]:
        gc = self._cache.setdefault(g, {})
        p = gc.get(idx)
        if p is not None:
            return p
        p = self.wal.entry_payload(g, idx)
        if p is not None:
            gc[idx] = p
        return p

    def payload_batch(self, g: int, start: int, n: int) -> List[bytes]:
        return [b"" if p is None else p
                for p in self.payloads_window(g, start, n)]

    def payloads_window(self, g: int, start: int, n: int
                        ) -> List[Optional[bytes]]:
        """Payloads for [start, start+n) with None where absent — one
        cache-dict resolution for the whole window (the replication pack
        path calls this once per AE column instead of once per entry).
        The all-cached common case is a single comprehension; WAL reads
        only run for the (rare) misses."""
        gc = self._cache.setdefault(g, {})
        get = gc.get
        out: List[Optional[bytes]] = [get(i) for i in range(start, start + n)]
        if None in out:
            for k, p in enumerate(out):
                if p is None:
                    p = self.wal.entry_payload(g, start + k)
                    if p is not None:
                        gc[start + k] = p
                        out[k] = p
        return out

    def entry_term(self, g: int, idx: int) -> int:
        return int(self.wal.entry_term(g, idx))

    def export_state(self, G: int, L: int):
        """Bulk crash-recovery export (LogStoreSPI contract): one engine
        call fills every per-group array + the term ring."""
        return self.wal.export_state(G, L)

    def stable(self, g: int):
        return self.wal.stable(g)

    def tail(self, g: int) -> int:
        return int(self.wal.tail(g))

    def floor(self, g: int) -> int:
        return int(self.wal.floor(g))

    def floor_term(self, g: int) -> int:
        return int(self.wal.floor_term(g))

    def close(self) -> None:
        self.wal.close()


def restore_raft_state(cfg, node_id: int, store: LogStore, seed: int = 0):
    """Rebuild the device RaftState from the durable store after a crash.

    Follows the reference's restore order (RaftContext.initialize,
    context/RaftContext.java:91-113): stable (term, ballot) first, then the
    log window above the milestone floor.  commitIndex is NOT persisted —
    it is rediscovered from leaderCommit traffic, exactly like the
    reference's volatile commitIndex (RocksLog.java:50, 92-109) — except
    entries at/below the floor, which are committed by definition.
    """
    import jax.numpy as jnp

    from ..core.types import NIL, init_state

    state = init_state(cfg, node_id, seed=seed)
    G, L = cfg.n_groups, cfg.log_slots
    # One bulk export call instead of an O(G*L) Python walk (VERDICT r1
    # #8); the native engine fills every per-group array + the term ring
    # in C (wal_export_state).  Works against any LogStoreSPI store.
    ex = store.export_state(G, L)
    term = np.where(ex["has_stable"] > 0, ex["stable_term"], 0) \
        .astype(np.int32)
    voted = np.where(ex["has_stable"] > 0, ex["ballot"], NIL) \
        .astype(np.int32)
    base = ex["floor"].astype(np.int32)
    base_term = ex["floor_term"].astype(np.int32)
    last = np.maximum(ex["tail"], ex["floor"]).astype(np.int32)
    commit = ex["floor"].astype(np.int32)
    ring = ex["ring"]
    # Contiguity check without a per-entry walk: live_count must equal the
    # window size.  A gap above the floor (inconsistent WAL) falls back to
    # the slow scan for just that group.
    expected = (last.astype(np.int64) - base.astype(np.int64))
    suspect = np.nonzero(ex["live_count"] != expected)[0]
    for g in suspect.tolist():
        ring[g] = 0
        last[g] = base[g]
        for idx in range(int(base[g]) + 1, int(ex["tail"][g]) + 1):
            t = store.entry_term(g, idx)
            if t < 0:
                break
            ring[g, idx % L] = t
            last[g] = idx
        # Repair the durable store to the adopted tail: entries above the
        # gap are unreachable to the engine, and leaving them in the WAL
        # would let a later contiguous re-append resurrect stale
        # terms/payloads on the NEXT recovery (the runtime's truncation
        # change-detection assumes durable tail == device tail at boot).
        if int(ex["tail"][g]) > int(last[g]):
            store.truncate_to(g, int(last[g]))
    if len(suspect):
        store.sync()
    return state.replace(
        term=jnp.asarray(term), voted_for=jnp.asarray(voted),
        commit=jnp.asarray(commit),
        log=state.log.replace(
            term=jnp.asarray(ring), base=jnp.asarray(base),
            base_term=jnp.asarray(base_term), last=jnp.asarray(last)),
        next_idx=jnp.asarray(np.broadcast_to(last[:, None] + 1,
                                             (G, cfg.n_peers)).copy()),
        send_next=jnp.asarray(np.broadcast_to(last[:, None] + 1,
                                              (G, cfg.n_peers)).copy()),
    )
