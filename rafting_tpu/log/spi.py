"""LogStoreSPI: the pluggable durable-log contract (reference StateLoader
SPI, command/spi/StateLoader.java:8-12, consumed through RaftFactory.loadState,
support/RaftFactory.java:18).

A log store owns every durable bit of a node's consensus state: entry
payloads + terms, the (term, ballot) stable record, the compaction-floor
milestone, and crash recovery.  The node runtime drives it with the tick
protocol (stage writes, then ONE :meth:`sync` barrier before any RPC from
that tick leaves — the reference's persist-before-reply rule,
context/member/RaftMember.java:25).

Implementations in-tree: :class:`rafting_tpu.log.store.LogStore` (segmented
group-commit WAL, native C++ engine with a byte-compatible Python fallback)
and :class:`rafting_tpu.log.memstore.MemoryLogStore` (non-durable, for
tests/ephemeral groups).  Swap via ``RaftFactory.log_store``.

Optional arena fast paths (the node runtime probes with ``getattr`` and
falls back to the protocol methods below when absent, so third-party
stores keep working unchanged):

* ``append_spans(spans)`` — stage a whole tick's appends as contiguous
  spans ``(group, start_index, buffer, lens_u32, terms)`` whose payload
  bytes sit back-to-back in ``buffer`` (terms: int64 vector or a plain
  int).  LogStore crosses into its native engine ONCE per tick with
  pointer vectors; a store without it receives per-entry materialized
  lists through :meth:`append_batch`.
* ``payload_runs(g, start, n) -> (pieces, lens_u32) | None`` — zero-copy
  window read consumed by the wire pack path and arena-aware machines
  (``RaftMachine.apply_run``); ``None`` iff an entry is absent.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Protocol, Sequence, Tuple, runtime_checkable

import numpy as np


@runtime_checkable
class LogStoreSPI(Protocol):
    # -- staging writes (durable after sync()) ------------------------------
    def append_entries(self, g: int, start: int, terms: Sequence[int],
                       payloads: Sequence[bytes]) -> None: ...

    def append_batch(self, groups: Sequence[int], idxs: Sequence[int],
                     terms: Sequence[int],
                     payloads: Sequence[bytes]) -> None: ...

    def truncate_to(self, g: int, tail: int) -> None: ...

    def put_stable(self, g: int, term: int, ballot: int) -> None: ...

    def set_floor(self, g: int, index: int, term: int) -> None: ...

    def reset_group(self, g: int) -> None: ...

    def sync(self) -> None: ...

    # -- space reclamation (no-ops for stores without a disk tier) ----------
    def should_gc(self, ratio: float = 4.0,
                  min_bytes: int = 8 << 20) -> bool: ...

    def gc_begin(self) -> int: ...       # < 0: nothing to do / unsupported

    def gc_rewrite(self) -> int: ...     # worker-thread phase

    def gc_finish(self) -> int: ...      # 0 = swapped in

    def gc_abort(self) -> None: ...

    def segment_count(self) -> int: ...

    # -- reads --------------------------------------------------------------
    def payload(self, g: int, idx: int) -> Optional[bytes]: ...

    def payloads_window(self, g: int, start: int, n: int
                        ) -> List[Optional[bytes]]: ...

    def entry_term(self, g: int, idx: int) -> int: ...   # -1 = absent

    def stable(self, g: int) -> Optional[Tuple[int, int]]: ...

    def tail(self, g: int) -> int: ...

    def floor(self, g: int) -> int: ...

    def floor_term(self, g: int) -> int: ...

    # -- crash recovery ------------------------------------------------------
    def export_state(self, G: int, L: int) -> Dict[str, np.ndarray]:
        """Bulk recovery export: arrays ``has_stable, stable_term, ballot,
        floor, floor_term, tail, live_count`` ([G]) and the entry-term
        ``ring`` ([G, L]) — everything ``restore_raft_state`` needs in one
        call (the vectorized analog of RaftContext.initialize's restore,
        context/RaftContext.java:91-113)."""
        ...

    def close(self) -> None: ...
