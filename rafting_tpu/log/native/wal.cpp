// Native WAL storage engine for the TPU Multi-Raft node.
//
// Role: the durability tier under the device-resident log rings — the
// TPU-native replacement for the reference's only native component, the
// embedded RocksDB log store (curioloop/rafting pom.xml:17-21,
// command/storage/RocksLog.java).  Where the reference opens one RocksDB
// per Raft group and fsyncs its WAL per group (RocksLog.java:55-89), this
// engine journals ALL groups of a node into one segmented append-only log
// and amortizes a single fsync over every group's writes in a tick — the
// group-commit discipline the vectorized engine's batch step makes natural.
//
// Record types (all integers little-endian; every record CRC-framed):
//   ENTRY     (group, index, term, payload)  — replicated-log entry
//   STABLE    (group, term, ballot)          — durable (currentTerm, votedFor),
//                                              the reference's StableLock record
//                                              (support/StableLock.java:69-80)
//   TRUNCATE  (group, from)                  — suffix truncation marker
//   MILESTONE (group, index, term)           — snapshot milestone / log floor
//                                              (StableLock.java:82-91 + RocksLog
//                                               epoch column, RocksLog.java:228-242)
//
// Recovery replays segments in order, dropping the tail after the first
// CRC/length mismatch (torn write).  Checkpointing rewrites live state into
// a fresh segment and deletes older ones (the deleteRange analog).
//
// Exposed as a C ABI consumed from Python via ctypes (no pybind11 in the
// toolchain).  Single-threaded by contract: one node runtime thread owns a
// handle.

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <map>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include <dirent.h>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0x52574131;  // "RWA1"
constexpr uint8_t kEntry = 1;
constexpr uint8_t kStable = 2;
constexpr uint8_t kTruncate = 3;
constexpr uint8_t kMilestone = 4;
constexpr uint8_t kReset = 5;  // group destroyed: forget ALL its state

// CRC-32 (IEEE), small table-driven implementation.
uint32_t crc_table[256];
bool crc_init_done = false;
void crc_init() {
  if (crc_init_done) return;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
  crc_init_done = true;
}
uint32_t crc32(const uint8_t* p, size_t n, uint32_t crc = 0) {
  crc_init();
  crc = ~crc;
  for (size_t i = 0; i < n; i++) crc = crc_table[(crc ^ p[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

struct EntryRef {
  int64_t term;
  uint32_t seg;     // segment id holding the payload
  uint64_t off;     // offset of payload bytes within the segment
  uint32_t len;     // payload length
};

struct GroupState {
  int64_t tail = 0;          // last live index (0 = empty)
  int64_t floor = 0;         // compaction floor ("epoch")
  int64_t floor_term = 0;    // term at the floor (snapshot milestone term)
  int64_t stable_term = 0;   // durable currentTerm
  int64_t ballot = -1;       // durable votedFor (-1 = none)
  bool has_stable = false;
  std::map<uint64_t, EntryRef> entries;  // live index -> payload ref

  void drop_suffix(uint64_t from) {
    entries.erase(entries.lower_bound(from), entries.end());
    if (tail >= (int64_t)from) tail = (int64_t)from - 1;
  }
  void drop_prefix(uint64_t upto) {  // drop indices <= upto
    entries.erase(entries.begin(), entries.upper_bound(upto));
  }
};

using GroupMap = std::unordered_map<uint32_t, GroupState>;

// Three-phase GC bookkeeping (begin on the tick thread, rewrite on a worker,
// finish on the tick thread).  `frozen` and `repoint` are immutable to the
// tick thread while `pending`; only the worker writes them.
struct GcRepoint {
  uint32_t g;
  uint64_t idx;
  int64_t term;
  uint64_t off;   // payload offset within the compacted base segment
  uint32_t len;
};
struct GcState {
  bool pending = false;     // gc_begin done, gc_finish not yet
  bool rewritten = false;   // gc_rewrite completed (worker -> tick handoff)
  std::vector<uint32_t> frozen;  // sealed segment ids (ascending)
  std::vector<GcRepoint> repoint;
};

struct Wal {
  std::string dir;
  uint64_t segment_bytes;
  GroupMap groups;
  // open segment
  int fd = -1;
  uint32_t seg_id = 0;
  uint64_t seg_off = 0;
  std::vector<uint8_t> buf;        // pending (unflushed) records
  std::vector<uint32_t> live_segs; // existing segment ids, ascending
  GcState gc;
  std::string err;
  // Injectable fault table (testkit/faultfs): countdowns fire once then
  // disarm (-1).  `poisoned` latches for the handle lifetime — a failed
  // fsync is never retried on the same fd (fail-stop contract, PARITY.md).
  int64_t fault_fsync_after = -1;
  int64_t fault_fsync_errno = EIO;
  int64_t fault_write_after = -1;
  int64_t fault_write_errno = EIO;
  int64_t fault_short_after = -1;
  int64_t fault_short_keep = 0;
  int64_t sync_delay_us = 0;
  bool poisoned = false;
  int last_errno = 0;
  // Cumulative per-handle (= per-stripe) instrumentation, exported
  // zero-copy via wal_stats().  Atomics because wal_pack_ae workers reach
  // arbitrary shards (`gs[c] % n_shards`); the stage/fsync writers are
  // single-threaded per handle by contract, so relaxed ordering suffices.
  std::atomic<uint64_t> stat_stage_ns{0};
  std::atomic<uint64_t> stat_fsync_ns{0};
  std::atomic<uint64_t> stat_pack_ns{0};
  std::atomic<uint64_t> stat_bytes{0};
  std::atomic<uint64_t> stat_stage_calls{0};
  std::atomic<uint64_t> stat_fsync_calls{0};
  std::atomic<uint64_t> stat_pack_calls{0};
};

// Countdown semantics: after=N arms the fault for the (N+1)-th guarded call.
bool fault_fire(int64_t& after) {
  if (after < 0) return false;
  if (after == 0) { after = -1; return true; }
  after--;
  return false;
}

std::string seg_path_in(const std::string& dir, uint32_t id) {
  char name[32];
  std::snprintf(name, sizeof name, "%08u.wal", id);
  return dir + "/" + name;
}

std::string seg_path(const Wal& w, uint32_t id) {
  return seg_path_in(w.dir, id);
}

void put_u32(std::vector<uint8_t>& b, uint32_t v) {
  b.push_back(v & 0xFF); b.push_back((v >> 8) & 0xFF);
  b.push_back((v >> 16) & 0xFF); b.push_back((v >> 24) & 0xFF);
}
void put_u64(std::vector<uint8_t>& b, uint64_t v) {
  for (int i = 0; i < 8; i++) b.push_back((v >> (8 * i)) & 0xFF);
}
uint32_t get_u32(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}
uint64_t get_u64(const uint8_t* p) {
  uint64_t v = 0;
  for (int i = 7; i >= 0; i--) v = (v << 8) | p[i];
  return v;
}

// Record frame: u32 magic | u32 body_len | u32 body_crc | body.
// Body: u8 type | type-specific fields.
void frame(std::vector<uint8_t>& out, const std::vector<uint8_t>& body) {
  put_u32(out, kMagic);
  put_u32(out, (uint32_t)body.size());
  put_u32(out, crc32(body.data(), body.size()));
  out.insert(out.end(), body.begin(), body.end());
}

bool open_segment(Wal& w, uint32_t id, bool fresh) {
  if (w.fd >= 0) { ::close(w.fd); w.fd = -1; }
  std::string p = seg_path(w, id);
  int flags = O_CREAT | O_WRONLY | (fresh ? O_TRUNC : O_APPEND);
  int fd = ::open(p.c_str(), flags, 0644);
  if (fd < 0) { w.err = "open " + p + ": " + std::strerror(errno); return false; }
  w.fd = fd;
  w.seg_id = id;
  struct stat st;
  w.seg_off = (!fresh && ::fstat(fd, &st) == 0) ? (uint64_t)st.st_size : 0;
  if (fresh || std::find(w.live_segs.begin(), w.live_segs.end(), id) ==
                   w.live_segs.end())
    w.live_segs.push_back(id);
  return true;
}

// Apply one record body to an in-memory index.  `seg`/`payload_off` locate
// ENTRY payload bytes for later pread.  Parametrized over the group map so
// the GC worker can replay frozen segments into a PRIVATE map without
// touching the live engine state.
bool apply_body(GroupMap& groups, const uint8_t* b, uint32_t len, uint32_t seg,
                uint64_t payload_off_base) {
  if (len < 1) return false;
  uint8_t type = b[0];
  switch (type) {
    case kEntry: {
      if (len < 1 + 4 + 8 + 8 + 4) return false;
      uint32_t g = get_u32(b + 1);
      uint64_t idx = get_u64(b + 5);
      int64_t term = (int64_t)get_u64(b + 13);
      uint32_t plen = get_u32(b + 21);
      if (len != 1 + 4 + 8 + 8 + 4 + plen) return false;
      auto& gs = groups[g];
      gs.drop_suffix(idx);  // overwrite implies any old suffix at >= idx dies
      gs.entries[idx] = EntryRef{term, seg, payload_off_base + 25, plen};
      gs.tail = (int64_t)idx;
      return true;
    }
    case kStable: {
      if (len != 1 + 4 + 8 + 8) return false;
      uint32_t g = get_u32(b + 1);
      auto& gs = groups[g];
      gs.stable_term = (int64_t)get_u64(b + 5);
      gs.ballot = (int64_t)get_u64(b + 13);
      gs.has_stable = true;
      return true;
    }
    case kTruncate: {
      if (len != 1 + 4 + 8) return false;
      uint32_t g = get_u32(b + 1);
      groups[g].drop_suffix(get_u64(b + 5));
      return true;
    }
    case kMilestone: {
      if (len != 1 + 4 + 8 + 8) return false;
      uint32_t g = get_u32(b + 1);
      uint64_t idx = get_u64(b + 5);
      int64_t term = (int64_t)get_u64(b + 13);
      auto& gs = groups[g];
      // `>=` (not `>`): re-applying the current milestone must be a state
      // no-op INCLUDING its drop_prefix/tail-raise effects — the GC crash
      // window replays stale frozen segments AFTER the compacted base, and
      // a strict guard would let resurrected sub-floor entries survive.
      if ((int64_t)idx >= gs.floor) {
        gs.floor = (int64_t)idx;
        gs.floor_term = term;
        gs.drop_prefix(idx);
        if (gs.tail < gs.floor) gs.tail = gs.floor;
      }
      return true;
    }
    case kReset: {
      if (len != 1 + 4) return false;
      uint32_t g = get_u32(b + 1);
      groups.erase(g);  // a later open of this lane starts from scratch
      return true;
    }
    default:
      return false;
  }
}

// Replay one segment file into `groups`.  `fix_tail` truncates the file
// after a torn/corrupt tail (recovery behavior); the GC worker replays
// fsynced frozen segments read-only and passes false.
bool replay_segment_into(const std::string& dir, GroupMap& groups,
                         uint32_t id, bool fix_tail) {
  std::string p = seg_path_in(dir, id);
  int fd = ::open(p.c_str(), O_RDONLY);
  if (fd < 0) return false;
  struct stat st;
  ::fstat(fd, &st);
  std::vector<uint8_t> data((size_t)st.st_size);
  ssize_t rd = data.empty() ? 0 : ::pread(fd, data.data(), data.size(), 0);
  ::close(fd);
  if (rd < 0) return false;
  size_t n = (size_t)rd, off = 0;
  while (off + 12 <= n) {
    if (get_u32(&data[off]) != kMagic) break;           // torn tail
    uint32_t blen = get_u32(&data[off + 4]);
    uint32_t crc = get_u32(&data[off + 8]);
    if (off + 12 + blen > n) break;                     // torn tail
    if (crc32(&data[off + 12], blen) != crc) break;     // corrupt tail
    apply_body(groups, &data[off + 12], blen, id, off + 12);
    off += 12 + blen;
  }
  // If a torn tail was detected, truncate the file to the valid prefix so
  // future appends don't interleave with garbage.
  if (fix_tail && off < n) ::truncate(p.c_str(), (off_t)off);
  return true;
}

bool replay_segment(Wal& w, uint32_t id) {
  return replay_segment_into(w.dir, w.groups, id, /*fix_tail=*/true);
}

bool flush_buf(Wal& w) {
  if (w.poisoned) {
    if (w.err.empty()) w.err = "wal poisoned";
    return false;
  }
  if (w.buf.empty()) return true;
  if (fault_fire(w.fault_short_after)) {
    // Injected torn write: a prefix of the staged records lands on disk,
    // then the device "fails".  Poisons like any non-ENOSPC write error;
    // recovery's CRC framing truncates the torn tail on reopen.
    size_t keep = (size_t)std::min<int64_t>(
        std::max<int64_t>(w.fault_short_keep, 0), (int64_t)w.buf.size());
    size_t off = 0;
    while (off < keep) {
      ssize_t wr = ::write(w.fd, w.buf.data() + off, keep - off);
      if (wr < 0) break;
      off += (size_t)wr;
    }
    w.err = "injected short write";
    w.last_errno = EIO;
    w.poisoned = true;
    return false;
  }
  int inj = fault_fire(w.fault_write_after) ? (int)w.fault_write_errno : 0;
  size_t off = 0;
  while (off < w.buf.size()) {
    ssize_t wr =
        inj ? -1 : ::write(w.fd, w.buf.data() + off, w.buf.size() - off);
    if (wr < 0) {
      int e = inj ? inj : errno;
      w.err = std::strerror(e);
      w.last_errno = e;
      if (e == ENOSPC) {
        // Disk full is the one RETRIABLE write failure: rewind the segment
        // to the last known-good offset (a partial flush may have landed)
        // and keep the buffer so a later barrier retries once space frees.
        // Fresh segments are opened without O_APPEND, so the file offset
        // must be walked back alongside the truncate.
        ::ftruncate(w.fd, (off_t)w.seg_off);
        ::lseek(w.fd, (off_t)w.seg_off, SEEK_SET);
      } else {
        w.poisoned = true;
      }
      return false;
    }
    off += (size_t)wr;
  }
  w.seg_off += w.buf.size();
  w.buf.clear();
  return true;
}

void maybe_rotate(Wal& w) {
  if (w.seg_off + w.buf.size() < w.segment_bytes) return;
  if (!flush_buf(w)) return;  // surfaces at the sync barrier
  if (::fsync(w.fd) != 0) {
    int e = errno;
    w.err = std::string("fsync: ") + std::strerror(e);
    w.last_errno = e;
    w.poisoned = true;  // never retry fsync on a failed fd
    return;
  }
  open_segment(w, w.seg_id + 1, true);
}

double mono_s() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (double)ts.tv_sec + 1e-9 * (double)ts.tv_nsec;
}

// Record-level bodies shared by the serial entry points and the native host
// tier (wal_stage_and_sync): one implementation per record type keeps the
// two paths byte-identical by construction.
void do_truncate(Wal& w, uint32_t group, uint64_t from) {
  std::vector<uint8_t> body;
  body.push_back(kTruncate);
  put_u32(body, group);
  put_u64(body, from);
  w.groups[group].drop_suffix(from);
  frame(w.buf, body);
  maybe_rotate(w);
}

void do_milestone(Wal& w, uint32_t group, uint64_t index, int64_t term) {
  std::vector<uint8_t> body;
  body.push_back(kMilestone);
  put_u32(body, group);
  put_u64(body, index);
  put_u64(body, (uint64_t)term);
  auto& gs = w.groups[group];
  if ((int64_t)index >= gs.floor) {  // mirror apply_body's replay semantics
    gs.floor = (int64_t)index;
    gs.floor_term = term;
    gs.drop_prefix(index);
    if (gs.tail < gs.floor) gs.tail = gs.floor;
  }
  frame(w.buf, body);
  maybe_rotate(w);
}

// Shared bulk-entry staging loop (hot path): records are framed IN PLACE
// into the write buffer (no per-entry body vector; the CRC chains over
// header and payload without a copy) and the in-memory index exploits the
// staging order — entries arrive as ascending contiguous runs per group, so
// after one drop_suffix at a run's head every insert is an O(1) hinted
// emplace at map end instead of an O(log n) search.  `ptr_at(i)` resolves
// row i's payload bytes, letting the blob-offset ABI (wal_append_entries)
// and the raw-pointer ABI (wal_stage_and_sync) share one byte-identical
// implementation.
template <typename PtrAt>
void stage_rows_impl(Wal& w, uint64_t n, const uint32_t* groups,
                     const uint64_t* idxs, const int64_t* terms,
                     const uint32_t* lens, PtrAt ptr_at) {
  if (n == 0) return;
  const double stat_t0 = mono_s();
  uint64_t total = 0;
  for (uint64_t i = 0; i < n; i++) total += 37u + (uint64_t)lens[i];
  w.buf.reserve(w.buf.size() + total);
  uint8_t hdr[25];
  hdr[0] = kEntry;
  GroupState* gs = nullptr;
  uint32_t cur_g = 0;
  uint64_t prev_idx = 0;
  bool run_live = false;
  for (uint64_t i = 0; i < n; i++) {
    const uint32_t g = groups[i];
    const uint64_t idx = idxs[i];
    const uint32_t plen = lens[i];
    const uint8_t* p = ptr_at(i);
    // body header (little-endian, layout matches wal_append_entry)
    hdr[1] = (uint8_t)g; hdr[2] = (uint8_t)(g >> 8);
    hdr[3] = (uint8_t)(g >> 16); hdr[4] = (uint8_t)(g >> 24);
    for (int b = 0; b < 8; b++) hdr[5 + b] = (uint8_t)(idx >> (8 * b));
    const uint64_t t = (uint64_t)terms[i];
    for (int b = 0; b < 8; b++) hdr[13 + b] = (uint8_t)(t >> (8 * b));
    hdr[21] = (uint8_t)plen; hdr[22] = (uint8_t)(plen >> 8);
    hdr[23] = (uint8_t)(plen >> 16); hdr[24] = (uint8_t)(plen >> 24);
    const uint32_t crc = crc32(p, plen, crc32(hdr, 25));
    put_u32(w.buf, kMagic);
    put_u32(w.buf, 25u + plen);
    put_u32(w.buf, crc);
    const uint64_t body_off = w.seg_off + w.buf.size();
    w.buf.insert(w.buf.end(), hdr, hdr + 25);
    if (plen) w.buf.insert(w.buf.end(), p, p + plen);
    // index update (mirrors wal_append_entry/replay semantics)
    if (gs == nullptr || g != cur_g) {
      gs = &w.groups[g];
      cur_g = g;
      run_live = false;
    }
    if (run_live && idx == prev_idx + 1) {
      gs->entries.emplace_hint(gs->entries.end(), idx,
                               EntryRef{terms[i], w.seg_id, body_off + 25,
                                        plen});
    } else {
      gs->drop_suffix(idx);
      gs->entries[idx] = EntryRef{terms[i], w.seg_id, body_off + 25, plen};
      run_live = true;
    }
    gs->tail = (int64_t)idx;
    prev_idx = idx;
    if (w.seg_off + w.buf.size() >= w.segment_bytes) {
      maybe_rotate(w);
      gs = nullptr;  // rotation does not move the map, but re-resolve for
                     // clarity; the payload refs already recorded keep
                     // their (seg, off) and are unaffected.
    }
  }
  w.stat_stage_ns.fetch_add((uint64_t)((mono_s() - stat_t0) * 1e9),
                            std::memory_order_relaxed);
  w.stat_bytes.fetch_add(total, std::memory_order_relaxed);
  w.stat_stage_calls.fetch_add(1, std::memory_order_relaxed);
}

// Split [0, n_items) into one contiguous chunk per worker; worker 0 runs
// inline on the calling thread.  `f(c0, c1)` must be thread-safe for
// disjoint ranges.
template <typename F>
void run_ranges(uint32_t n_workers, uint64_t n_items, F&& f) {
  if (n_workers <= 1 || n_items < (uint64_t)n_workers * 4) {
    f((uint64_t)0, n_items);
    return;
  }
  uint64_t chunk = (n_items + n_workers - 1) / n_workers;
  std::vector<std::thread> ts;
  for (uint64_t c0 = chunk; c0 < n_items; c0 += chunk) {
    uint64_t c1 = std::min(n_items, c0 + chunk);
    ts.emplace_back([&f, c0, c1]() { f(c0, c1); });
  }
  f((uint64_t)0, chunk);
  for (auto& t : ts) t.join();
}

}  // namespace

extern "C" {

void* wal_open(const char* dir, uint64_t segment_bytes) {
  Wal* w = new Wal();
  w->dir = dir;
  w->segment_bytes = segment_bytes ? segment_bytes : (64u << 20);
  ::mkdir(dir, 0755);
  // A leftover compaction temp from a crash mid-GC is garbage: the frozen
  // segments it was built from are still live (gc_finish renames before it
  // unlinks), so recovery replays them and the tmp is simply re-derived.
  ::unlink((w->dir + "/gc.tmp").c_str());
  // Discover and replay segments in ascending id order.
  std::vector<uint32_t> segs;
  if (DIR* d = ::opendir(dir)) {
    while (dirent* e = ::readdir(d)) {
      unsigned id;
      if (std::sscanf(e->d_name, "%8u.wal", &id) == 1) segs.push_back(id);
    }
    ::closedir(d);
  }
  std::sort(segs.begin(), segs.end());
  for (uint32_t id : segs) {
    replay_segment(*w, id);
    w->live_segs.push_back(id);
  }
  uint32_t next = segs.empty() ? 0 : segs.back();
  if (!open_segment(*w, next, segs.empty())) { delete w; return nullptr; }
  return w;
}

void wal_close(void* h) {
  Wal* w = (Wal*)h;
  if (!w) return;
  flush_buf(*w);
  if (w->fd >= 0) { ::fsync(w->fd); ::close(w->fd); }
  delete w;
}

// -- writes (buffered; durable after wal_sync) ------------------------------

void wal_append_entry(void* h, uint32_t group, uint64_t index, int64_t term,
                      const uint8_t* payload, uint32_t plen) {
  Wal* w = (Wal*)h;
  std::vector<uint8_t> body;
  body.reserve(25 + plen);
  body.push_back(kEntry);
  put_u32(body, group);
  put_u64(body, index);
  put_u64(body, (uint64_t)term);
  put_u32(body, plen);
  if (plen) body.insert(body.end(), payload, payload + plen);
  // Index update mirrors replay so reads see the write immediately; the
  // payload ref points into the open segment at its post-flush offset.
  uint64_t body_off = w->seg_off + w->buf.size() + 12;
  auto& gs = w->groups[group];
  gs.drop_suffix(index);
  gs.entries[index] = EntryRef{term, w->seg_id, body_off + 25, plen};
  gs.tail = (int64_t)index;
  frame(w->buf, body);
  w->stat_bytes.fetch_add(12u + 25u + (uint64_t)plen,
                          std::memory_order_relaxed);
  w->stat_stage_calls.fetch_add(1, std::memory_order_relaxed);
  maybe_rotate(*w);
}

void wal_append_stable(void* h, uint32_t group, int64_t term, int64_t ballot) {
  Wal* w = (Wal*)h;
  std::vector<uint8_t> body;
  body.push_back(kStable);
  put_u32(body, group);
  put_u64(body, (uint64_t)term);
  put_u64(body, (uint64_t)ballot);
  auto& gs = w->groups[group];
  gs.stable_term = term;
  gs.ballot = ballot;
  gs.has_stable = true;
  frame(w->buf, body);
  maybe_rotate(*w);
}

void wal_truncate(void* h, uint32_t group, uint64_t from) {
  do_truncate(*(Wal*)h, group, from);
}

void wal_milestone(void* h, uint32_t group, uint64_t index, int64_t term) {
  do_milestone(*(Wal*)h, group, index, term);
}

// Group destroyed (admin lifecycle): journal a RESET so the lane's entire
// durable state — entries, stable record, milestone — is forgotten, letting
// a future group reuse the lane from scratch (the reference deletes the
// group's RocksDB directory, command/storage/RocksStateLoader.java:48-59).
void wal_reset(void* h, uint32_t group) {
  Wal* w = (Wal*)h;
  std::vector<uint8_t> body;
  body.push_back(kReset);
  put_u32(body, group);
  w->groups.erase(group);
  frame(w->buf, body);
  maybe_rotate(*w);
}

// Flush buffered records and fsync — the durability barrier.  One call per
// node tick covers every group (group commit).
int wal_sync(void* h) {
  Wal* w = (Wal*)h;
  if (w->poisoned) return -1;  // fail-stop: never fsync a failed fd again
  // Timed from here so injected sync delays (the slow-I/O gray-failure
  // simulation) show up in stat_fsync_ns exactly as a real slow disk would.
  const double stat_t0 = mono_s();
  if (w->sync_delay_us > 0) ::usleep((useconds_t)w->sync_delay_us);
  if (!flush_buf(*w)) return -1;
  if (fault_fire(w->fault_fsync_after)) {
    w->err = "injected fsync failure";
    w->last_errno = (int)w->fault_fsync_errno;
    w->poisoned = true;
    return -1;
  }
  if (::fsync(w->fd) != 0) {
    int e = errno;
    w->err = std::string("fsync: ") + std::strerror(e);
    w->last_errno = e;
    w->poisoned = true;
    return -1;
  }
  w->stat_fsync_ns.fetch_add((uint64_t)((mono_s() - stat_t0) * 1e9),
                             std::memory_order_relaxed);
  w->stat_fsync_calls.fetch_add(1, std::memory_order_relaxed);
  return 0;
}

// -- reads ------------------------------------------------------------------

int64_t wal_tail(void* h, uint32_t group) {
  Wal* w = (Wal*)h;
  auto it = w->groups.find(group);
  return it == w->groups.end() ? 0 : it->second.tail;
}
int64_t wal_floor(void* h, uint32_t group) {
  Wal* w = (Wal*)h;
  auto it = w->groups.find(group);
  return it == w->groups.end() ? 0 : it->second.floor;
}
int64_t wal_floor_term(void* h, uint32_t group) {
  Wal* w = (Wal*)h;
  auto it = w->groups.find(group);
  return it == w->groups.end() ? 0 : it->second.floor_term;
}
int wal_stable(void* h, uint32_t group, int64_t* term, int64_t* ballot) {
  Wal* w = (Wal*)h;
  auto it = w->groups.find(group);
  if (it == w->groups.end() || !it->second.has_stable) return 0;
  *term = it->second.stable_term;
  *ballot = it->second.ballot;
  return 1;
}
// Entry term at index, or -1 if absent (floor itself reports floor_term).
int64_t wal_entry_term(void* h, uint32_t group, uint64_t index) {
  Wal* w = (Wal*)h;
  auto git = w->groups.find(group);
  if (git == w->groups.end()) return -1;
  auto& gs = git->second;
  if ((int64_t)index == gs.floor) return gs.floor_term;
  auto it = gs.entries.find(index);
  return it == gs.entries.end() ? -1 : it->second.term;
}
int64_t wal_entry_len(void* h, uint32_t group, uint64_t index) {
  Wal* w = (Wal*)h;
  auto git = w->groups.find(group);
  if (git == w->groups.end()) return -1;
  auto it = git->second.entries.find(index);
  return it == git->second.entries.end() ? -1 : (int64_t)it->second.len;
}
// Copy payload into caller buffer; returns bytes copied or -1.
int64_t wal_entry_payload(void* h, uint32_t group, uint64_t index,
                          uint8_t* out, uint64_t cap) {
  Wal* w = (Wal*)h;
  auto git = w->groups.find(group);
  if (git == w->groups.end()) return -1;
  auto it = git->second.entries.find(index);
  if (it == git->second.entries.end()) return -1;
  const EntryRef& r = it->second;
  if (r.len > cap) return -1;
  if (r.len == 0) return 0;
  if (r.seg == w->seg_id && r.off >= w->seg_off) {
    // Still in the unflushed buffer.
    size_t boff = (size_t)(r.off - w->seg_off);
    if (boff + r.len > w->buf.size()) return -1;
    std::memcpy(out, w->buf.data() + boff, r.len);
    return r.len;
  }
  std::string p = seg_path(*w, r.seg);
  int fd = ::open(p.c_str(), O_RDONLY);
  if (fd < 0) return -1;
  ssize_t rd = ::pread(fd, out, r.len, (off_t)r.off);
  ::close(fd);
  return rd == (ssize_t)r.len ? rd : -1;
}

uint64_t wal_group_count(void* h) { return ((Wal*)h)->groups.size(); }
uint64_t wal_segment_count(void* h) { return ((Wal*)h)->live_segs.size(); }

// On-disk footprint of all live segments + the unflushed buffer.  Stats the
// files (cheap at GC-policy cadence) so the figure survives restart.
uint64_t wal_total_bytes(void* h) {
  Wal* w = (Wal*)h;
  uint64_t total = w->buf.size();
  struct stat st;
  for (uint32_t id : w->live_segs)
    if (::stat(seg_path(*w, id).c_str(), &st) == 0)
      total += (uint64_t)st.st_size;
  return total;
}

// Bytes a checkpoint rewrite would carry: live entries (frame + body + payload)
// plus per-group stable/milestone records.  total_bytes / live_bytes is the
// GC trigger ratio (the dead fraction — entries superseded by overwrite,
// truncation, compaction or reset — is what GC reclaims).
uint64_t wal_live_bytes(void* h) {
  Wal* w = (Wal*)h;
  uint64_t live = 0;
  for (auto& kv : w->groups) {
    const GroupState& gs = kv.second;
    if (gs.has_stable) live += 12 + 21;
    if (gs.floor > 0) live += 12 + 21;
    for (auto& er : gs.entries) live += 12 + 25 + er.second.len;
  }
  return live;
}

// List group ids into caller buffer; returns count written.
uint64_t wal_groups(void* h, uint32_t* out, uint64_t cap) {
  Wal* w = (Wal*)h;
  uint64_t n = 0;
  for (auto& kv : w->groups) {
    if (n >= cap) break;
    out[n++] = kv.first;
  }
  return n;
}

// Bulk state export for boot-time restore (the vectorized analog of the
// reference's per-group RaftContext.initialize restore walk,
// context/RaftContext.java:91-113): one call fills per-group arrays for
// groups [0, G) plus the [G, L] ring of live entry terms (slot = idx % L
// for idx in (floor, tail]).  live_count[g] lets the caller verify
// contiguity (expected = tail - floor) without a per-entry Python loop.
uint64_t wal_export_state(void* h, uint32_t G, uint32_t L,
                          int64_t* stable_term, int64_t* ballot,
                          uint8_t* has_stable, int64_t* floor_out,
                          int64_t* floor_term, int64_t* tail,
                          int64_t* live_count, int32_t* ring) {
  Wal* w = (Wal*)h;
  uint64_t n = 0;
  for (auto& kv : w->groups) {
    uint32_t g = kv.first;
    if (g >= G) continue;
    GroupState& gs = kv.second;
    stable_term[g] = gs.stable_term;
    ballot[g] = gs.ballot;
    has_stable[g] = gs.has_stable ? 1 : 0;
    floor_out[g] = gs.floor;
    floor_term[g] = gs.floor_term;
    tail[g] = gs.tail;
    int64_t cnt = 0;
    for (auto& er : gs.entries) {
      int64_t idx = (int64_t)er.first;
      if (idx > gs.floor && idx <= gs.tail) {
        if (ring) ring[(uint64_t)g * L + (er.first % L)] =
            (int32_t)er.second.term;
        cnt++;
      }
    }
    live_count[g] = cnt;
    n++;
  }
  return n;
}

// Batched append: n entries across any mix of groups in ONE call, payload
// bytes concatenated in `payloads` at offsets `offs` (the host runtime
// stages a whole tick's writes and crosses the ctypes boundary once).
// Hot path of the durable tier; see stage_rows_impl for the framing and
// index discipline.
void wal_append_entries(void* h, uint64_t n, const uint32_t* groups,
                        const uint64_t* idxs, const int64_t* terms,
                        const uint8_t* payloads, const uint64_t* offs,
                        const uint32_t* lens) {
  stage_rows_impl(*(Wal*)h, n, groups, idxs, terms, lens,
                  [&](uint64_t i) { return payloads + offs[i]; });
}

// Rewrite all live state into a fresh segment and delete older segments —
// the compaction/GC pass (the reference's RocksDB deleteRange + snapshot
// retention analog, RocksLog.java:228-242).
int wal_checkpoint(void* h) {
  Wal* w = (Wal*)h;
  if (w->gc.pending) return -1;  // three-phase GC owns the frozen segments
  if (!flush_buf(*w)) return -1;
  ::fsync(w->fd);
  uint32_t new_id = w->seg_id + 1;
  std::vector<uint32_t> old_segs = w->live_segs;
  if (!open_segment(*w, new_id, true)) return -1;
  // Track only segments written from here on (rotation during the rewrite
  // may add more); everything in old_segs dies afterwards.
  w->live_segs.assign(1, new_id);
  // Re-emit live records; payload bytes are read via the OLD refs before
  // the index is repointed.
  for (auto& kv : w->groups) {
    uint32_t g = kv.first;
    GroupState& gs = kv.second;
    if (gs.has_stable) wal_append_stable(h, g, gs.stable_term, gs.ballot);
    if (gs.floor > 0) {
      std::vector<uint8_t> body;
      body.push_back(kMilestone);
      put_u32(body, g);
      put_u64(body, (uint64_t)gs.floor);
      put_u64(body, (uint64_t)gs.floor_term);
      frame(w->buf, body);
    }
    // Copy entries (iterate over a snapshot of refs; wal_append_entry
    // mutates the map).
    std::vector<std::pair<uint64_t, EntryRef>> ents(gs.entries.begin(),
                                                    gs.entries.end());
    for (auto& er : ents) {
      std::vector<uint8_t> payload(er.second.len);
      if (er.second.len) {
        std::string p = seg_path(*w, er.second.seg);
        int fd = ::open(p.c_str(), O_RDONLY);
        if (fd < 0) return -1;
        ssize_t rd = ::pread(fd, payload.data(), er.second.len,
                             (off_t)er.second.off);
        ::close(fd);
        if (rd != (ssize_t)er.second.len) return -1;
      }
      wal_append_entry(h, g, er.first, er.second.term, payload.data(),
                       er.second.len);
    }
  }
  if (!flush_buf(*w)) return -1;
  if (::fsync(w->fd) != 0) return -1;
  for (uint32_t id : old_segs)
    if (std::find(w->live_segs.begin(), w->live_segs.end(), id) ==
        w->live_segs.end())
      ::unlink(seg_path(*w, id).c_str());
  return 0;
}

// ---------------------------------------------------------------------------
// Three-phase GC: bounded tick-thread latency (VERDICT r2 #6 — the full
// wal_checkpoint rewrite on the tick thread is a multi-second stall at scale;
// the reference reclaims off the consensus path via RocksDB deleteRange +
// background compaction, command/storage/RocksLog.java:228-242).
//
//   gc_begin   (tick thread, O(1)):  seal + rotate; freeze prior segments.
//   gc_rewrite (worker thread):      replay the frozen files into a PRIVATE
//                                    index, write a compacted base to gc.tmp,
//                                    build the payload-repoint table.  Shares
//                                    no mutable state with the live engine.
//   gc_finish  (tick thread, O(live entries), memory-only + rename/unlink):
//                                    verify coverage, swap the base in under
//                                    the first frozen id, repoint EntryRefs,
//                                    drop the rest of the frozen set.
//
// Correctness of the swap: the base carries the frozen prefix's compacted
// state under id frozen[0], which sorts BEFORE every segment written after
// gc_begin — so recovery replay order (base, then post-begin segments)
// reproduces exactly the live state.  A crash between rename and the
// unlinks re-replays surviving frozen segments after the base, which is a
// no-op (each record reasserts state the base already contains or a later
// record overrides).
// ---------------------------------------------------------------------------

int wal_gc_begin(void* h) {
  Wal* w = (Wal*)h;
  if (w->gc.pending) return -1;
  if (!flush_buf(*w)) return -1;
  if (::fsync(w->fd) != 0) return -1;
  w->gc.frozen = w->live_segs;           // everything sealed so far
  w->gc.repoint.clear();
  w->gc.rewritten = false;
  if (!open_segment(*w, w->seg_id + 1, true)) return -1;
  w->gc.pending = true;
  return (int)w->gc.frozen.size();
}

// Worker-thread safe: reads only dir + the frozen file set (immutable while
// pending) and writes only gc.repoint/gc.rewritten (tick thread reads them
// only in gc_finish, after the caller observed rewrite completion).
int64_t wal_gc_rewrite(void* h) {
  Wal* w = (Wal*)h;
  if (!w->gc.pending || w->gc.rewritten) return -1;
  GroupMap priv;
  for (uint32_t id : w->gc.frozen)
    if (!replay_segment_into(w->dir, priv, id, /*fix_tail=*/false)) return -1;

  std::string tmp_path = w->dir + "/gc.tmp";
  int fd = ::open(tmp_path.c_str(), O_CREAT | O_WRONLY | O_TRUNC, 0644);
  if (fd < 0) return -1;
  // Source-segment fd cache: live entries cluster in a handful of frozen
  // segments; one open per segment, not per entry.
  std::unordered_map<uint32_t, int> src_fds;
  auto close_all = [&]() {
    for (auto& kv : src_fds) ::close(kv.second);
    ::close(fd);
  };
  std::vector<uint8_t> out;
  out.reserve(1 << 20);
  uint64_t written = 0;
  auto flush_out = [&]() -> bool {
    size_t off = 0;
    while (off < out.size()) {
      ssize_t wr = ::write(fd, out.data() + off, out.size() - off);
      if (wr < 0) return false;
      off += (size_t)wr;
    }
    written += out.size();
    out.clear();
    return true;
  };
  for (auto& kv : priv) {
    uint32_t g = kv.first;
    GroupState& gs = kv.second;
    if (gs.has_stable) {
      std::vector<uint8_t> body;
      body.push_back(kStable);
      put_u32(body, g);
      put_u64(body, (uint64_t)gs.stable_term);
      put_u64(body, (uint64_t)gs.ballot);
      frame(out, body);
    }
    if (gs.floor > 0) {
      std::vector<uint8_t> body;
      body.push_back(kMilestone);
      put_u32(body, g);
      put_u64(body, (uint64_t)gs.floor);
      put_u64(body, (uint64_t)gs.floor_term);
      frame(out, body);
    }
    for (auto& er : gs.entries) {
      std::vector<uint8_t> payload(er.second.len);
      if (er.second.len) {
        int sfd;
        auto fit = src_fds.find(er.second.seg);
        if (fit != src_fds.end()) {
          sfd = fit->second;
        } else {
          sfd = ::open(seg_path_in(w->dir, er.second.seg).c_str(), O_RDONLY);
          if (sfd < 0) { close_all(); return -1; }
          src_fds[er.second.seg] = sfd;
        }
        ssize_t rd = ::pread(sfd, payload.data(), er.second.len,
                             (off_t)er.second.off);
        if (rd != (ssize_t)er.second.len) { close_all(); return -1; }
      }
      std::vector<uint8_t> body;
      body.reserve(25 + er.second.len);
      body.push_back(kEntry);
      put_u32(body, g);
      put_u64(body, er.first);
      put_u64(body, (uint64_t)er.second.term);
      put_u32(body, er.second.len);
      body.insert(body.end(), payload.begin(), payload.end());
      // Payload lands at: frames so far + frame header (12) + body prefix (25).
      uint64_t payload_off = written + out.size() + 12 + 25;
      frame(out, body);
      w->gc.repoint.push_back(
          GcRepoint{g, er.first, er.second.term, payload_off, er.second.len});
      if (out.size() > (1u << 20) && !flush_out()) { close_all(); return -1; }
    }
  }
  if (!flush_out()) { close_all(); return -1; }
  if (::fsync(fd) != 0) { close_all(); return -1; }
  close_all();
  w->gc.rewritten = true;
  return (int64_t)written;
}

int wal_gc_finish(void* h) {
  Wal* w = (Wal*)h;
  if (!w->gc.pending || !w->gc.rewritten) return -1;
  std::unordered_set<uint32_t> frozen(w->gc.frozen.begin(),
                                      w->gc.frozen.end());
  uint32_t base_id = w->gc.frozen.front();

  // Coverage check BEFORE any destructive step: every live payload ref into
  // the frozen set must have a matching repoint row, else the base misses
  // data and the swap would corrupt reads.  (Cannot happen by construction —
  // any ref still pointing into frozen was last written there and therefore
  // replayed — but a cheap memory-only walk buys a hard guarantee.)
  uint64_t frozen_refs = 0, matched = 0;
  for (auto& kv : w->groups)
    for (auto& er : kv.second.entries)
      if (frozen.count(er.second.seg)) frozen_refs++;
  for (auto& rp : w->gc.repoint) {
    auto git = w->groups.find(rp.g);
    if (git == w->groups.end()) continue;
    auto it = git->second.entries.find(rp.idx);
    if (it != git->second.entries.end() && frozen.count(it->second.seg) &&
        it->second.term == rp.term)
      matched++;
  }
  if (matched != frozen_refs) { w->err = "gc coverage mismatch"; return -2; }

  // Durable swap: base file takes the first frozen id (sorts before every
  // post-begin segment), then the rest of the frozen set dies.
  std::string tmp_path = w->dir + "/gc.tmp";
  if (::rename(tmp_path.c_str(), seg_path(*w, base_id).c_str()) != 0) {
    w->err = std::string("gc rename: ") + std::strerror(errno);
    return -1;
  }
  if (int dfd = ::open(w->dir.c_str(), O_RDONLY); dfd >= 0) {
    ::fsync(dfd);  // make the rename itself durable
    ::close(dfd);
  }
  // Repoint live refs into the base.
  for (auto& rp : w->gc.repoint) {
    auto git = w->groups.find(rp.g);
    if (git == w->groups.end()) continue;
    auto it = git->second.entries.find(rp.idx);
    if (it != git->second.entries.end() && frozen.count(it->second.seg) &&
        it->second.term == rp.term)
      it->second = EntryRef{rp.term, base_id, rp.off, rp.len};
  }
  for (uint32_t id : w->gc.frozen)
    if (id != base_id) ::unlink(seg_path(*w, id).c_str());
  std::vector<uint32_t> segs;
  segs.push_back(base_id);
  for (uint32_t id : w->live_segs)
    if (!frozen.count(id)) segs.push_back(id);
  w->live_segs = std::move(segs);
  w->gc = GcState();
  return 0;
}

// Abandon a pending GC (worker failed / shutdown): drop the temp, keep the
// frozen segments live.  Always safe — nothing was swapped.
void wal_gc_abort(void* h) {
  Wal* w = (Wal*)h;
  ::unlink((w->dir + "/gc.tmp").c_str());
  w->gc = GcState();
}

const char* wal_error(void* h) { return ((Wal*)h)->err.c_str(); }

// -- injectable fault table (testkit/faultfs) -------------------------------
// op: 1=fsync-fail 2=write-fail 3=short-write 4=sync-delay.  `after` counts
// guarded calls before firing (0 = next call); `value` is an errno for ops
// 1/2 (0 -> EIO), bytes kept for op 3, microseconds for op 4 (op 4 is a
// level, not a countdown).  Clearing disarms countdowns but does NOT heal
// `poisoned`: fail-stop latches for the handle lifetime.

int wal_fault_set(void* h, int op, int64_t after, int64_t value) {
  Wal* w = (Wal*)h;
  switch (op) {
    case 1:
      w->fault_fsync_after = after;
      w->fault_fsync_errno = value ? value : EIO;
      return 0;
    case 2:
      w->fault_write_after = after;
      w->fault_write_errno = value ? value : EIO;
      return 0;
    case 3:
      w->fault_short_after = after;
      w->fault_short_keep = value;
      return 0;
    case 4:
      w->sync_delay_us = value;
      return 0;
  }
  return -1;
}

void wal_fault_clear(void* h) {
  Wal* w = (Wal*)h;
  w->fault_fsync_after = -1;
  w->fault_write_after = -1;
  w->fault_short_after = -1;
  w->sync_delay_us = 0;
}

int wal_poisoned(void* h) { return ((Wal*)h)->poisoned ? 1 : 0; }

int wal_last_errno(void* h) { return ((Wal*)h)->last_errno; }

// Zero-copy stats export: fill the caller's 7-slot u64 buffer with this
// handle's cumulative {stage_ns, fsync_ns, pack_ns, bytes, stage_calls,
// fsync_calls, pack_calls}.  Counters are never reset — the Python side
// keeps the last snapshot and folds deltas into the metrics registry.
void wal_stats(void* h, uint64_t* out) {
  Wal* w = (Wal*)h;
  out[0] = w->stat_stage_ns.load(std::memory_order_relaxed);
  out[1] = w->stat_fsync_ns.load(std::memory_order_relaxed);
  out[2] = w->stat_pack_ns.load(std::memory_order_relaxed);
  out[3] = w->stat_bytes.load(std::memory_order_relaxed);
  out[4] = w->stat_stage_calls.load(std::memory_order_relaxed);
  out[5] = w->stat_fsync_calls.load(std::memory_order_relaxed);
  out[6] = w->stat_pack_calls.load(std::memory_order_relaxed);
}

// ---------------------------------------------------------------------------
// Native host tier: the per-stripe persist hot loop behind ONE ctypes call.
//
// The striped Python worker pool (runtime/node.py _host_phase_striped) tops
// out near 1.15x because its workers only overlap the GIL-released syscalls;
// the staging loops themselves serialize on the interpreter.  These entry
// points move the whole stage → fsync → pack pipeline into real OS threads:
// ctypes releases the GIL for the duration of the call, worker k owns WAL
// shards `s % n_workers == k` (the exact ownership map of the Python pool,
// so per-shard record order — and therefore segment bytes — is identical),
// and the tick thread becomes pure orchestration.
//
// Handles must be distinct single-threaded engines (one per shard); within
// a call each shard is touched by exactly one worker, and no other thread
// may use the handles concurrently — the same contract the Python striped
// pool already upholds.
// ---------------------------------------------------------------------------

// Stage one tick's durable work across all shards and (optionally) fsync.
//
// Entry rows are pre-sorted by shard (stable, so the caller's per-group
// ascending contiguous runs survive); `row_off[s]..row_off[s+1]` is shard
// s's slice.  Payload bytes live at caller-supplied absolute addresses
// (`ptrs`, one per row — numpy arena views handed straight through, no blob
// join).  Truncations (`t*`) and milestones (`f*`) use the same per-shard
// CSR layout and are applied AFTER the shard's entries, matching the serial
// path's record order (stable records are staged by the caller before this
// call).  `do_sync=0` stages without the fsync barrier — the crash-window
// tests carve the torn-tail window with it.  Out-params receive the max
// per-worker stage and fsync wall times.
int wal_stage_and_sync(void** handles, uint32_t n_shards, uint32_t n_workers,
                       const uint64_t* row_off, const uint32_t* groups,
                       const uint64_t* idxs, const int64_t* terms,
                       const uint64_t* ptrs, const uint32_t* lens,
                       const uint64_t* trow_off, const uint32_t* tgroups,
                       const uint64_t* tfrom,
                       const uint64_t* frow_off, const uint32_t* fgroups,
                       const uint64_t* fidx, const int64_t* fterm,
                       int do_sync, double* stage_s, double* fsync_s) {
  if (!handles || n_shards == 0) return -1;
  if (n_workers == 0) n_workers = 1;
  if (n_workers > n_shards) n_workers = n_shards;
  std::vector<double> st(n_workers, 0.0), fs(n_workers, 0.0);
  std::vector<int> rc(n_workers, 0);
  auto work = [&](uint32_t k) {
    const double t0 = mono_s();
    for (uint32_t s = k; s < n_shards; s += n_workers) {
      Wal& w = *(Wal*)handles[s];
      const uint64_t r0 = row_off[s], r1 = row_off[s + 1];
      stage_rows_impl(w, r1 - r0, groups + r0, idxs + r0, terms + r0,
                      lens + r0,
                      [&, r0](uint64_t i) {
                        return (const uint8_t*)(uintptr_t)ptrs[r0 + i];
                      });
      for (uint64_t i = trow_off[s]; i < trow_off[s + 1]; i++)
        do_truncate(w, tgroups[i], tfrom[i]);
      for (uint64_t i = frow_off[s]; i < frow_off[s + 1]; i++)
        do_milestone(w, fgroups[i], fidx[i], fterm[i]);
    }
    const double t1 = mono_s();
    st[k] = t1 - t0;
    if (do_sync) {
      // One wal_sync per shard centralizes the failure policy: poisoned
      // engines fail fast, injected faults fire, and any fsync failure
      // latches `poisoned` exactly like the serial barrier.
      for (uint32_t s = k; s < n_shards; s += n_workers)
        if (wal_sync(handles[s]) != 0) rc[k] = -1;
      fs[k] = mono_s() - t1;
    }
  };
  if (n_workers == 1) {
    work(0);
  } else {
    std::vector<std::thread> ts;
    ts.reserve(n_workers - 1);
    for (uint32_t k = 1; k < n_workers; k++) ts.emplace_back(work, k);
    work(0);
    for (auto& t : ts) t.join();
  }
  if (stage_s) *stage_s = *std::max_element(st.begin(), st.end());
  if (fsync_s) *fsync_s = *std::max_element(fs.begin(), fs.end());
  for (int r : rc)
    if (r != 0) return -1;
  return 0;
}

namespace {

// Per-call mmap cache for flushed segment files (pack reads cluster in the
// open segment plus at most a handful of predecessors).
struct SegMap {
  uint8_t* p = nullptr;
  uint64_t size = 0;
  int fd = -1;
};
using SegMapCache = std::unordered_map<uint64_t, SegMap>;

bool copy_payload(Wal* w, uint32_t shard, const EntryRef& r, uint8_t* dst,
                  SegMapCache& maps) {
  if (r.len == 0) return true;
  if (r.seg == w->seg_id && r.off >= w->seg_off) {
    // Still in the unflushed buffer.  Safe to read concurrently: pack runs
    // strictly between staging phases, so no thread mutates the buffer.
    const size_t boff = (size_t)(r.off - w->seg_off);
    if (boff + r.len > w->buf.size()) return false;
    std::memcpy(dst, w->buf.data() + boff, r.len);
    return true;
  }
  const uint64_t key = ((uint64_t)shard << 32) | r.seg;
  auto it = maps.find(key);
  if (it == maps.end()) {
    SegMap sm;
    sm.fd = ::open(seg_path(*w, r.seg).c_str(), O_RDONLY);
    if (sm.fd < 0) return false;
    struct stat stt;
    if (::fstat(sm.fd, &stt) == 0) sm.size = (uint64_t)stt.st_size;
    if (sm.size) {
      void* mp = ::mmap(nullptr, sm.size, PROT_READ, MAP_SHARED, sm.fd, 0);
      if (mp != MAP_FAILED) sm.p = (uint8_t*)mp;
    }
    it = maps.emplace(key, sm).first;
  }
  const SegMap& sm = it->second;
  if (sm.p && r.off + r.len <= sm.size) {
    std::memcpy(dst, sm.p + r.off, r.len);
    return true;
  }
  return ::pread(sm.fd, dst, r.len, (off_t)r.off) == (ssize_t)r.len;
}

void drop_segmaps(SegMapCache& maps) {
  for (auto& kv : maps) {
    if (kv.second.p) ::munmap(kv.second.p, kv.second.size);
    if (kv.second.fd >= 0) ::close(kv.second.fd);
  }
  maps.clear();
}

// Total payload bytes for entries [start, start+n) of group g, or -1 if the
// range is not fully present (column gets dropped, exactly as the Python
// packer drops a column whose runs/window cannot cover it).
int64_t col_bytes(Wal* w, uint32_t g, uint64_t start, uint32_t n) {
  if (n == 0) return 0;
  auto git = w->groups.find(g);
  if (git == w->groups.end()) return -1;
  auto& ents = git->second.entries;
  auto it = ents.find(start);
  uint64_t sum = 0;
  for (uint32_t k = 0; k < n; k++, ++it) {
    if (it == ents.end() || it->first != start + k) return -1;
    sum += it->second.len;
  }
  return (int64_t)sum;
}

}  // namespace

// Pack the AppendEntries payload blob for n_cols columns: the u32 length
// vector for every kept column's entries, then the payload bytes — the
// byte-exact layout of codec.pack_kind_section's `blob_section`.  Columns
// whose range is absent get ok_out[c]=0 and contribute nothing (the caller
// drops/defers them like the Python packer).  Payloads are resolved from
// the engines' own indexes (unflushed buffer or mmap'd segments) with
// chunk-parallel workers; read-only over the maps, so safe to run while no
// staging is in flight.  Returns the malloc'd blob via *out_ptr (free with
// wal_buf_free) and its total length, or -1 on I/O failure (caller falls
// back to the Python pack path).
int64_t wal_pack_ae(void** handles, uint32_t n_shards, uint32_t n_workers,
                    uint64_t n_cols, const uint32_t* gs,
                    const uint64_t* starts, const uint32_t* ns,
                    uint8_t* ok_out, uint8_t** out_ptr) {
  if (!handles || n_shards == 0) return -1;
  *out_ptr = nullptr;
  std::vector<uint64_t> pay(n_cols, 0);
  run_ranges(n_workers, n_cols, [&](uint64_t c0, uint64_t c1) {
    for (uint64_t c = c0; c < c1; c++) {
      Wal* w = (Wal*)handles[gs[c] % n_shards];
      const int64_t b = col_bytes(w, gs[c], starts[c], ns[c]);
      ok_out[c] = b >= 0 ? 1 : 0;
      pay[c] = b >= 0 ? (uint64_t)b : 0;
    }
  });
  // Column offsets: kept columns' length words first, then their payloads.
  std::vector<uint64_t> loff(n_cols, 0), poff(n_cols, 0);
  uint64_t lens_total = 0, pay_total = 0;
  for (uint64_t c = 0; c < n_cols; c++) {
    if (!ok_out[c]) continue;
    loff[c] = lens_total;
    lens_total += 4ull * ns[c];
    poff[c] = pay_total;
    pay_total += pay[c];
  }
  const uint64_t total = lens_total + pay_total;
  uint8_t* out = (uint8_t*)std::malloc(total ? total : 1);
  if (!out) return -1;
  std::atomic<bool> fail(false);
  run_ranges(n_workers, n_cols, [&](uint64_t c0, uint64_t c1) {
    SegMapCache maps;
    for (uint64_t c = c0; c < c1 && !fail.load(std::memory_order_relaxed);
         c++) {
      if (!ok_out[c] || ns[c] == 0) continue;  // heartbeats carry no bytes
      Wal* w = (Wal*)handles[gs[c] % n_shards];
      const double pack_t0 = mono_s();
      auto git = w->groups.find(gs[c]);
      if (git == w->groups.end()) { fail.store(true); break; }
      auto it = git->second.entries.find(starts[c]);
      uint8_t* lp = out + loff[c];
      uint8_t* pp = out + lens_total + poff[c];
      for (uint32_t k = 0; k < ns[c]; k++, ++it) {
        if (it == git->second.entries.end() ||
            it->first != starts[c] + k) { fail.store(true); break; }
        const EntryRef& r = it->second;
        lp[0] = (uint8_t)r.len; lp[1] = (uint8_t)(r.len >> 8);
        lp[2] = (uint8_t)(r.len >> 16); lp[3] = (uint8_t)(r.len >> 24);
        lp += 4;
        if (!copy_payload(w, gs[c] % n_shards, r, pp, maps)) {
          fail.store(true);
          break;
        }
        pp += r.len;
      }
      w->stat_pack_ns.fetch_add((uint64_t)((mono_s() - pack_t0) * 1e9),
                                std::memory_order_relaxed);
      w->stat_pack_calls.fetch_add(1, std::memory_order_relaxed);
    }
    drop_segmaps(maps);
  });
  if (fail.load()) {
    std::free(out);
    return -1;
  }
  *out_ptr = out;
  return (int64_t)total;
}

void wal_buf_free(uint8_t* p) { std::free(p); }

}  // extern "C"
