"""Wire codec for the host transport plane.

The reference moves RPCs one Java object at a time through a custom Netty
frame protocol (transport/EventCodec.java:25-40 — SOH/STX framing, Kryo
bodies, 64MB cap).  Here the unit of transfer is a *tick slice*: everything
one node says to one peer in one engine tick, for all groups at once, packed
as sparse columns of the dense ``Messages`` arrays (only groups with a valid
message travel).  This is the wire analog of the reference's single
scope-multiplexed connection per peer (transport/NettyNode.java:54-74) with
the per-RPC overhead amortized across every group.

Frame format (all little-endian):
    magic u32 | type u8 | body_len u32 | crc32(body) u32 | body

Types:
    HELLO     — connection handshake: (node_id, G, P, B) shape contract
                (reference ShakeHandEvent, transport/EventBus.java:71-97)
    MSGS      — one tick slice (see ``pack_slice``)
    SNAP_REQ  — snapshot fetch request: (group, index, term)
                (reference WaitSnapEvent, transport/event/WaitSnapEvent.java:8-38)
    SNAP_HDR  — snapshot response header: (group, index, term, ok, total_len)
                (reference TransSnapEvent, transport/event/TransSnapEvent.java:8-64).
                After an ok header the stream switches to TRANSPARENT
                mode: exactly `total_len` RAW file bytes follow, outside
                the frame codec — served zero-copy via sendfile and
                written to disk incrementally on the receiving side.
                This matches the reference byte-for-byte in spirit
                (DefaultFileRegion sendfile, transport/EventBus.java:98-111;
                "transparent mode", EventCodec.java:282-290): the CRC
                covers the header only, the bulk pays no per-chunk
                framing or checksum, and snapshot size is unbounded by
                MAX_BODY.
"""

from __future__ import annotations

import struct
import zlib
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

MAGIC = 0x54505552  # "RUPT"
HELLO, MSGS, SNAP_REQ, SNAP_HDR, FWD_REQ, FWD_RESP = 1, 2, 3, 4, 5, 6
# Linearizable-read forward: same body format as FWD_REQ/FWD_RESP, routed
# to the serve side's read handler (RaftNode.read) instead of submit —
# reads must execute on the leader but never enter the log.
FWD_READ = 7
# Membership-op forward: a follower relays a §6 change or a leadership
# transfer to the current leader.  Body: group u32 | op u8 (CONF_OP_*) |
# timeout_ms u32 | a u32 | b u32 (conf: voters/learners masks; xfer:
# target/0).  Replies travel as FWD_RESP with a JSON result.
FWD_CONF = 8
CONF_OP_CHANGE, CONF_OP_TRANSFER = 1, 2
# Hop-tracing sideband (utils/latency.py HopTracer): a leader attaches a
# compact trace context to the AE traffic shipping a SAMPLED entry
# (direction 0, request), and the follower echoes it back with
# single-clock durability durations (direction 1, echo).  The frames
# piggyback on the same per-peer blob as the MSGS slice — one send, no
# extra wire round trips — and the kind is OUTSIDE SCHEMA_TAG (the tag
# covers the MSGS column layout only), so a hop-aware node interoperates
# with a hop-blind one: an unrecognized frame type falls through the
# reader's dispatch unhandled, and the ignored context simply expires
# leader-side (never fabricates a latency).
HOPS = 9

MAX_BODY = 64 << 20  # 64 MB cap, matching the reference (EventCodec.java:26)

_HDR = struct.Struct("<IBII")


class PayloadRun:
    """A contiguous run of entry payloads for ONE group, referencing a
    shared arena buffer: ``offs[k]``/``lens[k]`` locate entry
    ``start + k``'s bytes inside ``buf``.  The universal payload currency
    of the host tier (wire unpack -> adoption staging -> WAL -> cache ->
    wire pack): per-entry bytes objects are materialized only at the few
    consumers that truly need them (state-machine apply, SPI fallbacks).
    Entries are back-to-back in ``buf`` (offs strictly cumulative), so any
    sub-range is itself one contiguous slice — what lets the staging and
    pack paths work per-RUN instead of per-entry."""

    __slots__ = ("start", "buf", "offs", "lens", "end")

    def __init__(self, start: int, buf, offs: np.ndarray, lens: np.ndarray):
        self.start = start          # log index of entry 0
        self.buf = buf              # bytes-like arena
        self.offs = offs            # uint64 [n] absolute offsets into buf
        self.lens = lens            # uint32 [n]
        # Last covered log index, inclusive — precomputed: the run cache's
        # lookup path reads it millions of times per second.
        self.end = start + len(lens) - 1

    def __len__(self) -> int:
        return len(self.lens)

    def piece(self, k0: int, n: int):
        """The single contiguous buffer slice holding entries
        [start+k0, start+k0+n) — valid because entries are back-to-back."""
        a = int(self.offs[k0])
        b = int(self.offs[k0 + n - 1]) + int(self.lens[k0 + n - 1])
        return memoryview(self.buf)[a:b]

    def entry(self, k: int) -> bytes:
        a = int(self.offs[k])
        return bytes(memoryview(self.buf)[a:a + int(self.lens[k])])

    def materialize(self, k0: int = 0, n: int = -1) -> List[bytes]:
        """Per-entry bytes for [k0, k0+n) (n=-1: to the end)."""
        if n < 0:
            n = len(self.lens) - k0
        mv = memoryview(self.buf)
        offs, lens = self.offs, self.lens
        return [bytes(mv[int(offs[k]):int(offs[k]) + int(lens[k])])
                for k in range(k0, k0 + n)]

    @classmethod
    def single(cls, start: int, payload: bytes) -> "PayloadRun":
        """One-entry run (the submit() / cache-backfill shape) — ONE
        definition of the degenerate arena layout."""
        return cls(start, payload, np.zeros(1, np.uint64),
                   np.asarray([len(payload)], np.uint32))

    @classmethod
    def from_payloads(cls, start: int, payloads) -> "PayloadRun":
        """Build an arena run from a list of bytes (client submission
        path): one join + two vector ops, no per-entry records."""
        n = len(payloads)
        lens = np.fromiter(map(len, payloads), np.uint32, n)
        offs = np.zeros(n, np.uint64)
        if n > 1:
            np.cumsum(lens[:-1], dtype=np.uint64, out=offs[1:])
        return cls(start, b"".join(payloads), offs, lens)

# Message kinds -> (valid flag field, data fields).  Field order is the wire
# order; dtypes/shapes come from the Messages template at pack/unpack time.
KIND_FIELDS: Dict[str, Tuple[str, Tuple[str, ...]]] = {
    "ae": ("ae_valid", ("ae_term", "ae_prev_idx", "ae_prev_term",
                        "ae_commit", "ae_n", "ae_ents", "ae_cents",
                        "ae_occ", "ae_tick")),
    "aer": ("aer_valid", ("aer_term", "aer_success", "aer_match",
                          "aer_empty", "aer_occ", "aer_tick")),
    "rv": ("rv_valid", ("rv_term", "rv_last_idx", "rv_last_term",
                        "rv_prevote")),
    "rvr": ("rvr_valid", ("rvr_term", "rvr_granted", "rvr_prevote",
                          "rvr_echo")),
    "is": ("is_valid", ("is_term", "is_idx", "is_last_term", "is_probe",
                        "is_conf")),
    "isr": ("isr_valid", ("isr_term", "isr_success", "isr_probe")),
    # TimeoutNow (§3.10 leadership transfer).
    "tn": ("tn_valid", ("tn_term",)),
}
KIND_IDS = {k: i for i, k in enumerate(KIND_FIELDS)}
KIND_BY_ID = {i: k for k, i in KIND_IDS.items()}


def frame(ftype: int, body: bytes) -> bytes:
    if len(body) > MAX_BODY:
        raise IOError(f"frame body {len(body)} exceeds MAX_BODY {MAX_BODY}")
    return _HDR.pack(MAGIC, ftype, len(body), zlib.crc32(body)) + body


class FrameReader:
    """Incremental frame decoder over a byte stream (the stateful analog of
    the reference's FrameDecoder, transport/EventCodec.java:219-335)."""

    def __init__(self):
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[Tuple[int, bytes]]:
        self._buf += data
        out = []
        while True:
            if len(self._buf) < _HDR.size:
                break
            magic, ftype, blen, crc = _HDR.unpack_from(self._buf, 0)
            if magic != MAGIC or blen > MAX_BODY:
                raise IOError(f"bad frame header (magic={magic:#x})")
            if len(self._buf) < _HDR.size + blen:
                break
            body = bytes(self._buf[_HDR.size:_HDR.size + blen])
            if zlib.crc32(body) != crc:
                raise IOError("frame CRC mismatch")
            del self._buf[:_HDR.size + blen]
            out.append((ftype, body))
        return out



def peek_frame(buf) -> Optional[Tuple[int, bytes, int]]:
    """Decode exactly ONE frame from the head of ``buf``: returns
    (ftype, body, bytes_consumed), or None if the frame is still
    incomplete.  For streams that switch to transparent (raw) mode after
    a known frame — the snapshot channel after SNAP_HDR — where a greedy
    FrameReader would misparse the raw bytes that rode along in the same
    recv (the reference decoder makes the same one-frame-then-raw switch,
    EventCodec.java:282-290)."""
    if len(buf) < _HDR.size:
        return None
    magic, ftype, blen, crc = _HDR.unpack_from(buf, 0)
    if magic != MAGIC or blen > MAX_BODY:
        raise IOError(f"bad frame header (magic={magic:#x})")
    if len(buf) < _HDR.size + blen:
        return None
    body = bytes(buf[_HDR.size:_HDR.size + blen])
    if zlib.crc32(body) != crc:
        raise IOError("frame CRC mismatch")
    return ftype, body, _HDR.size + blen


def _schema_tag() -> int:
    """CRC of the per-kind field tables: two peers agree on the MSGS wire
    layout iff their tags match.  Carried in HELLO so a field-list change
    (e.g. aer_empty / is_probe) rejects a mixed-version peer with ONE
    clear log line instead of presenting as endless opaque connection
    drops when the misaligned columns fail the body bounds checks."""
    desc = ";".join(f"{k}:{v}:{','.join(d)}"
                    for k, (v, d) in KIND_FIELDS.items())
    return zlib.crc32(desc.encode())


SCHEMA_TAG = _schema_tag()


def pack_hello(node_id: int, G: int, P: int, B: int) -> bytes:
    return frame(HELLO, struct.pack("<IIIII", node_id, G, P, B, SCHEMA_TAG))


def unpack_hello(body: bytes) -> Tuple[int, int, int, int, int]:
    """Returns (node_id, G, P, B, schema_tag); a legacy 16-byte HELLO
    (no tag) yields tag 0, which never matches a real CRC."""
    if len(body) == 16:
        return struct.unpack("<IIII", body) + (0,)
    return struct.unpack("<IIIII", body)


# HOPS bodies: header (direction, origin node id, record count), then
# fixed-size records.  Requests carry the span's wire identity and the
# leader's send stamp (echoed back verbatim so the leader never needs a
# lookup to interpret an echo); echoes carry the follower's OWN-clock
# durations from frame arrival — receive->staged, receive->fsynced, and
# receive->echo-send (the residence the leader subtracts from its rtt
# for the clock-skew-free one-way estimate).
_HOPS_HDR = struct.Struct("<BBH")      # direction, origin, count
_HOP_REQ = struct.Struct("<IIiq")      # hop_id, group, idx, t_send_ns
_HOP_ECHO = struct.Struct("<Iqqqq")    # hop_id, t_send_ns, d_staged_ns,
#                                        d_fsync_ns, d_echo_ns
_HOPS_MAX = 0xFFFF


def pack_hops(direction: int, origin: int, records) -> bytes:
    """One HOPS frame.  ``records`` are request tuples
    ``(hop_id, group, idx, t_send_ns)`` when ``direction`` is
    HOP_REQUEST (0), echo tuples ``(hop_id, t_send_ns, d_staged_ns,
    d_fsync_ns, d_echo_ns)`` when HOP_ECHO (1)."""
    n = len(records)
    if n > _HOPS_MAX:
        records = records[:_HOPS_MAX]
        n = _HOPS_MAX
    rec = _HOP_REQ if direction == 0 else _HOP_ECHO
    return frame(HOPS, _HOPS_HDR.pack(direction, origin, n)
                 + b"".join(rec.pack(*r) for r in records))


def unpack_hops(body: bytes):
    """Returns ``(direction, origin, [record tuples])``; malformed
    bodies raise IOError like every other frame (reader treats it as a
    connection drop)."""
    if len(body) < _HOPS_HDR.size:
        raise IOError("truncated HOPS body")
    direction, origin, n = _HOPS_HDR.unpack_from(body, 0)
    rec = _HOP_REQ if direction == 0 else _HOP_ECHO
    if len(body) != _HOPS_HDR.size + n * rec.size:
        raise IOError("truncated HOPS body (malformed frame)")
    return direction, origin, [
        rec.unpack_from(body, _HOPS_HDR.size + i * rec.size)
        for i in range(n)]


def pack_snap_req(group: int, index: int, term: int) -> bytes:
    return frame(SNAP_REQ, struct.pack("<IQq", group, index, term))


def unpack_snap_req(body: bytes) -> Tuple[int, int, int]:
    return struct.unpack("<IQq", body)


def pack_fwd_req(group: int, payload: bytes,
                 timeout_s: float = 30.0, ftype: int = FWD_REQ) -> bytes:
    """Client-command forward: a follower relays a submission to the leader
    (the transport-level analog of the reference's NotLeader redirect hint,
    support/anomaly/NotLeaderException.java:11-27, resolved inside the
    cluster instead of bounced to the client).  The client's wait budget
    travels with the request so the serving side honors it.  ``ftype``
    FWD_READ carries a linearizable read instead (same body layout)."""
    tmo_ms = max(1, min(int(timeout_s * 1000), 0xFFFFFFFF))
    return frame(ftype, struct.pack("<II", group, tmo_ms) + payload)


def unpack_fwd_req(body: bytes) -> Tuple[int, float, bytes]:
    group, tmo_ms = struct.unpack_from("<II", body, 0)
    return group, tmo_ms / 1000.0, body[8:]


def pack_fwd_conf(group: int, op: int, a: int, b: int,
                  timeout_s: float = 30.0) -> bytes:
    """Membership-op forward frame (see FWD_CONF): ``op`` CONF_OP_CHANGE
    carries (voters, learners) masks in (a, b); CONF_OP_TRANSFER carries
    (target, 0)."""
    tmo_ms = max(1, min(int(timeout_s * 1000), 0xFFFFFFFF))
    return frame(FWD_CONF, struct.pack("<IBIII", group, op, tmo_ms, a, b))


def unpack_fwd_conf(body: bytes) -> Tuple[int, int, float, int, int]:
    group, op, tmo_ms, a, b = struct.unpack("<IBIII", body)
    return group, op, tmo_ms / 1000.0, a, b


def serve_conf(node, group: int, op: int, a: int, b: int,
               timeout_s: float) -> Tuple[bool, bytes]:
    """Shared serve-side contract for FWD_CONF (TCP and loopback): run
    the membership op on the local node and report the JSON-encoded
    result, with the same REFUSED/FAILED wire taxonomy as
    :func:`serve_forward` (a marked refusal provably never entered the
    log and is retry-safe)."""
    import json as _json

    from ..api.anomaly import is_refusal
    if node is None:
        return False, b"FAILED:forwarding disabled"
    try:
        if op == CONF_OP_CHANGE:
            fut = node.change_membership(group, a, b)
        elif op == CONF_OP_TRANSFER:
            fut = node.transfer_leadership(group, a)
        else:
            return False, f"FAILED:unknown membership op {op}".encode()
        return True, _json.dumps(fut.result(timeout=timeout_s)).encode()
    except Exception as e:
        tag = "REFUSED" if is_refusal(e) else "FAILED"
        return False, f"{tag}:{type(e).__name__}: {e}".encode()


def pack_fwd_resp(ok: bool, result: bytes) -> bytes:
    return frame(FWD_RESP, struct.pack("<B", 1 if ok else 0) + result)


def unpack_fwd_resp(body: bytes) -> Tuple[bool, bytes]:
    return bool(body[0]), body[1:]


def serve_forward(submit_handler: Optional[Callable], group: int,
                  payload: bytes, timeout_s: float,
                  encode_result: Optional[Callable] = None
                  ) -> Tuple[bool, bytes]:
    """Shared serve-side forward contract (TCP and loopback): run the
    submission, encode the apply result via the node's CmdSerializer
    (api/serial.py; default JSON).

    Error wire format: ``REFUSED:TypeName: msg`` when the error is a
    MARKED pre-log refusal (api/anomaly.py as_refusal — set only at the
    creation sites that provably never enqueued the command), so the
    client may safely retry it elsewhere; ``FAILED:TypeName: msg`` for
    anything else (abort on step-down of an accepted command, apply
    timeout, ...) where the command MAY still commit cluster-wide and a
    retry could double-apply.  Neither the exception TYPE (a step-down
    abort also raises NotLeaderError) nor future-completion TIMING (the
    tick thread can accept AND abort a command between our enqueue and
    our done() check) can carry the distinction — only the marker can."""
    import json as _json

    from ..api.anomaly import is_refusal
    if submit_handler is None:
        return False, b"FAILED:forwarding disabled"
    if encode_result is None:
        encode_result = lambda r: _json.dumps(r).encode()
    try:
        fut = submit_handler(group, payload)
    except Exception as e:
        tag = "REFUSED" if is_refusal(e) else "FAILED"
        return False, f"{tag}:{type(e).__name__}: {e}".encode()
    try:
        return True, encode_result(fut.result(timeout=timeout_s))
    except Exception as e:
        tag = "REFUSED" if is_refusal(e) else "FAILED"
        return False, f"{tag}:{type(e).__name__}: {e}".encode()


def pack_snap_hdr(group: int, index: int, term: int, ok: bool,
                  total_len: int) -> bytes:
    return frame(SNAP_HDR,
                 struct.pack("<IQqBQ", group, index, term,
                             1 if ok else 0, total_len))


def unpack_snap_hdr(body: bytes) -> Tuple[int, int, int, bool, int]:
    group, index, term, ok, total_len = struct.unpack("<IQqBQ", body)
    return group, index, term, bool(ok), total_len




# Kinds a leader may release BEFORE its tick's fsync in pipelined mode:
# AppendEntries (incl. heartbeats) only.  Safe because the commit rule
# counts a leader's own match at min(log.last, durable_tail) (core/step.py
# HostInbox.durable_tail clamp) — an un-fsynced local range can never be
# counted toward a majority.  Everything vote- or ack-bearing (rv/rvr, aer,
# is/isr, tn) reflects state that must be durable before it is announced
# and stays strictly behind the fsync barrier.
EAGER_KINDS = ("ae",)

_EMPTY_COLS = np.zeros(0, np.uint32)


def pack_kind_section(kind: str, fields: Dict[str, np.ndarray],
                      payload_window_fn: Optional[Callable[[int, int, int],
                                                           list]] = None,
                      payload_runs_fn: Optional[Callable] = None,
                      cols: Optional[np.ndarray] = None,
                      payload_blob_fn: Optional[Callable] = None
                      ) -> Tuple[bytes, int, np.ndarray]:
    """Pack ONE kind's wire section (the ``<BI>`` kind header + columns +
    field planes [+ ae payload blob]) for the given column ids.

    ``cols`` defaults to every valid column; a striped packer passes its
    own group subset so each stripe packs independently and the per-peer
    sections concatenate via :func:`assemble_slice` (``unpack_slice``
    accumulates repeated kinds).  Returns ``(section, n_cols, dropped)``:
    ``dropped`` lists the ``ae`` columns whose payloads were unavailable —
    an eager (pre-persist) packer defers them to the host phase, where the
    entries are staged; the serial pack path treats a drop as network loss
    (the engine's resend/timeout recovers).  Other kinds never drop.

    ``payload_blob_fn(cols, starts, ns) -> Optional[(ok_mask, blob)]``:
    the native host tier's bulk blob builder — when it returns a result,
    the whole per-column Python resolution loop is skipped and ``blob``
    (byte-identical layout: kept columns' u32 length words, then their
    payloads) lands in the section directly; columns with ``ok`` False
    are dropped/deferred exactly like a Python-path payload miss.  A
    ``None`` return falls back to the Python loop.
    """
    vfield, dfields = KIND_FIELDS[kind]
    if cols is None:
        cols = np.nonzero(fields[vfield])[0].astype(np.uint32)
    else:
        cols = np.asarray(cols, np.uint32)
    dropped = _EMPTY_COLS
    blob_section = b""
    if kind == "ae" and len(cols):
        # Resolve payloads for indices prev_idx+1 .. prev_idx+n per
        # column FIRST.  Blob layout: one u32 length VECTOR for all kept
        # entries, then the payload bytes concatenated — per-COLUMN bulk
        # ops (run slices when the store exposes runs, else a bytes
        # window), never a struct.pack per entry (the pack path is on the
        # per-tick critical section of every node).
        prevs = fields["ae_prev_idx"][cols]
        ns = fields["ae_n"][cols]
        if payload_blob_fn is not None:
            res = payload_blob_fn(
                cols, prevs.astype(np.int64) + 1, ns.astype(np.uint32))
            if res is not None:
                ok, blob_section = res
                dropped = cols[~ok]
                cols = cols[ok]
                n_cols = len(cols)
                parts = [struct.pack("<BI", KIND_IDS[kind], n_cols)]
                if n_cols:
                    parts.append(cols.tobytes())
                    for f in dfields:
                        parts.append(
                            np.ascontiguousarray(fields[f][cols]).tobytes())
                    parts.append(blob_section)
                return b"".join(parts), n_cols, dropped
        keep, drop, pieces, len_parts = [], [], [], []
        for g, prev, n in zip(cols.tolist(), prevs.tolist(), ns.tolist()):
            if n and payload_runs_fn is not None:
                run = payload_runs_fn(int(g), prev + 1, n)
                if run is None:
                    drop.append(g)
                    continue
                keep.append(g)
                pieces.extend(run[0])
                len_parts.append(np.asarray(run[1], np.uint32))
                continue
            win = (payload_window_fn(int(g), prev + 1, n)
                   if n and payload_window_fn is not None else
                   [None] * n if n else [])
            if any(p is None for p in win):
                drop.append(g)
                continue
            keep.append(g)
            pieces.extend(win)
            len_parts.append(np.fromiter(map(len, win), np.uint32,
                                         len(win)))
        cols = np.asarray(keep, np.uint32)
        dropped = np.asarray(drop, np.uint32)
        lens = (np.concatenate(len_parts) if len_parts
                else np.zeros(0, np.uint32))
        blob_section = lens.tobytes() + b"".join(pieces)
    n_cols = len(cols)
    parts = [struct.pack("<BI", KIND_IDS[kind], n_cols)]
    if n_cols:
        parts.append(cols.tobytes())
        for f in dfields:
            parts.append(np.ascontiguousarray(fields[f][cols]).tobytes())
        parts.append(blob_section)
    return b"".join(parts), n_cols, dropped


def assemble_slice(src: int, sections: List[bytes]) -> bytes:
    """Concatenate independently packed kind sections into ONE MSGS frame.

    One frame per (src, peer) per tick is a delivery invariant: the inbox
    accumulator drains one slice per source per tick, so per-stripe or
    eager/deferred sections must merge here rather than travel as separate
    frames (which would add a tick of latency each and grow the backlog).
    Sections may repeat a kind — ``unpack_slice`` concatenates them, and
    the dense scatter is last-wins in section order for any duplicated
    (kind, group) lane."""
    if len(sections) > 255:
        raise IOError(f"too many MSGS sections ({len(sections)})")
    return frame(MSGS,
                 struct.pack("<IB", src, len(sections)) + b"".join(sections))


def pack_slice(src: int, fields: Dict[str, np.ndarray],
               payload_fn: Optional[Callable[[int, int], Optional[bytes]]],
               payload_window_fn: Optional[Callable[[int, int, int], list]]
               = None,
               payload_runs_fn: Optional[Callable] = None) -> Optional[bytes]:
    """Pack one destination's tick slice into a MSGS frame body.

    ``fields`` maps Messages field name -> numpy array of shape [G] or
    [G, B] (this destination's slice of the outbox).  ``payload_fn(g, idx)``
    supplies AppendEntries command payloads (LogStore.payload);
    ``payload_window_fn(g, start, n) -> [bytes|None]`` is the batched
    variant (LogStore.payloads_window) used when provided — one call per
    column instead of one per entry.  ``payload_runs_fn(g, start, n) ->
    (pieces, lens) | None`` is the zero-copy variant (LogStore.
    payload_runs): contiguous buffer slices + a uint32 length vector, no
    per-entry Python at all — preferred when available.  Returns None when
    the slice is empty (nothing valid for this peer).  An ``ae`` column
    whose payload is unavailable (e.g. compacted between outbox build and
    pack) is dropped entirely — indistinguishable from network loss, which
    the engine's resend/timeout path already recovers; shipping a
    substitute empty command would silently diverge replica state.
    """
    if payload_window_fn is None and payload_fn is not None:
        # One resolution path: adapt the per-entry fetcher so the packing
        # logic (incl. column-drop-on-missing) has a single implementation
        # exercised by every caller and test.
        payload_window_fn = (lambda g, start, n:
                             [payload_fn(g, i)
                              for i in range(start, start + n)])
    sections: List[bytes] = []
    n_total = 0
    for kind in KIND_FIELDS:
        sec, n_cols, _dropped = pack_kind_section(
            kind, fields, payload_window_fn, payload_runs_fn)
        sections.append(sec)
        n_total += n_cols
    if n_total == 0:
        return None
    return assemble_slice(src, sections)


def unpack_slice(body: bytes, template: Dict[str, Tuple[np.dtype, tuple]],
                 n_groups: Optional[int] = None
                 ) -> Tuple[int, Dict[str, Tuple[np.ndarray, np.ndarray]],
                            Dict[int, "PayloadRun"]]:
    """Unpack a MSGS body.

    ``template`` maps field name -> (dtype, per-group trailing shape), e.g.
    ae_ents -> (int32, (B,)).  Returns (src, {field: (cols, values)},
    {group: PayloadRun}) — payloads as one contiguous arena RUN per group
    (an AE column is always a contiguous index range) referencing the
    frame body directly: offsets + lengths, ZERO per-entry bytes objects.
    The adoption path slices the run's numpy vectors; per-entry bytes are
    materialized only where a consumer truly needs them (PayloadRun.
    materialize).  ``n_groups`` bounds-checks column ids so a corrupt or
    shape-mismatched frame can't scatter out of range.

    A kind may appear in SEVERAL sections (striped packers and the
    eager/deferred AE split each contribute one per frame —
    :func:`assemble_slice`): their columns CONCATENATE in section order,
    so the consumer's dense scatter is last-wins for a duplicated
    (kind, group) lane, and a later section's payload run replaces an
    earlier one for the same group.
    """
    end = len(body)

    def need(n: int, off: int) -> None:
        # A CRC-valid but semantically malformed frame (buggy or hostile
        # peer) must fail as a clean IOError — the reader treats it as a
        # connection drop — never as silent truncation or a stray
        # struct.error that kills the reader thread.
        if off + n > end:
            raise IOError("truncated MSGS body (malformed frame)")

    need(struct.calcsize("<IB"), 0)
    src, n_kinds = struct.unpack_from("<IB", body, 0)
    off = struct.calcsize("<IB")
    # field -> list of (cols, vals) parts, one per section carrying it;
    # concatenated at the end (the single-section case stays zero-copy).
    acc: Dict[str, List[Tuple[np.ndarray, np.ndarray]]] = {}
    payloads: Dict[int, PayloadRun] = {}
    for _ in range(n_kinds):
        need(struct.calcsize("<BI"), off)
        kid, n_cols = struct.unpack_from("<BI", body, off)
        off += struct.calcsize("<BI")
        if kid not in KIND_BY_ID:
            raise IOError(f"unknown message kind id {kid}")
        kind = KIND_BY_ID[kid]
        vfield, dfields = KIND_FIELDS[kind]
        if n_cols == 0:
            continue
        need(4 * n_cols, off)
        cols = np.frombuffer(body, np.uint32, n_cols, off).astype(np.int64)
        if n_groups is not None and cols.size and int(cols.max()) >= n_groups:
            raise IOError("column id out of range (shape mismatch?)")
        off += 4 * n_cols
        acc.setdefault(vfield, []).append((cols, np.ones(n_cols, bool)))
        sec_vals: Dict[str, np.ndarray] = {}
        for f in dfields:
            dt, trail = template[f]
            count = n_cols * int(np.prod(trail, dtype=np.int64)) \
                if trail else n_cols
            need(count * np.dtype(dt).itemsize, off)
            vals = np.frombuffer(body, dt, count, off).reshape(
                (n_cols,) + trail)
            off += vals.nbytes
            sec_vals[f] = vals
            acc.setdefault(f, []).append((cols, vals))
        if kind == "ae":
            prevs = sec_vals["ae_prev_idx"]
            ns = sec_vals["ae_n"].astype(np.int64)
            total = int(ns.sum())
            need(4 * total, off)
            lens = np.frombuffer(body, np.uint32, total, off)
            off += 4 * total
            ends = np.cumsum(lens, dtype=np.uint64)
            need(int(ends[-1]) if total else 0, off)
            starts = (ends - lens) + np.uint64(off)
            k = 0
            for g, prev, n in zip(cols.tolist(), prevs.tolist(), ns.tolist()):
                n = int(n)
                if n:
                    # One run per group: numpy slices into the shared body
                    # buffer — no per-entry bytes objects on the unpack
                    # path (they were ~5% of the durable tick at 32k).
                    payloads[int(g)] = PayloadRun(
                        int(prev) + 1, body, starts[k:k + n], lens[k:k + n])
                    k += n
            off += int(ends[-1]) if total else 0
    out: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
    for f, parts in acc.items():
        if len(parts) == 1:
            out[f] = parts[0]
        else:
            out[f] = (np.concatenate([p[0] for p in parts]),
                      np.concatenate([p[1] for p in parts]))
    return src, out, payloads


def messages_template(cfg) -> Dict[str, Tuple[np.dtype, tuple]]:
    """Field -> (dtype, trailing shape beyond [P, G]) from a Messages.empty."""
    from ..core.types import Messages

    m = Messages.empty(cfg)
    out = {}
    for name in dir(m):
        if name.startswith("_"):
            continue
        v = getattr(m, name)
        if hasattr(v, "shape") and hasattr(v, "dtype"):
            out[name] = (np.dtype(v.dtype), tuple(v.shape[2:]))
    return out
