"""In-process loopback transport: N nodes, zero sockets.

The generalization of the reference's loopback test trick (it connects the
EventBus to itself, transport/EventClusterTest.java:81-83): a
``LoopbackNetwork`` wires N transports directly accumulator-to-accumulator,
with per-link drop control for partition/chaos testing.  Same interface as
TcpTransport, so the node runtime is transport-agnostic.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Tuple

from . import codec


class LoopbackNetwork:
    def __init__(self, n_nodes: int):
        self.n = n_nodes
        self.transports: Dict[int, "LoopbackTransport"] = {}
        self._lock = threading.Lock()
        # conn[s][d] False = link cut
        self.conn = [[True] * n_nodes for _ in range(n_nodes)]
        # dup[s][d] True = every MSGS frame over s->d is delivered twice
        # (nemesis duplicate-delivery regime: the host-path analog of
        # FaultSchedule.dup, exercising stale/duplicate RPC idempotency
        # through the real codec round-trip)
        self.dup = [[False] * n_nodes for _ in range(n_nodes)]

    def set_link(self, src: int, dst: int, up: bool) -> None:
        with self._lock:
            self.conn[src][dst] = up

    def set_conn(self, conn) -> None:
        """Adopt a whole [N, N] connectivity matrix at once — the bulk
        entry point nemesis schedule replay drives per tick
        (testkit/harness.py ``LocalCluster.replay_schedule``)."""
        with self._lock:
            for s in range(self.n):
                for d in range(self.n):
                    self.conn[s][d] = bool(conn[s][d])

    def set_dup(self, dup) -> None:
        """Adopt a whole [N, N] duplicate-delivery matrix."""
        with self._lock:
            for s in range(self.n):
                for d in range(self.n):
                    self.dup[s][d] = bool(dup[s][d])

    def partition(self, sides) -> None:
        with self._lock:
            for s in range(self.n):
                for d in range(self.n):
                    self.conn[s][d] = any(
                        s in side and d in side for side in sides)

    def heal(self) -> None:
        with self._lock:
            for s in range(self.n):
                for d in range(self.n):
                    self.conn[s][d] = True

    def _up(self, s: int, d: int) -> bool:
        with self._lock:
            return self.conn[s][d]

    def _dup(self, s: int, d: int) -> bool:
        with self._lock:
            return self.dup[s][d]


class LoopbackTransport:
    def __init__(self, network: LoopbackNetwork, node_id: int, cfg, template,
                 on_slice: Callable,
                 snapshot_provider: Optional[Callable] = None,
                 submit_handler: Optional[Callable] = None,
                 result_encoder: Optional[Callable] = None,
                 read_handler: Optional[Callable] = None,
                 conf_node=None):
        self.net = network
        self.node_id = node_id
        self.cfg = cfg
        self.template = template
        self.on_slice = on_slice
        self.snapshot_provider = snapshot_provider
        self.submit_handler = submit_handler
        self.result_encoder = result_encoder
        self.read_handler = read_handler
        self.conf_node = conf_node

    def start(self) -> None:
        self.net.transports[self.node_id] = self

    def close(self) -> None:
        self.net.transports.pop(self.node_id, None)

    def send_slice(self, dst: int, packed: bytes) -> None:
        """Deliver a packed MSGS frame to dst (round-trips through the real
        codec so loopback tests exercise the wire format too)."""
        if not self.net._up(self.node_id, dst):
            return
        t = self.net.transports.get(dst)
        if t is None:
            return  # peer down
        # Duplicate-delivery links (nemesis schedule replay) hand the same
        # frame to the receiver twice — the receiving stack must be
        # idempotent against replayed RPCs, exactly like the device
        # plane's FaultSchedule.dup lane.
        rounds = 2 if self.net._dup(self.node_id, dst) else 1
        for _ in range(rounds):
            ftype_body = codec.FrameReader().feed(packed)
            for ftype, body in ftype_body:
                if ftype == codec.MSGS:
                    src, fields, payloads = codec.unpack_slice(
                        body, t.template, t.cfg.n_groups)
                    t.on_slice(src, fields, payloads)

    def forward_submit(self, peer: int, group: int, payload: bytes,
                       timeout: float = 30.0):
        if not (self.net._up(self.node_id, peer)
                and self.net._up(peer, self.node_id)):
            return False, b"link down"
        t = self.net.transports.get(peer)
        if t is None:
            return False, b"peer down"
        return codec.serve_forward(t.submit_handler, group, payload, timeout,
                                   t.result_encoder)

    def forward_read(self, peer: int, group: int, payload: bytes,
                     timeout: float = 30.0):
        """Relay a linearizable read to the leader (the loopback analog of
        TcpTransport.forward_read — serve side routes to RaftNode.read)."""
        if not (self.net._up(self.node_id, peer)
                and self.net._up(peer, self.node_id)):
            return False, b"link down"
        t = self.net.transports.get(peer)
        if t is None:
            return False, b"peer down"
        return codec.serve_forward(t.read_handler, group, payload, timeout,
                                   t.result_encoder)

    def forward_conf(self, peer: int, group: int, op: int, a: int, b: int,
                     timeout: float = 30.0):
        """Relay a membership op (§6 change / leadership transfer) to the
        leader — the loopback analog of TcpTransport.forward_conf."""
        if not (self.net._up(self.node_id, peer)
                and self.net._up(peer, self.node_id)):
            return False, b"link down"
        t = self.net.transports.get(peer)
        if t is None:
            return False, b"peer down"
        return codec.serve_conf(t.conf_node, group, op, a, b, timeout)

    def fetch_snapshot(self, peer: int, group: int, index: int, term: int,
                       dest_path: str, timeout: float = 60.0
                       ) -> Optional[Tuple[int, int]]:
        """File-to-file snapshot copy (the loopback analog of the TCP
        chunk stream): bytes never accumulate in memory."""
        if not self.net._up(self.node_id, peer) or \
                not self.net._up(peer, self.node_id):
            return None
        t = self.net.transports.get(peer)
        if t is None or t.snapshot_provider is None:
            return None
        res = t.snapshot_provider(group, index, term)
        if res is None:
            return None
        idx, tm, path = res
        try:
            import shutil
            shutil.copyfile(path, dest_path)
        except OSError:
            return None
        return idx, tm
