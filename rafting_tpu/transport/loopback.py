"""In-process loopback transport: N nodes, zero sockets.

The generalization of the reference's loopback test trick (it connects the
EventBus to itself, transport/EventClusterTest.java:81-83): a
``LoopbackNetwork`` wires N transports directly accumulator-to-accumulator,
with per-link drop control for partition/chaos testing.  Same interface as
TcpTransport, so the node runtime is transport-agnostic.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from . import codec


class LoopbackNetwork:
    def __init__(self, n_nodes: int):
        self.n = n_nodes
        self.transports: Dict[int, "LoopbackTransport"] = {}
        self._lock = threading.Lock()
        # conn[s][d] False = link cut
        self.conn = [[True] * n_nodes for _ in range(n_nodes)]
        # dup[s][d] True = every MSGS frame over s->d is delivered twice
        # (nemesis duplicate-delivery regime: the host-path analog of
        # FaultSchedule.dup, exercising stale/duplicate RPC idempotency
        # through the real codec round-trip)
        self.dup = [[False] * n_nodes for _ in range(n_nodes)]
        # Optional shared LinkFaults table (transport/faults.py) — the
        # chaos conductor's richer per-directed-link plane (asymmetric
        # cuts, probabilistic drop/dup/delay/reorder), consulted in
        # ADDITION to the legacy conn/dup matrices above.
        self.faults = None
        # Frames a delay/reorder verdict held back, per directed link:
        # (frame, after) — after=False is a delayed frame (delivered
        # BEFORE the link's next frame: a one-frame time shift, order
        # kept), after=True is a reordered one (delivered AFTER the next
        # frame: the adjacent swap).
        self._held: Dict[Tuple[int, int],
                         List[Tuple[bytes, bool]]] = {}

    def _take_held(self, key) -> Tuple[list, list]:
        with self._lock:
            entries = self._held.pop(key, [])
        pre = [fr for fr, after in entries if not after]
        post = [fr for fr, after in entries if after]
        return pre, post

    def _hold(self, key, frame: bytes, after: bool) -> None:
        with self._lock:
            self._held.setdefault(key, []).append((frame, after))

    def flush_held(self) -> None:
        """Deliver every held-back frame now (heal-time drain so a link
        that goes quiet doesn't strand a delayed frame forever)."""
        with self._lock:
            held, self._held = self._held, {}
        for (src, dst), entries in held.items():
            t = self.transports.get(dst)
            if t is None:
                continue
            for frame, _after in entries:
                t._deliver(frame)

    def set_link(self, src: int, dst: int, up: bool) -> None:
        with self._lock:
            self.conn[src][dst] = up

    def set_conn(self, conn) -> None:
        """Adopt a whole [N, N] connectivity matrix at once — the bulk
        entry point nemesis schedule replay drives per tick
        (testkit/harness.py ``LocalCluster.replay_schedule``)."""
        with self._lock:
            for s in range(self.n):
                for d in range(self.n):
                    self.conn[s][d] = bool(conn[s][d])

    def set_dup(self, dup) -> None:
        """Adopt a whole [N, N] duplicate-delivery matrix."""
        with self._lock:
            for s in range(self.n):
                for d in range(self.n):
                    self.dup[s][d] = bool(dup[s][d])

    def partition(self, sides) -> None:
        with self._lock:
            for s in range(self.n):
                for d in range(self.n):
                    self.conn[s][d] = any(
                        s in side and d in side for side in sides)

    def heal(self) -> None:
        with self._lock:
            for s in range(self.n):
                for d in range(self.n):
                    self.conn[s][d] = True

    def _up(self, s: int, d: int) -> bool:
        with self._lock:
            return self.conn[s][d]

    def _dup(self, s: int, d: int) -> bool:
        with self._lock:
            return self.dup[s][d]


class LoopbackTransport:
    def __init__(self, network: LoopbackNetwork, node_id: int, cfg, template,
                 on_slice: Callable,
                 snapshot_provider: Optional[Callable] = None,
                 submit_handler: Optional[Callable] = None,
                 result_encoder: Optional[Callable] = None,
                 read_handler: Optional[Callable] = None,
                 conf_node=None):
        self.net = network
        self.node_id = node_id
        self.cfg = cfg
        self.template = template
        self.on_slice = on_slice
        self.snapshot_provider = snapshot_provider
        self.submit_handler = submit_handler
        self.result_encoder = result_encoder
        self.read_handler = read_handler
        self.conf_node = conf_node

    def start(self) -> None:
        self.net.transports[self.node_id] = self

    def close(self) -> None:
        self.net.transports.pop(self.node_id, None)

    def send_slice(self, dst: int, packed: bytes) -> None:
        """Deliver a packed MSGS frame to dst (round-trips through the real
        codec so loopback tests exercise the wire format too).  When the
        network carries a LinkFaults table, each frame's fate (cut /
        drop / delay / dup / reorder) is decided per directed link; held
        frames ride out with the link's NEXT frame — before it for a
        delay (order kept, time shifted), after it for a reorder (the
        adjacent swap)."""
        if not self.net._up(self.node_id, dst):
            return
        t = self.net.transports.get(dst)
        if t is None:
            return  # peer down
        key = (self.node_id, dst)
        frames = [packed]
        f = self.net.faults
        if f is not None:
            act = f.plan(self.node_id, dst)
            if act.cut:
                self._mirror("net_faults_cut_total")
                return  # link down: held frames stay held too
            pre, post = self.net._take_held(key)
            if not act.deliver:
                self._mirror("net_faults_dropped_total")
                frames = []
            elif act.delay_s > 0:
                self._mirror("net_faults_delayed_total")
                self.net._hold(key, packed, after=False)
                frames = []
            elif act.reorder:
                self._mirror("net_faults_reordered_total")
                self.net._hold(key, packed, after=True)
                frames = []
            elif act.dup:
                self._mirror("net_faults_duplicated_total")
                frames = [packed, packed]
            frames = pre + frames + post
        # Duplicate-delivery links (nemesis schedule replay) hand the same
        # frame to the receiver twice — the receiving stack must be
        # idempotent against replayed RPCs, exactly like the device
        # plane's FaultSchedule.dup lane.
        rounds = 2 if self.net._dup(self.node_id, dst) else 1
        for frame in frames:
            for _ in range(rounds):
                t._deliver(frame)

    def _deliver(self, packed: bytes) -> None:
        """Receiver half: unpack a frame and merge it into our inbox."""
        for ftype, body in codec.FrameReader().feed(packed):
            if ftype == codec.MSGS:
                src, fields, payloads = codec.unpack_slice(
                    body, self.template, self.cfg.n_groups)
                self.on_slice(src, fields, payloads)
            elif ftype == codec.HOPS:
                # Hop-tracing sideband — ``on_hops`` is assigned by the
                # runtime after construction (see TcpTransport); unset
                # means the owner is hop-blind and the frame is ignored.
                handler = getattr(self, "on_hops", None)
                if handler is not None:
                    import time as _time
                    t_recv = _time.perf_counter_ns()
                    direction, origin, records = codec.unpack_hops(body)
                    handler(origin, direction, records, t_recv)

    def _mirror(self, name: str) -> None:
        m = getattr(self, "metrics", None)
        if m is not None:
            try:
                m[name] += 1
            except Exception:
                pass

    def _link_open(self, peer: int) -> bool:
        """Forwards and snapshot fetches are round trips: a cut in either
        direction — legacy conn matrix or LinkFaults table — fails them."""
        if not (self.net._up(self.node_id, peer)
                and self.net._up(peer, self.node_id)):
            return False
        f = self.net.faults
        return f is None or (f.link_up(self.node_id, peer)
                             and f.link_up(peer, self.node_id))

    def forward_submit(self, peer: int, group: int, payload: bytes,
                       timeout: float = 30.0):
        if not self._link_open(peer):
            return False, b"link down"
        t = self.net.transports.get(peer)
        if t is None:
            return False, b"peer down"
        return codec.serve_forward(t.submit_handler, group, payload, timeout,
                                   t.result_encoder)

    def forward_read(self, peer: int, group: int, payload: bytes,
                     timeout: float = 30.0):
        """Relay a linearizable read to the leader (the loopback analog of
        TcpTransport.forward_read — serve side routes to RaftNode.read)."""
        if not self._link_open(peer):
            return False, b"link down"
        t = self.net.transports.get(peer)
        if t is None:
            return False, b"peer down"
        return codec.serve_forward(t.read_handler, group, payload, timeout,
                                   t.result_encoder)

    def forward_conf(self, peer: int, group: int, op: int, a: int, b: int,
                     timeout: float = 30.0):
        """Relay a membership op (§6 change / leadership transfer) to the
        leader — the loopback analog of TcpTransport.forward_conf."""
        if not self._link_open(peer):
            return False, b"link down"
        t = self.net.transports.get(peer)
        if t is None:
            return False, b"peer down"
        return codec.serve_conf(t.conf_node, group, op, a, b, timeout)

    def fetch_snapshot(self, peer: int, group: int, index: int, term: int,
                       dest_path: str, timeout: float = 60.0
                       ) -> Optional[Tuple[int, int]]:
        """File-to-file snapshot copy (the loopback analog of the TCP
        chunk stream): bytes never accumulate in memory."""
        if not self._link_open(peer):
            return None
        t = self.net.transports.get(peer)
        if t is None or t.snapshot_provider is None:
            return None
        res = t.snapshot_provider(group, index, term)
        if res is None:
            return None
        idx, tm, path = res
        try:
            import shutil
            shutil.copyfile(path, dest_path)
        except OSError:
            return None
        return idx, tm
