"""Host transport plane: dense tick-slice RPC between Raft nodes.

Replaces the reference's Netty stack (transport/EventBus.java,
EventNode.java, EventCodec.java, NettyNode.java) with a tick-sliced wire
protocol: everything one node says to one peer in one engine tick travels
as one sparse-packed frame (codec.py), merged at the receiver into the
dense inbox the vectorized engine consumes (inbox.py).  TCP and in-process
loopback backends share the interface (tcp.py, loopback.py)."""

from .codec import messages_template
from .faults import LinkAction, LinkFaults
from .inbox import InboxAccumulator
from .loopback import LoopbackNetwork, LoopbackTransport
from .tcp import TcpTransport

__all__ = [
    "messages_template", "InboxAccumulator",
    "LinkAction", "LinkFaults",
    "LoopbackNetwork", "LoopbackTransport", "TcpTransport",
]
