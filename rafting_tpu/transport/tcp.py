"""TCP transport: the host communication backend between Raft nodes.

Topology mirrors the reference (transport/EventBus.java, EventNode.java):
every node runs one listening server; every node maintains ONE persistent
outbound connection to each peer carrying all groups' consensus traffic
(scope-multiplexing inverted into dense tick slices, see codec.py), with
1-second auto-reconnect (reference EventNode.java:93-94).  Snapshot bulk
transfer uses a separate ephemeral connection per fetch so large state never
head-of-line-blocks consensus frames (reference SnapChannel,
transport/EventNode.java:122-267; zero-copy serve EventBus.java:98-111).

Inbound connections self-identify with their first frame: HELLO = a peer's
persistent message channel (reference handshake upgrade,
EventBus.java:71-97); SNAP_REQ = an ephemeral snapshot fetch.

Send-side queues are bounded and drop-oldest under backpressure: Raft
tolerates loss (resend on timeout), so shedding beats unbounded buffering —
the analog of the reference's busy-loop backpressure hint
(support/EventLoop.java:136-138).
"""

from __future__ import annotations

import logging
import os
import queue
import random
import socket
import struct
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from . import codec

log = logging.getLogger(__name__)

RECONNECT_DELAY = 1.0   # base backoff (reference EventNode.java:93-94)
RECONNECT_MAX = 15.0    # backoff ceiling for a persistently-down peer
SEND_QUEUE_CAP = 1024


class PeerSender:
    """One persistent outbound channel to a peer, with reconnect."""

    def __init__(self, my_id: int, peer_id: int, addr: Tuple[str, int],
                 hello: bytes, metrics=None, faults_get=None):
        """``faults_get()`` (optional) returns the cluster's current
        LinkFaults table or None — a getter, not the table itself, so the
        owning transport can install/replace faults at runtime and every
        sender sees the swap on its next frame."""
        self.my_id = my_id
        self.peer_id = peer_id
        self.addr = addr
        self.hello = hello
        self.metrics = metrics
        self.faults_get = faults_get
        self.q: "queue.Queue[bytes]" = queue.Queue(SEND_QUEUE_CAP)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"raft-send-{my_id}->{peer_id}",
            daemon=True)
        self.connected = False
        self._held: Optional[bytes] = None  # reorder nemesis holdback

    def start(self):
        self._thread.start()

    def send(self, data: Optional[bytes]) -> None:
        if not data:  # empty tick slice: nothing to say
            return
        try:
            self.q.put_nowait(data)
        except queue.Full:
            try:  # drop-oldest: newest consensus state supersedes stale
                self.q.get_nowait()
            except queue.Empty:
                pass
            try:
                self.q.put_nowait(data)
            except queue.Full:
                pass

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)

    def _backoff(self, attempts: int) -> float:
        """Jittered exponential backoff: 1s doubling to the 15s cap, with
        0.5-1.0x jitter so a restarted peer isn't hit by every sender in
        lockstep (a reconnect stampede is itself a storage-adjacent fault
        amplifier: N simultaneous hellos against a node mid-recovery)."""
        base = min(RECONNECT_MAX,
                   RECONNECT_DELAY * (2.0 ** min(attempts - 1, 6)))
        return base * (0.5 + 0.5 * random.random())

    def _count(self, name: str) -> None:
        if self.metrics is not None:
            try:
                self.metrics[name] += 1
            except Exception:  # metrics must never kill the sender
                pass

    def _flush_held(self, sock) -> None:
        """Send a frame the reorder nemesis held back — after the next
        frame (the adjacent swap), or on queue idle so it never starves."""
        if self._held is not None:
            h, self._held = self._held, None
            sock.sendall(h)

    def _faults(self):
        return self.faults_get() if self.faults_get is not None else None

    def _run(self):
        attempts = 0
        while not self._stop.is_set():
            f = self._faults()
            if f is not None and not f.link_up(self.my_id, self.peer_id):
                # Injected partition: behave exactly like an unreachable
                # peer — count a reconnect attempt and climb the backoff
                # ladder, so a flapping partition exercises the same
                # jittered-exponential path a flapping switch would.
                attempts += 1
                self._count("reconnects_total")
                # Full jittered-exponential ladder, but capped at 2s so a
                # healed partition is noticed promptly in bounded tests.
                self._stop.wait(min(2.0, self._backoff(attempts)))
                continue
            sock = None
            try:
                sock = socket.create_connection(self.addr, timeout=5)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                sock.sendall(self.hello)
                self.connected = True
                attempts = 0  # established: next drop restarts the ladder
                while not self._stop.is_set():
                    try:
                        data = self.q.get(timeout=0.5)
                    except queue.Empty:
                        self._flush_held(sock)
                        continue
                    f = self._faults()
                    if f is None:
                        sock.sendall(data)
                        continue
                    act = f.plan(self.my_id, self.peer_id)
                    if act.cut:
                        # Partition dropped mid-connection: sever like a
                        # network failure.  The dequeued frame is lost
                        # (Raft resends on timeout), and so is any held
                        # one — buffered bytes die with the connection.
                        self._count("net_faults_cut_total")
                        raise OSError("injected link cut")
                    if not act.deliver:
                        self._count("net_faults_dropped_total")
                        continue
                    if act.delay_s > 0:
                        self._count("net_faults_delayed_total")
                        self._stop.wait(act.delay_s)
                    if act.reorder and self._held is None:
                        self._count("net_faults_reordered_total")
                        self._held = data
                        continue
                    sock.sendall(data)
                    if act.dup:
                        self._count("net_faults_duplicated_total")
                        sock.sendall(data)
                    self._flush_held(sock)
            except OSError:
                pass
            finally:
                self.connected = False
                self._held = None
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
            if not self._stop.is_set():
                attempts += 1
                self._count("reconnects_total")
                # stop.wait, not sleep: close() shouldn't stall on backoff
                self._stop.wait(self._backoff(attempts))


class TcpTransport:
    """The node's network endpoint.

    ``on_slice(src, fields, payloads)`` is called from reader threads with
    each arriving tick slice (typically InboxAccumulator.merge).
    ``snapshot_provider(group, index, term) -> (index, term, ok, bytes)``
    serves snapshot fetches (None payload -> not available).
    """

    def __init__(self, node_id: int, peers: Dict[int, Tuple[str, int]],
                 cfg, template,
                 on_slice: Callable,
                 snapshot_provider: Optional[Callable] = None,
                 submit_handler: Optional[Callable] = None,
                 result_encoder: Optional[Callable] = None,
                 read_handler: Optional[Callable] = None,
                 conf_node=None, faults=None):
        """``submit_handler(group, payload) -> Future`` serves forwarded
        client commands (None -> forwards are refused).
        ``read_handler(group, payload) -> Future`` serves forwarded
        linearizable reads (RaftNode.read; None -> read forwards refused).
        ``result_encoder(result) -> bytes`` encodes forwarded apply results
        (the node's CmdSerializer, api/serial.py; default JSON).
        ``conf_node`` serves forwarded membership ops (FWD_CONF): any
        object with change_membership/transfer_leadership — normally the
        RaftNode itself (None -> membership forwards refused).
        ``faults``: an optional shared LinkFaults table (transport/
        faults.py) — assignable at runtime (``transport.faults = ...``);
        sender threads read it through a getter so a mid-run swap takes
        effect on the next frame."""
        self.node_id = node_id
        self.faults = faults
        self.peers = peers
        self.cfg = cfg
        self.template = template
        self.on_slice = on_slice
        self.snapshot_provider = snapshot_provider
        self.submit_handler = submit_handler
        self.result_encoder = result_encoder
        self.read_handler = read_handler
        self.conf_node = conf_node
        self._hello = codec.pack_hello(node_id, cfg.n_groups, cfg.n_peers,
                                       cfg.batch)
        self._senders: Dict[int, PeerSender] = {}
        self._server: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._threads = []

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        host, port = self.peers[self.node_id]
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(16)
        srv.settimeout(0.5)
        self._server = srv
        t = threading.Thread(target=self._accept_loop,
                             name=f"raft-accept-{self.node_id}", daemon=True)
        t.start()
        self._threads.append(t)
        for pid, addr in self.peers.items():
            if pid == self.node_id:
                continue
            s = PeerSender(self.node_id, pid, addr, self._hello,
                           metrics=getattr(self, "metrics", None),
                           faults_get=lambda: self.faults)
            s.start()
            self._senders[pid] = s

    def close(self) -> None:
        self._stop.set()
        for s in self._senders.values():
            s.stop()
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5)

    @property
    def bound_port(self) -> int:
        return self._server.getsockname()[1]

    # -- sending -------------------------------------------------------------

    def send_slice(self, dst: int, packed: bytes) -> None:
        self._senders[dst].send(packed)

    def fetch_snapshot(self, peer: int, group: int, index: int, term: int,
                       dest_path: str, timeout: float = 60.0
                       ) -> Optional[Tuple[int, int]]:
        """Ephemeral snapshot fetch (reference SnapChannel,
        transport/EventNode.java:122-267).  After the SNAP_HDR frame the
        stream is TRANSPARENT: exactly ``total_len`` raw file bytes,
        written to ``dest_path`` incrementally — bytes never accumulate
        in memory, nothing is framed or checksummed per chunk (the serve
        side is a zero-copy sendfile), and snapshot size is unbounded by
        MAX_BODY.  Blocking — call from a worker thread.  Returns
        (index, term) or None."""
        if not self._link_open(peer):
            return None
        try:
            with socket.create_connection(self.peers[peer],
                                          timeout=timeout) as sock:
                sock.settimeout(timeout)
                sock.sendall(codec.pack_snap_req(group, index, term))
                # One-frame decode, NOT a greedy FrameReader: the raw
                # stream's head may ride in the same recv as the header
                # and must not be parsed as frames.
                buf = bytearray()
                meta = None          # (idx, term, total_len)
                while meta is None:
                    data = sock.recv(1 << 20)
                    if not data:
                        return None
                    buf += data
                    fr = codec.peek_frame(buf)
                    if fr is None:
                        continue
                    ftype, body, consumed = fr
                    if ftype != codec.SNAP_HDR:
                        return None
                    g, idx, tm, ok, total = codec.unpack_snap_hdr(body)
                    if not ok:
                        return None
                    meta = (idx, tm, total)
                    del buf[:consumed]
                received = 0
                with open(dest_path, "wb") as f:
                    if buf:              # raw bytes that rode along
                        f.write(buf[:meta[2]])
                        received = min(len(buf), meta[2])
                    while received < meta[2]:
                        data = sock.recv(1 << 20)
                        if not data:
                            return None     # short stream: fetch failed
                        f.write(data[:meta[2] - received])
                        received += min(len(data), meta[2] - received)
                return meta[0], meta[1]
        except (OSError, IOError, ValueError, struct.error, KeyError) as e:
            # Malformed frames / unknown peer fail like any transport error.
            log.debug("snapshot fetch from %d failed: %s", peer, e)
            return None

    def _link_open(self, peer: int) -> bool:
        """Ephemeral channels (forward / snapshot fetch) respect injected
        partitions too: a cut in EITHER direction fails the round trip —
        these connections need both the request and the reply to pass."""
        f = self.faults
        return f is None or (f.link_up(self.node_id, peer)
                             and f.link_up(peer, self.node_id))

    # -- inbound -------------------------------------------------------------

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._read_loop, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def _read_loop(self, conn: socket.socket):
        reader = codec.FrameReader()
        src: Optional[int] = None
        conn.settimeout(1.0)
        try:
            while not self._stop.is_set():
                try:
                    data = conn.recv(1 << 20)
                except socket.timeout:
                    continue
                if not data:
                    return
                for ftype, body in reader.feed(data):
                    if ftype == codec.HELLO:
                        nid, G, P, B, tag = codec.unpack_hello(body)
                        if (G, P, B) != (self.cfg.n_groups, self.cfg.n_peers,
                                         self.cfg.batch):
                            log.error("shape mismatch from node %d", nid)
                            return
                        if tag != codec.SCHEMA_TAG:
                            log.error("wire-schema mismatch from node %d "
                                      "(tag %#x != ours %#x) — peer runs a "
                                      "different build", nid, tag,
                                      codec.SCHEMA_TAG)
                            return
                        src = nid
                    elif ftype == codec.MSGS:
                        if src is None:
                            # No handshake yet: refuse to trust the frame's
                            # claimed source (reference validates the channel
                            # identity, EventBus.java:119-147).
                            log.warning("MSGS before HELLO — connection drop")
                            return
                        s, fields, payloads = codec.unpack_slice(
                            body, self.template, self.cfg.n_groups)
                        if s != src:
                            log.warning("frame src %d != channel src %d — "
                                        "dropped", s, src)
                            continue  # source spoof guard
                        self.on_slice(s, fields, payloads)
                    elif ftype == codec.HOPS:
                        # Hop-tracing sideband (utils/latency.py): rides
                        # the persistent channel, so the HELLO identity
                        # guards it exactly like MSGS.  ``on_hops`` is
                        # assigned by the runtime after construction
                        # (same pattern as ``transport.metrics``); a
                        # hop-blind owner leaves it unset and the frame
                        # is ignored.
                        handler = getattr(self, "on_hops", None)
                        if handler is None or src is None:
                            continue
                        t_recv = time.perf_counter_ns()
                        direction, origin, records = codec.unpack_hops(body)
                        if origin != src:
                            log.warning("HOPS origin %d != channel src %d "
                                        "— dropped", origin, src)
                            continue
                        handler(origin, direction, records, t_recv)
                    elif ftype == codec.SNAP_REQ:
                        self._serve_snapshot(conn, body)
                        return  # ephemeral connection: one fetch, then close
                    elif ftype == codec.FWD_REQ:
                        self._serve_forward(conn, body)
                        return  # ephemeral: one command, then close
                    elif ftype == codec.FWD_READ:
                        self._serve_forward(conn, body, read=True)
                        return  # ephemeral: one read, then close
                    elif ftype == codec.FWD_CONF:
                        group, op, tmo, a, b = codec.unpack_fwd_conf(body)
                        ok, res = codec.serve_conf(self.conf_node, group,
                                                   op, a, b, tmo)
                        conn.sendall(codec.pack_fwd_resp(ok, res))
                        return  # ephemeral: one membership op, then close
        except (OSError, IOError, ValueError, struct.error):
            # Malformed frames (struct/ValueError from a buggy or hostile
            # peer) end the connection cleanly, same as transport errors.
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def forward_submit(self, peer: int, group: int, payload: bytes,
                       timeout: float = 30.0
                       ) -> Tuple[bool, bytes]:
        """Relay a client command to ``peer`` and wait for the apply result
        (JSON bytes).  Blocking — call from a worker/client thread."""
        return self._forward(peer, group, payload, timeout, codec.FWD_REQ)

    def forward_read(self, peer: int, group: int, payload: bytes,
                     timeout: float = 30.0) -> Tuple[bool, bytes]:
        """Relay a linearizable read to ``peer`` (the leader) and wait for
        the query result — the read-plane sibling of forward_submit."""
        return self._forward(peer, group, payload, timeout, codec.FWD_READ)

    def forward_conf(self, peer: int, group: int, op: int, a: int, b: int,
                     timeout: float = 30.0) -> Tuple[bool, bytes]:
        """Relay a membership op (§6 change / leadership transfer) to
        ``peer`` over an ephemeral FWD_CONF connection."""
        if not self._link_open(peer):
            return False, b"link cut (fault injection)"
        try:
            with socket.create_connection(self.peers[peer],
                                          timeout=timeout) as sock:
                sock.settimeout(timeout + 1.0)
                sock.sendall(codec.pack_fwd_conf(group, op, a, b, timeout))
                reader = codec.FrameReader()
                while True:
                    data = sock.recv(1 << 20)
                    if not data:
                        return False, b"connection closed"
                    for ftype_r, body in reader.feed(data):
                        if ftype_r == codec.FWD_RESP:
                            return codec.unpack_fwd_resp(body)
        except OSError as e:
            return False, str(e).encode()

    def _forward(self, peer: int, group: int, payload: bytes,
                 timeout: float, ftype: int) -> Tuple[bool, bytes]:
        if not self._link_open(peer):
            return False, b"link cut (fault injection)"
        try:
            with socket.create_connection(self.peers[peer],
                                          timeout=timeout) as sock:
                sock.settimeout(timeout + 1.0)  # serve side bounds the wait
                sock.sendall(codec.pack_fwd_req(group, payload, timeout,
                                                ftype))
                reader = codec.FrameReader()
                while True:
                    data = sock.recv(1 << 20)
                    if not data:
                        return False, b"connection closed"
                    for ftype_r, body in reader.feed(data):
                        if ftype_r == codec.FWD_RESP:
                            return codec.unpack_fwd_resp(body)
        except OSError as e:
            return False, str(e).encode()

    def _serve_forward(self, conn: socket.socket, body: bytes,
                       read: bool = False):
        group, timeout_s, payload = codec.unpack_fwd_req(body)
        handler = self.read_handler if read else self.submit_handler
        ok, res = codec.serve_forward(handler, group, payload,
                                      timeout_s, self.result_encoder)
        conn.sendall(codec.pack_fwd_resp(ok, res))

    def _serve_snapshot(self, conn: socket.socket, body: bytes):
        """Serve our snapshot file zero-copy (reference DefaultFileRegion
        sendfile, transport/EventBus.java:98-111): a CRC-framed SNAP_HDR,
        then the raw file bytes via ``socket.sendfile`` — the kernel moves
        pages straight from the file cache to the socket, so a laggard
        catch-up storm at 100k groups never pays a per-byte Python copy on
        the tick-adjacent host (falls back to plain send() internally on
        platforms without os.sendfile)."""
        group, index, term = codec.unpack_snap_req(body)
        # The read loop's 1s poll timeout is wrong for a bulk send: a >1s
        # receiver stall would abort the stream mid-transfer.  Give the
        # serve its own generous deadline.
        conn.settimeout(60.0)
        res = (self.snapshot_provider(group, index, term)
               if self.snapshot_provider is not None else None)
        if res is None:
            conn.sendall(codec.pack_snap_hdr(group, index, term, False, 0))
            return
        idx, tm, path = res
        try:
            total = os.path.getsize(path)
            with open(path, "rb") as f:
                conn.sendall(codec.pack_snap_hdr(group, idx, tm, True, total))
                sent = 0
                while sent < total:
                    n = conn.sendfile(f, offset=sent, count=total - sent)
                    if not n:
                        break   # file truncated under us: short stream,
                                # client's byte count check re-requests
                    sent += n
        except OSError:
            # File vanished (e.g. retention rotated it): the client's
            # byte-count check fails and it re-requests.
            log.debug("snapshot serve failed g=%d", group)
