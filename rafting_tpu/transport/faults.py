"""Transport-level fault injection: the chaos plane's network nemesis.

One :class:`LinkFaults` instance is the shared fault table for a whole
cluster — every transport (TCP sender threads, loopback send paths)
consults it per frame, per DIRECTED link, so asymmetric partitions
(A->B dead, B->A alive) fall out of the representation instead of being
a special case.  Faults are runtime-togglable: a partition installed
mid-run heals mid-run, with senders rejoining through the normal
reconnect-backoff ladder (tcp.py) — the same code path a real switch
flap exercises.

Fault taxonomy (the Jepsen network nemeses, per directed link):

* ``cut``      — the link is down: TCP senders fail like an unreachable
  peer and run the reconnect ladder; loopback frames vanish.
* ``drop_p``   — each frame is independently lost with this probability.
* ``delay_p/delay_s`` — a frame is held back (TCP: the sender thread
  sleeps ``delay_s``; loopback: the frame is delivered just before the
  NEXT frame on that link, a one-frame time shift that preserves order).
* ``dup_p``    — a frame is delivered twice (stale/duplicate RPC
  idempotency through the real codec round-trip).
* ``reorder_p`` — a frame is held and delivered AFTER the next frame on
  the link (adjacent swap: the minimal observable reordering).

Determinism: every directed link owns a private ``random.Random`` stream
derived from ``(seed, src, dst)``, and each :meth:`plan` call consumes a
fixed number of draws — so a link's fault decisions depend only on the
seed and how many frames crossed it, never on thread interleaving with
other links.  That is what makes a seeded chaos run replayable.
"""

from __future__ import annotations

import threading
from collections import namedtuple
from random import Random
from typing import Dict, Iterable, Optional, Set, Tuple

__all__ = ["LinkAction", "LinkFaults", "PASS"]

# The per-frame verdict a transport acts on.  ``cut`` means the link is
# administratively down (TCP severs the connection); ``deliver=False``
# without cut is a probabilistic single-frame drop.
LinkAction = namedtuple("LinkAction",
                        ("deliver", "cut", "delay_s", "dup", "reorder"))
PASS = LinkAction(True, False, 0.0, False, False)
_CUT = LinkAction(False, True, 0.0, False, False)
_DROP = LinkAction(False, False, 0.0, False, False)

# Counter names as they render on /metrics (pre-registered at 0 by the
# node so a clean cluster exposes the whole family).
COUNTERS = ("net_faults_cut_total", "net_faults_dropped_total",
            "net_faults_delayed_total", "net_faults_duplicated_total",
            "net_faults_reordered_total")


class LinkFaults:
    """Seeded per-directed-link fault table shared by a cluster's
    transports.  All methods are thread-safe (sender threads consult it
    concurrently with the conductor mutating it)."""

    def __init__(self, n_nodes: int, seed: int = 0):
        self.n = n_nodes
        self.seed = seed
        self._lock = threading.Lock()
        self._down: Set[Tuple[int, int]] = set()
        # (src, dst) -> (drop_p, dup_p, reorder_p, delay_p, delay_s)
        self._spec: Dict[Tuple[int, int], Tuple[float, float, float,
                                                float, float]] = {}
        self._rng: Dict[Tuple[int, int], Random] = {}
        self.counters: Dict[str, int] = {
            "cut": 0, "dropped": 0, "delayed": 0, "duplicated": 0,
            "reordered": 0}

    # -- topology (partitions) ----------------------------------------------

    def set_link(self, src: int, dst: int, up: bool) -> None:
        """Directed cut/restore: ``up=False`` kills src->dst only — the
        asymmetric half-partition (dst still reaches src)."""
        with self._lock:
            if up:
                self._down.discard((src, dst))
            else:
                self._down.add((src, dst))

    def cut(self, a: int, b: int, sym: bool = True) -> None:
        self.set_link(a, b, False)
        if sym:
            self.set_link(b, a, False)

    def restore(self, a: int, b: int, sym: bool = True) -> None:
        self.set_link(a, b, True)
        if sym:
            self.set_link(b, a, True)

    def isolate(self, node: int) -> None:
        """Cut every link touching ``node`` in both directions."""
        with self._lock:
            for o in range(self.n):
                if o != node:
                    self._down.add((node, o))
                    self._down.add((o, node))

    def partition(self, sides: Iterable[Iterable[int]]) -> None:
        """Install a full partition: links WITHIN a side stay up, links
        ACROSS sides go down (same contract as LoopbackNetwork.partition)."""
        sides = [set(s) for s in sides]
        with self._lock:
            self._down.clear()
            for s in range(self.n):
                for d in range(self.n):
                    if s == d:
                        continue
                    if not any(s in side and d in side for side in sides):
                        self._down.add((s, d))

    def heal(self) -> None:
        """Restore all connectivity AND clear per-link fault specs.  RNG
        streams survive — determinism counts plan() calls, not heals."""
        with self._lock:
            self._down.clear()
            self._spec.clear()

    def link_up(self, src: int, dst: int) -> bool:
        with self._lock:
            return (src, dst) not in self._down

    # -- per-link probabilistic faults --------------------------------------

    def set_flaky(self, src: int, dst: int, *, drop_p: float = 0.0,
                  dup_p: float = 0.0, reorder_p: float = 0.0,
                  delay_p: float = 0.0, delay_s: float = 0.0) -> None:
        """Install (or, with all zeros, clear) probabilistic faults on the
        directed link src->dst."""
        with self._lock:
            if drop_p or dup_p or reorder_p or delay_p:
                self._spec[(src, dst)] = (drop_p, dup_p, reorder_p,
                                          delay_p, delay_s)
            else:
                self._spec.pop((src, dst), None)

    def set_all_flaky(self, **kw) -> None:
        for s in range(self.n):
            for d in range(self.n):
                if s != d:
                    self.set_flaky(s, d, **kw)

    # -- the per-frame verdict ----------------------------------------------

    def plan(self, src: int, dst: int) -> LinkAction:
        """One frame is about to cross src->dst: decide its fate.  Exactly
        four RNG draws per call on a flaky link (none on a clean or cut
        one), so outcome streams are a pure function of (seed, link,
        frame count)."""
        with self._lock:
            if (src, dst) in self._down:
                self.counters["cut"] += 1
                return _CUT
            spec = self._spec.get((src, dst))
            if spec is None:
                return PASS
            drop_p, dup_p, reorder_p, delay_p, delay_s = spec
            key = (src, dst)
            rng = self._rng.get(key)
            if rng is None:
                rng = self._rng[key] = Random(
                    (self.seed * 1000003) ^ (src * 8191 + dst))
            r_drop, r_dup, r_reord, r_delay = (
                rng.random(), rng.random(), rng.random(), rng.random())
            if r_drop < drop_p:
                self.counters["dropped"] += 1
                return _DROP
            dup = r_dup < dup_p
            reorder = r_reord < reorder_p
            delay = delay_s if r_delay < delay_p else 0.0
            if dup:
                self.counters["duplicated"] += 1
            if reorder:
                self.counters["reordered"] += 1
            if delay:
                self.counters["delayed"] += 1
            return LinkAction(True, False, delay, dup, reorder)

    # -- audit ---------------------------------------------------------------

    def snapshot(self) -> dict:
        """Current table + counters, JSON-shaped (chaos artifacts embed
        this so a soak's final network state is part of the record)."""
        with self._lock:
            return {
                "down": sorted(list(p) for p in self._down),
                "flaky": {f"{s}->{d}": list(v)
                          for (s, d), v in sorted(self._spec.items())},
                "counters": dict(self.counters),
            }
