"""InboxAccumulator: merges asynchronously arriving peer slices into the
dense per-tick inbox the engine consumes.

Nodes tick independently; a peer may deliver zero, one or several slices
between two local ticks.  Per (kind, src, group) the *latest* message wins —
overwrite-merge.  This is safe for Raft: every RPC is either idempotent or
re-sent on timeout (the engine's ``awaiting``/``rpc_timeout_ticks`` resend
path), so dropping a superseded message is indistinguishable from network
loss, which the protocol already tolerates.  The reference gets the same
effect from per-request timeouts + stale-reply term fencing
(transport/rpc/AsyncService.java:120-132, context/member/Leader.java:224-227).

AppendEntries payload bytes ride with their frame and are staged here until
the engine accepts the entries (StepInfo.appended_from/to), at which point
the runtime moves them into the durable LogStore.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

import numpy as np

from .codec import KIND_FIELDS


class InboxAccumulator:
    def __init__(self, cfg, template: Dict[str, Tuple[np.dtype, tuple]]):
        self.cfg = cfg
        self.template = template
        self._lock = threading.Lock()
        P, G = cfg.n_peers, cfg.n_groups
        self._arrays: Dict[str, np.ndarray] = {
            name: np.zeros((P, G) + trail, dt)
            for name, (dt, trail) in template.items()
        }
        self._valid_fields = [v for v, _ in KIND_FIELDS.values()]
        # payload staging: (src, group, index) -> bytes
        self._payloads: Dict[Tuple[int, int, int], bytes] = {}
        self._dirty = False

    def merge(self, src: int,
              fields: Dict[str, Tuple[np.ndarray, np.ndarray]],
              payloads: Dict[Tuple[int, int], bytes]) -> None:
        """Merge one unpacked slice from peer ``src`` (codec.unpack_slice)."""
        with self._lock:
            for name, (cols, vals) in fields.items():
                self._arrays[name][src, cols] = vals
            for (g, idx), p in payloads.items():
                self._payloads[(src, g, idx)] = p
            self._dirty = True

    def merge_dense(self, src: int, fields: Dict[str, np.ndarray],
                    payloads: Dict[Tuple[int, int], bytes]) -> None:
        """Loopback fast path: merge a full [G]/[G,B] dense slice."""
        with self._lock:
            for vfield, dfields in KIND_FIELDS.values():
                valid = fields[vfield]
                cols = np.nonzero(valid)[0]
                if len(cols) == 0:
                    continue
                self._arrays[vfield][src, cols] = True
                for f in dfields:
                    self._arrays[f][src, cols] = fields[f][cols]
            for (g, idx), p in payloads.items():
                self._payloads[(src, g, idx)] = p
            self._dirty = True

    def drain(self) -> Tuple[Dict[str, np.ndarray],
                             Dict[Tuple[int, int, int], bytes]]:
        """Take the accumulated inbox + payload staging, resetting both.

        Returns the live arrays (ownership transfers to the caller) and the
        staged payloads keyed (src, group, index)."""
        with self._lock:
            arrays = self._arrays
            payloads = self._payloads
            P, G = self.cfg.n_peers, self.cfg.n_groups
            self._arrays = {
                name: np.zeros((P, G) + trail, dt)
                for name, (dt, trail) in self.template.items()
            }
            self._payloads = {}
            self._dirty = False
            return arrays, payloads

    @property
    def has_traffic(self) -> bool:
        return self._dirty
