"""InboxAccumulator: delivers asynchronously arriving peer slices to the
dense per-tick inbox the engine consumes.

Nodes tick independently; a peer may deliver zero, one or several slices
between two local ticks.  Slices are queued per source and drained **one
per source per tick, in arrival order** — the engine sees exactly the
per-tick message planes the sender emitted, just time-shifted.  Ordered
delivery is what makes the leader's pipelined AppendEntries window sound
(several un-acked batches in flight per (group, peer), core/step.py
phase 9): batch k+1's prev-entry check assumes batch k was offered first,
the same in-order contract the reference gets from one TCP connection per
peer (transport/EventNode.java:39-120).

Catch-up: a consumer that falls behind (tick-rate drift, a JIT-compile
stall) must not lag permanently — one-slice-per-tick service can never
drain a standing backlog under sustained traffic, and stale delivery makes
every reply look timed out.  When a source's queue exceeds
COLLAPSE_BACKLOG, the whole backlog is collapsed into one slice,
newest-wins per (kind, group).  Collapsing reorders nothing the protocol
can't absorb: replies/votes are idempotent, and a collapsed (= partially
lost) AppendEntries stream makes the follower reject at the gap, which
resets the leader's window and resends from the ack base — the engine's
normal loss recovery (the reference's per-request timeouts + stale-reply
term fencing, transport/rpc/AsyncService.java:120-132,
context/member/Leader.java:224-227).

AppendEntries payload bytes ride with their frame and are staged here until
the engine accepts the entries (StepInfo.appended_from/to), at which point
the runtime moves them into the durable LogStore.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Dict, Tuple

import numpy as np

from .codec import KIND_FIELDS


class InboxAccumulator:
    MAX_QUEUED_SLICES = 64   # per source; beyond this, new slices drop
    COLLAPSE_BACKLOG = 3     # backlog beyond this collapses to one slice

    def __init__(self, cfg, template: Dict[str, Tuple[np.dtype, tuple]]):
        self.cfg = cfg
        self.template = template
        self._lock = threading.Lock()
        # src -> FIFO of (fields, payloads) slices, fields in the sparse
        # codec.unpack_slice format: field -> (group cols, values).
        self._queues: Dict[int, Deque[tuple]] = {}

    def merge(self, src: int,
              fields: Dict[str, Tuple[np.ndarray, np.ndarray]],
              payloads: Dict[int, Tuple[int, list]]) -> None:
        """Enqueue one unpacked slice from peer ``src`` (payloads as
        per-group contiguous runs, codec.unpack_slice format)."""
        with self._lock:
            q = self._queues.get(src)
            if q is None:
                q = self._queues[src] = deque()
            if len(q) >= self.MAX_QUEUED_SLICES:
                return   # = network loss; sender's resend timeout recovers
            q.append((fields, payloads))

    def drain(self) -> Tuple[Dict[str, np.ndarray],
                             Dict[Tuple[int, int], Tuple[int, list]]]:
        """Pop the oldest queued slice of every source and merge them into
        one dense inbox (different sources occupy disjoint [src, :] rows,
        so one slice per source never collides).  A source whose backlog
        exceeds COLLAPSE_BACKLOG has its entire queue collapsed instead
        (newest wins per lane) so lag stays bounded.

        Returns the dense arrays (ownership transfers to the caller) and
        the popped slices' payload runs keyed (src, group) — newest-wins
        per group under collapse, matching the field planes."""
        P, G = self.cfg.n_peers, self.cfg.n_groups
        arrays: Dict[str, np.ndarray] = {
            name: np.zeros((P, G) + trail, dt)
            for name, (dt, trail) in self.template.items()
        }
        payloads: Dict[Tuple[int, int], Tuple[int, list]] = {}
        with self._lock:
            for src, q in self._queues.items():
                if not q:
                    continue
                if len(q) > self.COLLAPSE_BACKLOG:
                    batch, q_new = list(q), deque()
                    self._queues[src] = q_new
                else:
                    batch = [q.popleft()]
                for fields, pl in batch:
                    for name, (cols, vals) in fields.items():
                        arrays[name][src, cols] = vals
                    for g, run in pl.items():
                        payloads[(src, g)] = run
        return arrays, payloads

    @property
    def has_traffic(self) -> bool:
        with self._lock:
            return any(self._queues.values())
