#!/usr/bin/env python
"""Headline benchmark: AppendEntries commits/sec across 100k Raft groups.

Runs the full consensus loop — leader election, AppendEntries fan-out over a
3-node cluster, quorum-median commit, slack compaction — entirely on device,
with every node's engine vectorized over all groups (BASELINE.json north
star: 100k groups, >1M commits/sec on one TPU v5e-1).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def run(n_groups: int = 100_000, n_peers: int = 3, measure_ticks: int = 512,
        warmup_ticks: int = 128) -> dict:
    from rafting_tpu import DeviceCluster, EngineConfig
    from rafting_tpu.core.sim import run_cluster_ticks

    cfg = EngineConfig(
        n_groups=n_groups, n_peers=n_peers,
        log_slots=64, batch=8, max_submit=8,
        election_ticks=10, heartbeat_ticks=3, rpc_timeout_ticks=8,
        pre_vote=True,
    )
    c = DeviceCluster(cfg, seed=0)
    submit = jnp.full((n_peers, n_groups), cfg.max_submit, jnp.int32)

    # Warm-up: compile + elect leaders + reach steady-state replication.
    states, inflight, info = run_cluster_ticks(
        cfg, warmup_ticks, c.states, c.inflight, c.last_info, c.conn, submit)
    jax.block_until_ready(states.commit)
    start_commit = np.asarray(states.commit).max(axis=0).astype(np.int64).sum()

    t0 = time.perf_counter()
    states, inflight, info = run_cluster_ticks(
        cfg, measure_ticks, states, inflight, info, c.conn, submit)
    jax.block_until_ready(states.commit)
    elapsed = time.perf_counter() - t0

    end_commit = np.asarray(states.commit).max(axis=0).astype(np.int64).sum()
    commits = int(end_commit - start_commit)
    cps = commits / elapsed

    # Sanity: every group must have exactly one leader and nonzero commits.
    roles = np.asarray(states.role)
    n_lead = (roles == 3).sum(axis=0)
    assert (n_lead == 1).all(), f"leaders per group: {np.unique(n_lead)}"
    assert commits > 0

    return {
        "metric": f"AppendEntries commits/sec @{n_groups // 1000}k Raft groups "
                  f"({n_peers}-node cluster, full consensus loop on device)",
        "value": round(cps),
        "unit": "commits/sec",
        "vs_baseline": round(cps / 1_000_000, 3),
    }


if __name__ == "__main__":
    n_groups = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    print(json.dumps(run(n_groups=n_groups)))
