#!/usr/bin/env python
"""Headline benchmark: AppendEntries commits/sec across 100k Raft groups.

Runs the full consensus loop — leader election, AppendEntries fan-out over a
3-node cluster, quorum-median commit, slack compaction — entirely on device,
with every node's engine vectorized over all groups (BASELINE.json north
star: 100k groups, >1M commits/sec on one TPU v5e-1).

Defensive, smoke-first harness (the r1/r2 bench was all-or-nothing and died
silent — rc=1 then rc=124 with zero JSON).  Structure:

* every scale runs in its OWN subprocess under a hard timeout, so a wedged
  TPU backend (r2: bare ``jax.devices()`` hung forever) or a kernel fault
  (r1: UNAVAILABLE at 100k groups) costs one scale, not the whole run;
* scales escalate 1k (smoke) → 4k → 16k → 32k → 65k → 100k and a
  fully-formed headline JSON line is printed and flushed after EVERY
  successful scale — whatever kills the parent later, a parseable number is
  already on stdout;
* children enable ``faulthandler`` with a watchdog dump so a hang leaves a
  traceback on stderr instead of silence;
* if even the smoke scale cannot reach the default (TPU) backend, one CPU
  fallback run is emitted (clearly labeled) so the artifact is never empty.

The final stdout line is the headline result at the largest surviving scale:
``{"metric", "value", "unit", "vs_baseline"}``.
"""

import json
import os
import subprocess
import sys
import time

ARTIFACT_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "artifacts")

SCALES = (1_024, 4_096, 16_384, 32_768, 65_536, 100_000)
BASELINE_CPS = 1_000_000  # BASELINE.md: >1M commits/sec @100k groups, v5e-1
FALSY = ("", "0", "false", "no", "off")
# The tuned pipeline budget (32k-group sweep; see git log) — one definition
# shared by the fallback and bonus stages so they cannot drift.
TUNED_ENV = {"BENCH_MAX_SUBMIT": "32", "BENCH_BATCH": "32",
             "BENCH_LOG_SLOTS": "256"}
TUNED_TAG = " [tuned budget S=32/B=32/L=256]"


def env_flag(name: str) -> bool:
    return os.environ.get(name, "").strip().lower() not in FALSY


def child_run(n_groups: int, measure_ticks: int, warmup_ticks: int,
              platform: str = "", profile_dir: str = "") -> dict:
    """One scale, in-process.  Prints nothing; returns the result dict."""
    import faulthandler
    faulthandler.enable()
    # If anything (backend init, compile, device exec) wedges, dump every
    # thread's stack to stderr before the parent's timeout fires.
    timeout_s = float(os.environ.get("BENCH_CHILD_WATCHDOG", "240"))
    faulthandler.dump_traceback_later(timeout_s, exit=False)

    import jax
    if platform:
        jax.config.update("jax_platforms", platform)
    import jax.numpy as jnp
    import numpy as np
    from functools import partial as _partial
    from rafting_tpu import DeviceCluster, EngineConfig
    from rafting_tpu.core.sim import run_cluster_ticks, run_cluster_ticks_blocked

    t_init = time.perf_counter()
    dev = jax.devices()[0]
    init_s = time.perf_counter() - t_init

    n_peers = 3
    # BENCH_NEMESIS=1: measure commits/sec UNDER the standard three-regime
    # fault schedule (testkit/nemesis.chaos_mix, seed 0: partitions ->
    # crash/stall storm -> lossy+duplicating links) instead of a healthy
    # network — the honest number behind the BASELINE config-4 "under
    # partition" target.  Warm-up stays healthy (elect + reach steady
    # state); only the measured window runs the schedule, entirely inside
    # fused scans.
    nemesis_on = env_flag("BENCH_NEMESIS")
    # BENCH_READS=1: measure the LINEARIZABLE READ PLANE instead of pure
    # append throughput — a warm-compiled mixed 90/10 read/write load
    # (per tick per group: one ReadIndex batch of 9*max_submit queries +
    # max_submit log writes), entirely inside the fused scan
    # (core/sim.py run_cluster_ticks_reads).  Reads never touch the log,
    # so the headline is reads/sec on top of a still-live write stream.
    reads_on = env_flag("BENCH_READS")
    if reads_on and nemesis_on:
        # The reads scan measures the HEALTHY path; silently honoring both
        # flags would label a fault-free measurement as a chaos number.
        raise SystemExit("BENCH_READS and BENCH_NEMESIS are mutually "
                         "exclusive: the read stage measures the healthy "
                         "path (a faults-on reads scan does not exist yet)")
    # Pipeline budget knobs.  Defaults are the proven-on-TPU envelope
    # (r1 data was taken at L=64/B=8); the CPU fallback overrides them to
    # the tuned point from the 32k-group sweep (S=32/B=32/L=256 ~ 2.1x —
    # the reference itself ships up to 50 entries per AppendEntries,
    # Leadership.java REPLICATE_LIMIT).
    # BENCH_TRACE=1: compile the flight recorder into the scan
    # (cfg.trace_depth event-ring slots per group, BENCH_TRACE_DEPTH
    # overrides the default 16) — the recorder-overhead A/B: same load,
    # same schedule, commits/sec with the trace lanes vs without.
    trace_on = env_flag("BENCH_TRACE")
    cfg = EngineConfig(
        n_groups=n_groups, n_peers=n_peers,
        log_slots=int(os.environ.get("BENCH_LOG_SLOTS", "64")),
        batch=int(os.environ.get("BENCH_BATCH", "8")),
        max_submit=int(os.environ.get("BENCH_MAX_SUBMIT", "8")),
        election_ticks=10, heartbeat_ticks=3, rpc_timeout_ticks=8,
        pre_vote=True,
        # BENCH_USE_PALLAS=1: quorum commit through the Pallas kernel
        # (ops/quorum.py) instead of inline jnp — the A/B the TPU decision
        # needs is then one env var per run.
        use_pallas=env_flag("BENCH_USE_PALLAS"),
        # Default matches the engine's floor (>= 12 slots now that a tick
        # can emit up to 11 events — membership added three kinds).
        trace_depth=(int(os.environ.get("BENCH_TRACE_DEPTH", "16"))
                     if trace_on else 0),
    )
    # Group-axis tiling (groups are independent; run_cluster_ticks_blocked).
    # The r1 ">= 65k fault" turned out to be the per-execution duration
    # limit, NOT a program-size limit: an UNBLOCKED 100k program runs fine
    # in short chunks (r4: 3.48M c/s) — but 32k blocks still measure
    # slightly faster (3.58M c/s at 100k), so tiling stays the default.
    max_block = int(os.environ.get("BENCH_GROUP_BLOCK", "32768"))
    if n_groups > max_block:
        n_blocks = -(-n_groups // max_block)
        block = -(-n_groups // n_blocks)  # equal blocks, minimal padding
        run_ticks = _partial(run_cluster_ticks_blocked, group_block=block)
    else:
        n_blocks = 1
        block = 0
        run_ticks = run_cluster_ticks
    c = DeviceCluster(cfg, seed=0)
    submit = jnp.full((n_peers, n_groups), cfg.max_submit, jnp.int32)

    # Execution granularity: the scan length per device execution.  Two
    # r4 findings (tools/bisect_tpu.py) force this structure:
    # * one LONG execution (512 ticks at >= 65k groups, ~2+ min device
    #   time) dies with UNAVAILABLE — the r1 ">= 65k kernel fault" was a
    #   per-execution duration limit, NOT a scale limit: the same shapes
    #   complete as back-to-back 128-tick executions;
    # * jax.block_until_ready is a NO-OP on the tunneled TPU platform
    #   ('axon'), so timing must be fenced by a real device->host read.
    # Default chunk scales inversely with the block count (lax.map runs
    # blocks sequentially INSIDE one execution), keeping per-execution
    # device work near the proven envelope of a 128-tick 32k-group run.
    default_chunk = max(16, 128 // n_blocks)
    chunk = max(1, min(int(os.environ.get("BENCH_TICKS_PER_CALL",
                                          str(default_chunk))),
                       measure_ticks))

    def run_chunks(n_ticks, states, inflight, info):
        done = 0
        while done < n_ticks:
            step = min(chunk, n_ticks - done)
            states, inflight, info = run_ticks(
                cfg, step, states, inflight, info, c.conn, submit)
            done += step
        return states, inflight, info

    if reads_on:
        from rafting_tpu.core.sim import run_cluster_ticks_reads
        # 90/10 offered mix: 9*S reads per group-tick ride one ReadIndex
        # batch; S writes flow beside them.
        read_load = jnp.full((n_peers, n_groups), 9 * cfg.max_submit,
                             jnp.int32)
        read_totals = {"served": 0, "lease": 0, "appended": 0}

        def run_chunks_reads(n_ticks, states, inflight, info):
            done = 0
            served = lease = appended = 0
            while done < n_ticks:
                step = min(chunk, n_ticks - done)
                states, inflight, info, sv, lh, ap = run_cluster_ticks_reads(
                    cfg, step, states, inflight, info, c.conn, submit,
                    read_load)
                # Lazy device scalars: summed on device, pulled once after
                # the measured window (the commit read is the fence).
                served, lease, appended = served + sv, lease + lh, appended + ap
                done += step
            return states, inflight, info, served, lease, appended

    if nemesis_on:
        from rafting_tpu.core.sim import run_cluster_ticks_nemesis
        from rafting_tpu.testkit import nemesis as _nem
        sched = _nem.chaos_mix(n_peers, measure_ticks, seed=0)

        def run_chunks_faulted(states, inflight, info):
            done = 0
            while done < measure_ticks:
                step = min(chunk, measure_ticks - done)
                states, inflight, info = run_cluster_ticks_nemesis(
                    cfg, states, inflight, info,
                    jax.tree.map(lambda a: a[done:done + step], sched),
                    submit)
                done += step
            return states, inflight, info

    def commit_sum(states):
        # Device->host read: the ONLY reliable execution fence here.
        return int(np.asarray(states.commit).max(axis=0)
                   .astype(np.int64).sum())

    # Warm-up: compile + elect leaders + reach steady-state replication.
    t0 = time.perf_counter()
    states, inflight, info = run_chunks(warmup_ticks, c.states, c.inflight,
                                        c.last_info)
    if nemesis_on:
        # Compile the nemesis scan during warm-up, NOT inside measure():
        # one execution per distinct step size of the measured chunk
        # sequence, driven by an all-healthy schedule (the compiled
        # program is identical — the fault schedule is data), so the
        # faults-on headline times pure execution like the healthy one.
        for step in sorted({min(chunk, measure_ticks - d)
                            for d in range(0, measure_ticks, chunk)}):
            states, inflight, info = run_cluster_ticks_nemesis(
                cfg, states, inflight, info,
                _nem.healthy(n_peers, step), submit)
    if reads_on:
        # Same warm-compile discipline for the reads scan (the read load
        # is data; only the per-step-size programs need building).
        for step in sorted({min(chunk, measure_ticks - d)
                            for d in range(0, measure_ticks, chunk)}):
            states, inflight, info, *_ = run_cluster_ticks_reads(
                cfg, step, states, inflight, info, c.conn, submit,
                read_load)
    start_commit = commit_sum(states)
    warm_s = time.perf_counter() - t0

    def measure():
        nonlocal states, inflight, info
        t0 = time.perf_counter()
        if reads_on:
            states, inflight, info, sv, lh, ap = run_chunks_reads(
                measure_ticks, states, inflight, info)
        elif nemesis_on:
            states, inflight, info = run_chunks_faulted(states, inflight,
                                                        info)
        else:
            states, inflight, info = run_chunks(measure_ticks, states,
                                                inflight, info)
        # The commit read fences the elapsed time; its cost ([N, G] i32
        # pull) is part of the measurement and negligible at every scale.
        commit_sum(states)
        if reads_on:
            read_totals["served"] = int(np.asarray(sv))
            read_totals["lease"] = int(np.asarray(lh))
            read_totals["appended"] = int(np.asarray(ap))
        return time.perf_counter() - t0

    from rafting_tpu.utils.profiling import device_trace
    with device_trace(profile_dir):   # no-op when unset
        elapsed = measure()

    end_commit = int(np.asarray(states.commit).max(axis=0).astype(np.int64).sum())
    commits = end_commit - start_commit

    # Sanity: nonzero commits always; exactly one leader per group only on
    # the healthy path (mid-chaos a deposed minority leader may linger at
    # a lower term — legal Raft, so the faulted run asserts AT LEAST one).
    roles = np.asarray(states.role)
    n_lead = (roles == 3).sum(axis=0)
    if nemesis_on:
        assert (n_lead >= 1).any(), "no leaders anywhere after chaos"
    else:
        assert (n_lead == 1).all(), f"leaders per group: {np.unique(n_lead)}"
    assert commits > 0

    faulthandler.cancel_dump_traceback_later()
    res = {
        "scale": n_groups,
        "platform": dev.platform,
        "cps": commits / elapsed,
        "commits": commits,
        "ticks": measure_ticks,
        "elapsed_s": round(elapsed, 4),
        "warmup_s": round(warm_s, 2),
        "init_s": round(init_s, 2),
        "nemesis": nemesis_on,
        "trace_depth": cfg.trace_depth,
    }
    if trace_on:
        # The recorder must have actually recorded (elections at minimum).
        ev = int(np.asarray(states.trace.n).astype(np.int64).sum())
        assert ev > 0, "BENCH_TRACE run recorded zero events"
        res["trace_events"] = ev
    if reads_on:
        assert read_totals["served"] > 0, "read stage served nothing"
        res.update(
            reads=read_totals["served"],
            rps=read_totals["served"] / elapsed,
            lease_hits=read_totals["lease"],
            appended=read_totals["appended"],
            read_mix="90/10",
        )
    return res


def member_child(n_groups: int) -> dict:
    """BENCH_MEMBER stage, in-process: (1) the masked-quorum commit
    kernel A/B'd against the legacy fixed-majority baseline at P=3 —
    asserting the membership-aware kernel stays within noise (>= 0.95x);
    (2) reconfig walk-through throughput: every group walks the full
    3 -> 3-disjoint rebalance (add learners {3,4,5} -> catch up ->
    joint switch to {3,4,5} -> auto-leave) at P=6, reported as groups
    reconfigured per second with zero committed-entry loss asserted."""
    import faulthandler
    faulthandler.enable()
    timeout_s = float(os.environ.get("BENCH_CHILD_WATCHDOG", "240"))
    faulthandler.dump_traceback_later(timeout_s, exit=False)

    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np
    from rafting_tpu import DeviceCluster, EngineConfig
    from rafting_tpu.core.cluster import cluster_snapshot
    from rafting_tpu.core.sim import run_cluster_ticks
    from rafting_tpu.core.types import conf_new_of, conf_voters_of

    dev = jax.devices()[0]
    # ONE scan length everywhere: run_cluster_ticks compiles per static
    # tick count, so warm-up must execute the exact program the measured
    # window re-runs (a 32-tick warmup before a 64-tick measure times the
    # 64-tick compile INSIDE the measurement).
    CHUNK = 16

    def scan_chunks(cfg, c, n_ticks, submit):
        for _ in range(n_ticks // CHUNK):
            c.states, c.inflight, c.last_info = run_cluster_ticks(
                cfg, CHUNK, c.states, c.inflight, c.last_info, c.conn,
                submit)

    def commits_per_sec(cfg, reps=2) -> float:
        c = DeviceCluster(cfg, seed=0)
        submit = jnp.full((cfg.n_peers, cfg.n_groups), cfg.max_submit,
                          jnp.int32)
        scan_chunks(cfg, c, 32, submit)   # compile + elect + steady state
        best = 0.0
        for _ in range(reps):
            start = int(np.asarray(c.states.commit).max(axis=0)
                        .astype(np.int64).sum())
            t0 = time.perf_counter()
            scan_chunks(cfg, c, 64, submit)
            end = int(np.asarray(c.states.commit).max(axis=0)
                      .astype(np.int64).sum())
            best = max(best, (end - start) / (time.perf_counter() - t0))
        return best

    # -- (1) masked vs fixed-majority commit kernel, P=3 ------------------
    base_cfg = EngineConfig(
        n_groups=n_groups, n_peers=3,
        log_slots=int(os.environ.get("BENCH_LOG_SLOTS", "64")),
        batch=int(os.environ.get("BENCH_BATCH", "8")),
        max_submit=int(os.environ.get("BENCH_MAX_SUBMIT", "8")),
        election_ticks=10, heartbeat_ticks=3, rpc_timeout_ticks=8,
        pre_vote=True)
    cps_fixed = commits_per_sec(
        dataclasses.replace(base_cfg, quorum_fixed=True))
    cps_masked = commits_per_sec(base_cfg)
    ratio = cps_masked / max(cps_fixed, 1e-9)
    assert ratio >= 0.95, \
        (f"masked-quorum kernel regressed commit throughput beyond noise "
         f"at P=3: {cps_masked:,.0f} vs fixed {cps_fixed:,.0f} "
         f"({ratio:.3f}x)")

    # -- (2) reconfig walk-through throughput, P=6 3->3-disjoint ----------
    cfg6 = dataclasses.replace(base_cfg, n_peers=6)
    c = DeviceCluster(cfg6, seed=0, n_voters=3)
    submit = jnp.full((6, n_groups), cfg6.max_submit, jnp.int32)
    scan_chunks(cfg6, c, 64, submit)   # compile + elect + steady state
    pre_commit = cluster_snapshot(c.states)["commit"].max(axis=0).copy()
    assert (pre_commit > 0).all(), "warm-up never committed"
    target = 0b111000
    new_nodes = (3, 4, 5)

    def walk_done() -> bool:
        w = np.asarray(c.last_info.conf_word)[new_nodes, :]
        ok = ((conf_voters_of(w) == target) & (conf_new_of(w) == 0)).all()
        roles = np.asarray(c.states.role)[new_nodes, :]
        return bool(ok and ((roles == 3).sum(axis=0) == 1).all())

    # The walk runs under LIGHT live traffic (1 command/group/tick): the
    # self-driving scan policy compacts every tick, and at full offered
    # load the floor outruns any learner snapshot install (the documented
    # pursuit-never-converges regime, core/cluster.py auto_host_inbox) —
    # real deployments gate compaction on checkpoint cadences instead.
    submit_walk = jnp.ones((6, n_groups), jnp.int32)
    scan_chunks(cfg6, c, CHUNK, submit_walk)   # compile the walk program
    t0 = time.perf_counter()
    c.request_membership(voters=0b000111, learners=target)   # learners in
    scan_chunks(cfg6, c, 48, submit_walk)
    c.request_membership(voters=target, learners=0)          # joint switch
    chunks = 0
    while not walk_done():
        scan_chunks(cfg6, c, CHUNK, submit_walk)
        chunks += 1
        assert chunks < 64, "rebalance walk did not converge"
    elapsed = time.perf_counter() - t0
    # Zero committed-entry loss: the new set's commit frontier covers the
    # pre-walk frontier and keeps advancing under the new voters.
    snap = cluster_snapshot(c.states)
    post = snap["commit"][new_nodes, :].max(axis=0)
    assert (post >= pre_commit).all(), "committed entries lost in the walk"
    scan_chunks(cfg6, c, CHUNK, submit)
    post2 = cluster_snapshot(c.states)["commit"][new_nodes, :].max(axis=0)
    assert (post2 > post).all(), "commits stalled after the walk"

    faulthandler.cancel_dump_traceback_later()
    return {
        "scale": n_groups,
        "platform": dev.platform,
        "member_stage": True,
        "walk_groups_per_sec": n_groups / elapsed,
        "walk_elapsed_s": round(elapsed, 3),
        "cps_masked": cps_masked,
        "cps_fixed": cps_fixed,
        "masked_vs_fixed": round(ratio, 4),
    }


def run_member_ladder(profile_unused: str = "") -> None:
    """BENCH_MEMBER=1: the membership stage replaces the normal ladder —
    reconfig walk-through throughput at 1k/32k/100k plus the
    masked-vs-fixed commit A/B at P=3, one subprocess per scale."""
    timeout_s = float(os.environ.get("BENCH_MEMBER_TIMEOUT", "420"))
    any_ok = False
    for g in (1_024, 32_768, 100_000):
        cmd = [sys.executable, os.path.abspath(__file__),
               "--member-child", str(g)]
        env = dict(os.environ)
        env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__)) + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        try:
            r = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=timeout_s, env=env)
        except subprocess.TimeoutExpired:
            sys.stderr.write(f"[bench] member scale {g}: TIMEOUT\n")
            continue
        if r.returncode != 0:
            tail = "\n".join(r.stderr.strip().splitlines()[-10:])
            sys.stderr.write(f"[bench] member scale {g}: rc="
                             f"{r.returncode}\n{tail}\n")
            continue
        try:
            res = json.loads(r.stdout.strip().splitlines()[-1])
        except (json.JSONDecodeError, IndexError):
            continue
        save_artifact(res, child_env=env, note="BENCH_MEMBER stage")
        any_ok = True
        emit({
            "metric": f"membership rebalance walk-throughs/sec "
                      f"@{g // 1000}k Raft groups (3->3-disjoint walk: "
                      f"add-learner -> catch-up -> joint switch -> "
                      f"auto-leave, P=6, {res['platform']}) "
                      f"[masked-quorum commit kernel "
                      f"{res['masked_vs_fixed']}x of fixed-majority @P=3]",
            "value": round(res["walk_groups_per_sec"]),
            "unit": "groups/sec",
            "vs_baseline": res["masked_vs_fixed"],
        })
    if not any_ok:
        emit({"metric": "membership rebalance stage (no scale survived)",
              "value": 0, "unit": "groups/sec", "vs_baseline": 0.0})
        sys.exit(1)


def run_openloop_stage() -> None:
    """BENCH_OPENLOOP=1: the overload stage replaces the ladder — an
    OPEN-LOOP rate sweep (testkit/openloop.py) against a small durable
    3-node cluster, with the admission-control plane ON and then
    force-disabled (RAFT_ADMISSION=0), emitting offered-vs-goodput +
    shed-rate + admitted-percentile curves per sweep point.  The
    headline is the NO-COLLAPSE property: past the measured capacity,
    goodput with admission on plateaus (>= 85% of its peak) and the
    admitted p999 stays bounded, while the admission-off control run is
    free to collapse (unbounded standing queues -> every completion
    lands past its deadline).  Closed-loop ladders cannot see any of
    this — the driver's politeness hides the overload (ROADMAP item 5).

    Scale knobs: BENCH_OPENLOOP_GROUPS (default 8), BENCH_OPENLOOP_DUR
    (seconds per sweep point, default 2), BENCH_OPENLOOP_MULTS (offered
    load as x capacity, default "0.5,1.0,2.0,3.0")."""
    import shutil
    import tempfile

    import jax
    jax.config.update("jax_platforms", "cpu")
    from rafting_tpu.core.types import EngineConfig
    from rafting_tpu.testkit.harness import LocalCluster
    from rafting_tpu.testkit.openloop import (
        OpenLoopSpec, no_collapse_check, run_open_loop)

    n_groups = int(os.environ.get("BENCH_OPENLOOP_GROUPS", "8"))
    dur = float(os.environ.get("BENCH_OPENLOOP_DUR", "2"))
    mults = [float(x) for x in os.environ.get(
        "BENCH_OPENLOOP_MULTS", "0.5,1.0,2.0,3.0").split(",")]
    deadline_s = float(os.environ.get("BENCH_OPENLOOP_DEADLINE_S", "1.0"))
    cfg = EngineConfig(
        n_groups=n_groups, n_peers=3, log_slots=64, batch=8, max_submit=8,
        election_ticks=10, heartbeat_ticks=3, rpc_timeout_ticks=8)

    def build(root: str) -> LocalCluster:
        c = LocalCluster(cfg, root, seed=7)
        for g in range(n_groups):
            c.wait_leader(g)
        return c

    def submit_fn(c: LocalCluster):
        leaders = {g: c.leader_of(g) for g in range(n_groups)}

        def submit(grp: int, tenant: str, seq: int):
            g = grp % n_groups
            ld = leaders.get(g)
            if ld is None or not c.nodes[ld].is_leader(g):
                leaders[g] = ld = c.leader_of(g)
            if ld is None:
                return None
            return c.nodes[ld].submit(g, b"ol-%d" % seq, tenant=tenant)
        return submit

    def probe_capacity(c: LocalCluster) -> float:
        """Closed-loop throughput at this scale: burst-submit to every
        leader, tick until drained, repeat — the politeness the open
        loop then discards."""
        t0 = time.monotonic()
        done = 0
        for _ in range(16):
            futs = []
            for g in range(n_groups):
                ld = c.leader_of(g)
                if ld is not None:
                    futs.append(c.nodes[ld].submit_batch(
                        g, [b"cap"] * 8))
            for _ in range(200):
                if all(f.done() for f in futs):
                    break
                c.tick(1)
            done += sum(8 for f in futs
                        if f.done() and f.exception() is None)
        return done / max(time.monotonic() - t0, 1e-9)

    def sweep(c: LocalCluster, cap: float, label: str) -> list:
        out = []
        for m in mults:
            spec = OpenLoopSpec(
                rate=max(1.0, cap * m), duration_s=dur, n_tenants=4,
                n_groups=n_groups, deadline_s=deadline_s,
                seed=int(m * 100))
            r = run_open_loop(spec, submit_fn(c),
                              step=lambda: c.tick(1), drain_s=2.0)
            d = r.to_dict()
            d["offered_x_capacity"] = m
            adms = [n.admission for n in c.nodes.values()]
            d["admission"] = {
                "enabled": adms[0].enabled,
                "level": round(max(a.level for a in adms), 4),
                "shed_total": sum(a.shed for a in adms)}
            out.append((m, r, d))
            emit({"metric": f"open-loop goodput @{n_groups} groups, "
                            f"admission={label}, offered={m:g}x capacity",
                  "value": round(r.goodput, 1), "unit": "ops/sec",
                  "vs_baseline": None, **d})
        return out

    results = {}
    for label, env_admission in (("on", None), ("off", "0")):
        root = tempfile.mkdtemp(prefix=f"openloop-{label}-")
        old = os.environ.get("RAFT_ADMISSION")
        try:
            if env_admission is not None:
                os.environ["RAFT_ADMISSION"] = env_admission
            else:
                os.environ.pop("RAFT_ADMISSION", None)
            c = build(root)
            try:
                cap = probe_capacity(c)
                emit({"metric": f"closed-loop capacity probe "
                                f"@{n_groups} groups (admission={label})",
                      "value": round(cap, 1), "unit": "ops/sec",
                      "vs_baseline": None})
                results[label] = (cap, sweep(c, cap, label))
            finally:
                c.close()
        finally:
            if old is None:
                os.environ.pop("RAFT_ADMISSION", None)
            else:
                os.environ["RAFT_ADMISSION"] = old
            shutil.rmtree(root, ignore_errors=True)

    on = [r for _m, r, _d in results["on"][1]]
    ok, why = no_collapse_check(on, slo_s=deadline_s)
    emit({"metric": "open-loop no-collapse verdict (admission on)",
          "value": 1 if ok else 0, "unit": "pass", "vs_baseline": None,
          "why": why,
          "capacity_ops_per_sec": round(results["on"][0], 1)})
    save_artifact(
        {"platform": "cpu", "scale": n_groups,
         "capacity": {k: round(v[0], 1) for k, v in results.items()},
         "sweep": {k: [d for _m, _r, d in v[1]]
                   for k, v in results.items()},
         "no_collapse": {"ok": ok, "why": why}},
        note="BENCH_OPENLOOP stage: open-loop overload sweep")
    assert ok, f"no-collapse property failed: {why}"


def run_txn_stage() -> None:
    """BENCH_TXN=1: the cross-group transaction stage replaces the
    ladder — closed-loop 2-key Zipf bank transfers through the 2PC
    plane (runtime/txn.py) on a durable 3-node cluster, A/B'd against
    the SAME key traffic issued as two independent single-group writes
    (the no-atomicity upper bound: what the cluster does when nobody
    asks for cross-group all-or-nothing).  Emits txn/sec + abort rate
    per scale point plus the atomicity-tax ratio vs that bound; the
    tax is real and bounded — one transfer is five sequential quorum
    commits (begin, 2x prepare, decide, finalize) against the bound's
    two independent ones, so the honest ceiling is ~0.4x before lock
    conflicts subtract their share.

    Scale knobs: BENCH_TXN_GROUPS (comma ladder of total group counts,
    coordinator + N-1 participants, default "3,5"), BENCH_TXN_CLIENTS
    (default 8), BENCH_TXN_DUR (seconds per phase, default 4),
    BENCH_TXN_ZIPF (account skew, default 1.0)."""
    import itertools
    import shutil
    import tempfile
    import threading

    import jax
    jax.config.update("jax_platforms", "cpu")
    from rafting_tpu.api.stub import RaftStub
    from rafting_tpu.core.types import EngineConfig
    from rafting_tpu.machine.kv_machine import KVMachineProvider
    from rafting_tpu.testkit.chaos import StubHost
    from rafting_tpu.testkit.harness import LocalCluster
    from rafting_tpu.testkit.openloop import OpenLoopSpec, gen_transfers

    ladder = [int(x) for x in os.environ.get(
        "BENCH_TXN_GROUPS", "3,5").split(",")]
    clients = int(os.environ.get("BENCH_TXN_CLIENTS", "8"))
    dur = float(os.environ.get("BENCH_TXN_DUR", "4"))
    zipf = float(os.environ.get("BENCH_TXN_ZIPF", "1.0"))
    n_accounts = 16

    for n_groups in ladder:
        participants = list(range(1, n_groups))
        cfg = EngineConfig(n_groups=n_groups, n_peers=3, log_slots=64,
                           batch=8, max_submit=8, election_ticks=10,
                           heartbeat_ticks=3, rpc_timeout_ticks=8,
                           read_lease=True)
        root = tempfile.mkdtemp(prefix=f"txnbench-{n_groups}-")
        cluster = LocalCluster(
            cfg, root, seed=5,
            provider_factory=lambda i: KVMachineProvider(
                os.path.join(root, f"node{i}", "kv")))
        stop = threading.Event()

        def tick_loop():
            while not stop.is_set():
                for node in list(cluster.nodes.values()):
                    node.tick()
                time.sleep(0.002)

        try:
            for g in range(n_groups):
                cluster.wait_leader(g)
            threading.Thread(target=tick_loop, daemon=True).start()
            hosts = [StubHost(cluster, c % cfg.n_peers)
                     for c in range(clients)]
            seeder = StubHost(cluster, 0)
            for g in participants:
                s = RaftStub(seeder, str(g), g, forward=True,
                             forward_budget=10.0)
                for a in range(n_accounts):
                    s.execute(json.dumps({"op": "set", "k": f"acct{a}",
                                          "v": 10_000}), timeout=10)
            # One seeded plan feeds BOTH phases: same keys, same skew,
            # same amounts — the A/B differs only in atomicity.
            spec = OpenLoopSpec(rate=500.0, duration_s=dur * 8,
                                n_tenants=4, n_groups=len(participants),
                                seed=5)
            plan = gen_transfers(spec, n_accounts=n_accounts,
                                 account_zipf=zipf)

            def phase(body) -> tuple:
                idx = itertools.count()
                outs = [{"ok": 0, "aborted": 0, "failed": 0}
                        for _ in range(clients)]

                def worker(c):
                    host = hosts[c]
                    parts = {g: RaftStub(host, str(g), g, forward=True,
                                         forward_budget=8.0)
                             for g in participants}
                    coord = RaftStub(host, "0", 0, forward=True,
                                     forward_budget=8.0)
                    end = time.monotonic() + dur
                    while time.monotonic() < end:
                        step = plan[next(idx) % len(plan)]
                        body(coord, parts, step, outs[c])
                threads = [threading.Thread(target=worker, args=(c,))
                           for c in range(clients)]
                t0 = time.monotonic()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                el = time.monotonic() - t0
                tot = {k: sum(o[k] for o in outs) for k in outs[0]}
                return tot, el

            def txn_body(coord, parts, step, out):
                _t, _tn, src, dst, ka, kb, amt = step
                sg, dg = participants[src], participants[dst]
                try:
                    r = (coord.txn(deadline_s=2.0)
                         .transfer(parts[sg], ka, parts[dg], kb, amt)
                         .execute(timeout=6.0))
                    out["ok" if r.committed else "aborted"] += 1
                except Exception:
                    out["failed"] += 1

            def write_body(coord, parts, step, out):
                _t, _tn, src, dst, ka, kb, amt = step
                sg, dg = participants[src], participants[dst]
                try:
                    parts[sg].execute(json.dumps(
                        {"op": "incr", "k": ka, "v": -amt}), timeout=6.0)
                    parts[dg].execute(json.dumps(
                        {"op": "incr", "k": kb, "v": amt}), timeout=6.0)
                    out["ok"] += 1
                except Exception:
                    out["failed"] += 1

            txn_tot, txn_el = phase(txn_body)
            wr_tot, wr_el = phase(write_body)
        finally:
            stop.set()
            time.sleep(0.05)
            cluster.close()
            shutil.rmtree(root, ignore_errors=True)

        attempted = txn_tot["ok"] + txn_tot["aborted"] + txn_tot["failed"]
        txn_rate = txn_tot["ok"] / max(txn_el, 1e-9)
        abort_rate = txn_tot["aborted"] / max(attempted, 1)
        wr_rate = wr_tot["ok"] / max(wr_el, 1e-9)
        ratio = txn_rate / max(wr_rate, 1e-9)
        res = {
            "platform": "cpu", "scale": n_groups,
            "participants": len(participants), "clients": clients,
            "duration_s": dur, "account_zipf": zipf,
            "txn": {**txn_tot, "attempted": attempted,
                    "elapsed_s": round(txn_el, 3)},
            "independent_writes": {**wr_tot,
                                   "elapsed_s": round(wr_el, 3)},
            "txn_per_sec": round(txn_rate, 1),
            "abort_rate": round(abort_rate, 4),
            "independent_pairs_per_sec": round(wr_rate, 1),
            "atomicity_tax": round(ratio, 3),
        }
        save_artifact(res, note="BENCH_TXN stage: cross-group 2PC "
                                "transfers vs independent-writes bound")
        emit({"metric": f"cross-group 2PC transfers/sec @{n_groups} "
                        f"groups (1 coordinator + "
                        f"{len(participants)} participants, 2-key "
                        f"Zipf({zipf:g}) transfers, {clients} closed-"
                        f"loop clients, durable 3-node cluster) "
                        f"[abort rate {abort_rate:.1%}; independent-"
                        f"writes bound {wr_rate:.0f} pairs/sec]",
              "value": round(txn_rate, 1), "unit": "txn/sec",
              "vs_baseline": round(ratio, 3)})
        assert txn_tot["ok"] > 0, "txn stage committed nothing"


def run_latency_ab() -> None:
    """BENCH_LAT=1: the latency-plane overhead A/B replaces the ladder —
    durable commits/sec through bench_runtime.run() with span sampling
    ON (1/64, the default rate) vs OFF (RAFT_LAT_SAMPLE=0) at the same
    scale (default 100k groups, BENCH_LAT_SCALE overrides), in one
    process so all runs share jit caches and the comparison is
    load-for-load fair.  Mirrored ABBA order (off, on, on, off): on a
    shared host, back-to-back in-process runs drift — the second of two
    IDENTICAL unsampled runs measured ~10% slower on a single-vCPU
    container — and ABBA cancels linear drift exactly, where a naive
    off-then-on pair books the entire drift as "sampling overhead".
    Asserts the sampled pair keeps >98% of the unsampled pair's
    throughput — the plane's whole admission design (seeded stride
    selection, bounded in-flight spans, single-writer harvest) exists to
    make observation cheaper than 2%.  The ON runs' results carry the
    per-entry e2e + per-phase distributions."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import bench_runtime
    scale = int(os.environ.get("BENCH_LAT_SCALE", "100000"))
    off1 = bench_runtime.run(n_groups=scale, lat_sample=0)
    on1 = bench_runtime.run(n_groups=scale, lat_sample=64)
    on2 = bench_runtime.run(n_groups=scale, lat_sample=64)
    off2 = bench_runtime.run(n_groups=scale, lat_sample=0)
    assert on1["latency"]["sample_rate"] == 64 and \
        off1["latency"]["sample_rate"] == 0, "A/B pins did not take"
    on_cps = (on1["value"] + on2["value"]) / 2
    off_cps = (off1["value"] + off2["value"]) / 2
    overhead = 1.0 - on_cps / max(off_cps, 1)
    res = {
        "scale": scale,
        "platform": "cpu",
        "lat_overhead": round(overhead, 4),
        "sampled_commits_per_sec": round(on_cps),
        "unsampled_commits_per_sec": round(off_cps),
        "order": "ABBA (off, on, on, off)",
        "sampled": [on1, on2],
        "unsampled": [off1, off2],
    }
    save_artifact(res, note="BENCH_LAT stage: span-sampling overhead A/B")
    emit({
        "metric": f"latency-plane sampling overhead @{scale // 1000}k "
                  f"groups (durable runtime, 1/64 sampling vs off, "
                  f"loopback)",
        "value": round(overhead * 100, 2),
        "unit": "% durable commits/sec regression (target <2%)",
        "vs_baseline": None,
        "sampled_commits_per_sec": round(on_cps),
        "unsampled_commits_per_sec": round(off_cps),
        "sampled_e2e": on1["latency"].get("e2e"),
        "sampled_counts": on1["latency"].get("counts"),
    })
    assert overhead < 0.02, (
        f"latency plane costs {overhead * 100:.2f}% durable throughput "
        f"(budget: 2%) — sampled {on_cps:.0f} vs unsampled "
        f"{off_cps:.0f} commits/sec")


def run_heat_ab() -> None:
    """BENCH_HEAT=1: the fleet-attribution overhead A/B replaces the
    ladder — durable commits/sec with the FULL attribution plane on
    (heat lanes compiled in + 1/64 span sampling + cross-node hop
    tracing) vs everything off, at the same scale (default 100k groups,
    BENCH_HEAT_SCALE overrides), in one process so all runs share jit
    caches.  Mirrored ABBA order (off, on, on, off) for the same
    drift-cancellation reason as BENCH_LAT.  Asserts the attributed
    pair keeps >98% of the bare pair's throughput: the heat lanes are
    four branchless [G] adds folded into the existing scan, the drain
    is one vectorized delta per tick, and hop records ride existing
    flushes — observation must stay cheaper than 2%."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import bench_runtime
    scale = int(os.environ.get("BENCH_HEAT_SCALE", "100000"))
    off1 = bench_runtime.run(n_groups=scale, lat_sample=0, heat=False,
                             hops=False)
    on1 = bench_runtime.run(n_groups=scale, lat_sample=64, heat=True,
                            hops=True)
    on2 = bench_runtime.run(n_groups=scale, lat_sample=64, heat=True,
                            hops=True)
    off2 = bench_runtime.run(n_groups=scale, lat_sample=0, heat=False,
                             hops=False)
    assert on1["heat"]["enabled"] and not off1["heat"]["enabled"], \
        "A/B heat pins did not take"
    on_cps = (on1["value"] + on2["value"]) / 2
    off_cps = (off1["value"] + off2["value"]) / 2
    overhead = 1.0 - on_cps / max(off_cps, 1)
    res = {
        "scale": scale,
        "platform": "cpu",
        "heat_overhead": round(overhead, 4),
        "attributed_commits_per_sec": round(on_cps),
        "bare_commits_per_sec": round(off_cps),
        "order": "ABBA (off, on, on, off)",
        "active_set": on1["heat"].get("active_set"),
        "attributed": [on1, on2],
        "bare": [off1, off2],
    }
    save_artifact(res, note="BENCH_HEAT stage: fleet-attribution "
                            "overhead A/B")
    emit({
        "metric": f"fleet-attribution overhead @{scale // 1000}k groups "
                  f"(heat lanes + 1/64 sampling + hop tracing vs all "
                  f"off, durable runtime, loopback)",
        "value": round(overhead * 100, 2),
        "unit": "% durable commits/sec regression (target <2%)",
        "vs_baseline": None,
        "attributed_commits_per_sec": round(on_cps),
        "bare_commits_per_sec": round(off_cps),
        "active_set": on1["heat"].get("active_set"),
    })
    assert overhead < 0.02, (
        f"attribution plane costs {overhead * 100:.2f}% durable "
        f"throughput (budget: 2%) — attributed {on_cps:.0f} vs bare "
        f"{off_cps:.0f} commits/sec")


def headline(res: dict, fallback: str = "", tuned: bool = False,
             extra_note: str = "") -> dict:
    plat = res["platform"]
    tag = "" if plat == "cpu" else " on device"
    note = f" [CPU FALLBACK — {fallback}]" if fallback else ""
    note += TUNED_TAG if tuned else ""
    if res.get("nemesis"):
        note += " [NEMESIS: three-regime fault schedule on]"
    if res.get("trace_depth"):
        note += f" [TRACE: flight recorder on, depth {res['trace_depth']}]"
    note += f" [{extra_note}]" if extra_note else ""
    return {
        # "device engine, payload-free": the full consensus protocol
        # (elections, replication fan-out, quorum commit) but no WAL, no
        # payload bytes, no transport — the durable product path is
        # bench_runtime.py's separate metric; the two are NOT comparable.
        "metric": f"AppendEntries commits/sec @{res['scale'] // 1000}k Raft "
                  f"groups (3-node cluster, device engine, "
                  f"payload-free{tag}){note}",
        "value": round(res["cps"]),
        "unit": "commits/sec",
        "vs_baseline": round(res["cps"] / BASELINE_CPS, 3),
    }


def headline_reads(res: dict) -> dict:
    """The read-plane headline: linearizable reads/sec under a mixed
    90/10 read/write load.  A SEPARATE metric from the commits/sec
    ladder — reads bypass the log, so the two are not directly
    comparable; its baseline is the mix-implied read throughput AT the
    commits baseline (90/10 mix at BASELINE_CPS writes = 9x reads), so
    vs_baseline == 1.0 means the read plane keeps pace with a
    baseline-rate write stream, not a unit-mismatched commits ratio."""
    plat = res["platform"]
    tag = "" if plat == "cpu" else " on device"
    return {
        "metric": f"linearizable reads/sec @{res['scale'] // 1000}k Raft "
                  f"groups (ReadIndex+lease, mixed {res['read_mix']} "
                  f"read/write, 3-node cluster, device engine{tag}) "
                  f"[writes rode along at {round(res['cps'])} commits/sec]",
        "value": round(res["rps"]),
        "unit": "reads/sec",
        "vs_baseline": round(res["rps"] / (9 * BASELINE_CPS), 3),
    }


def emit(line: dict) -> None:
    print(json.dumps(line), flush=True)


def save_artifact(res: dict, child_env: dict | None = None,
                  extra_env: dict | None = None, note: str = "") -> None:
    """Persist one successful scale's raw result as a committed-to-repo
    artifact: artifacts/bench_<platform>_<scale>_<seq>.json.  The r1-r4
    story was device numbers living only in README prose / commit messages
    — driver capture windows hit tunnel wedges and banked nothing.  With
    every successful run writing its raw result + config + env knobs to a
    file the builder commits, a TPU ladder survives as auditable evidence
    no matter what the capture window later sees (the reference's
    verification ethos is artifact-driven, /root/reference/README.md:28-33).
    Best-effort: artifact IO must never kill the bench itself."""
    try:
        os.makedirs(ARTIFACT_DIR, exist_ok=True)
        stem = f"bench_{res.get('platform', 'unknown')}_{res.get('scale', 0)}"
        seq = 0
        while os.path.exists(
                os.path.join(ARTIFACT_DIR, f"{stem}_{seq:03d}.json")):
            seq += 1
        doc = {
            "result": res,
            "note": note,
            "seed": 0,                       # DeviceCluster(cfg, seed=0)
            "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            # The CHILD's effective environment, not the parent's: the
            # fallback child is env-pinned to cpu and a device child may
            # have had a cpu pin dropped — recording os.environ would
            # misstate the platform for exactly the runs that matter.
            "env": {k: v for k, v in (child_env or os.environ).items()
                    if k.startswith("BENCH_") or k == "JAX_PLATFORMS"},
            "extra_env": extra_env or {},
            "argv": sys.argv[1:],
        }
        path = os.path.join(ARTIFACT_DIR, f"{stem}_{seq:03d}.json")
        with open(path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        sys.stderr.write(f"[bench] artifact saved: {path}\n")
    except OSError as e:
        sys.stderr.write(f"[bench] artifact save failed: {e}\n")


def run_scale(n_groups: int, measure_ticks: int, warmup_ticks: int,
              timeout_s: float, platform: str = "",
              profile_dir: str = "", extra_env: dict | None = None
              ) -> dict | None:
    """Run one scale in a subprocess; return its result dict or None."""
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           str(n_groups), str(measure_ticks), str(warmup_ticks), platform,
           profile_dir]
    env = dict(os.environ)
    if extra_env:
        env.update(extra_env)
    env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__)) + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    if not platform:
        # A device-scale child must reach the accelerator: drop only a
        # leftover CPU pin, keep an explicit accelerator pin (the one
        # shared rule — see the helper's docstring).
        from __graft_entry__ import _drop_cpu_pin
        _drop_cpu_pin(env)
    elif platform == "cpu":
        # The last-resort fallback must be wedge-proof: the in-child
        # programmatic pin (child_run) is NOT sufficient when the tunnel's
        # sitecustomize pre-imports jax — the r4 tail shows exactly this
        # child stuck in jax.devices() and the whole artifact came out
        # empty.  Pin the env TOO, byte-for-byte the working pattern of
        # __graft_entry__.dryrun_multichip's CPU-mesh subprocess.
        env["JAX_PLATFORMS"] = "cpu"
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=timeout_s, env=env)
    except subprocess.TimeoutExpired as e:
        # Keep the child's faulthandler watchdog dump — it is the only
        # evidence of WHERE the hang was.
        tail = ""
        if isinstance(e.stderr, (bytes, str)):
            s = e.stderr.decode(errors="replace") if isinstance(e.stderr, bytes) else e.stderr
            tail = "\n".join(s.splitlines()[-25:])
        sys.stderr.write(f"[bench] scale {n_groups}: TIMEOUT after "
                         f"{timeout_s:.0f}s\n{tail}\n")
        run_scale.last_failure = f"device child timed out ({timeout_s:.0f}s)"
        return None
    if r.returncode != 0:
        tail = r.stderr.strip().splitlines()[-12:]
        sys.stderr.write(f"[bench] scale {n_groups}: rc={r.returncode}\n" +
                         "\n".join(tail) + "\n")
        run_scale.last_failure = f"device child failed rc={r.returncode}"
        return None
    try:
        res = json.loads(r.stdout.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError):
        sys.stderr.write(f"[bench] scale {n_groups}: unparseable output: "
                         f"{r.stdout[-500:]!r}\n")
        return None
    save_artifact(res, child_env=env, extra_env=extra_env)
    return res


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        n_groups, ticks, warmup = map(int, sys.argv[2:5])
        platform = sys.argv[5] if len(sys.argv) > 5 else ""
        profile_dir = sys.argv[6] if len(sys.argv) > 6 else ""
        print(json.dumps(child_run(n_groups, ticks, warmup, platform,
                                   profile_dir)))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--member-child":
        print(json.dumps(member_child(int(sys.argv[2]))))
        return
    if env_flag("BENCH_MEMBER"):
        # The membership stage replaces the ladder (like a pinned
        # BENCH_READS run measures reads): reconfig walk-through
        # throughput + the masked-vs-fixed commit kernel A/B.
        run_member_ladder()
        return
    if env_flag("BENCH_LAT"):
        # The latency-plane overhead A/B replaces the ladder: durable
        # commits/sec with 1/64 span sampling vs off (<2% budget).
        run_latency_ab()
        return
    if env_flag("BENCH_HEAT"):
        # The fleet-attribution overhead A/B replaces the ladder:
        # durable commits/sec with heat lanes + sampling + hop tracing
        # vs all off (<2% budget).
        run_heat_ab()
        return
    if env_flag("BENCH_OPENLOOP"):
        # The overload stage replaces the ladder: open-loop rate sweep
        # with admission control on vs force-disabled (no-collapse A/B).
        run_openloop_stage()
        return
    if env_flag("BENCH_TXN"):
        # The transaction stage replaces the ladder: cross-group 2PC
        # transfers/sec + abort rate vs the independent-writes bound.
        run_txn_stage()
        return

    profile_dir = os.environ.get("BENCH_PROFILE_DIR", "")
    only = int(sys.argv[1]) if len(sys.argv) > 1 else None
    scales = [only] if only else list(SCALES)
    smoke_timeout = float(os.environ.get("BENCH_SMOKE_TIMEOUT", "420"))
    scale_timeout = float(os.environ.get("BENCH_SCALE_TIMEOUT", "300"))
    # Global wall budget: keep the whole ladder inside the driver's window
    # even if several scales burn their full timeout.
    # The healthy-TPU ladder measures ~1300 s end to end (r4); leave room
    # for the tuned bonus stage on top.
    budget = float(os.environ.get("BENCH_TOTAL_BUDGET", "2200"))
    t_start = time.monotonic()

    # Pre-probe the device tunnel (throwaway subprocess under a hard
    # timeout — a wedged backend hangs jax.devices() forever, and that
    # hang is exactly what starved r4's fallback: every ladder child
    # burned its full timeout against a known-dead backend until the
    # driver's window closed with an EMPTY artifact).  One bounded-backoff
    # retry covers a transient wedge; if the tunnel is down both times the
    # device ladder is skipped entirely and the CPU fallback runs with
    # plenty of budget left.
    from __graft_entry__ import _PROBE, _probe_default_backend
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        device_ok = False
        probe_why = "operator pinned JAX_PLATFORMS=cpu"
    else:
        count, plat = _probe_default_backend()
        if count == 0:
            backoff = float(os.environ.get("BENCH_PROBE_BACKOFF", "45"))
            sys.stderr.write(f"[bench] device probe failed; one retry in "
                             f"{backoff:.0f}s\n")
            time.sleep(backoff)
            _PROBE.clear()
            count, plat = _probe_default_backend()
        device_ok = count > 0
        probe_why = (f"device probe: {count} x {plat or 'none'}" if count
                     else "device backend unreachable (probe timed out "
                          "twice, bounded backoff between)")
    sys.stderr.write(f"[bench] {probe_why}\n")

    best = None
    best_is_tuned = False
    # The extra env AND run shape (ticks, warmup) that produced `best` —
    # any later stage whose number is COMPARED against best (the
    # flight-recorder A/B) must re-run identically, or the ratio
    # conflates config / run-length effects with stage overhead.
    best_env: dict = {}
    best_shape = (512, 128)
    if not device_ok:
        scales = []   # straight to the CPU fallback below
        run_scale.last_failure = probe_why
    for i, g in enumerate(scales):
        is_smoke = (i == 0 and only is None)
        timeout_s = smoke_timeout if i == 0 else scale_timeout
        remaining = budget - (time.monotonic() - t_start)
        if remaining < timeout_s * 0.5:
            sys.stderr.write(f"[bench] budget exhausted before scale {g}\n")
            break
        ticks, warmup = (64, 32) if is_smoke else (512, 128)
        res = run_scale(g, ticks, warmup, min(timeout_s, remaining),
                        profile_dir="" if is_smoke else profile_dir)
        if res is None:
            if best is None and i == 0:
                break   # smoke failed: CPU fallback below
            # A mid-ladder failure costs that scale only (bounded by its
            # timeout): larger scales may still succeed.
            continue
        best = res
        best_shape = (ticks, warmup)
        sys.stderr.write(f"[bench] scale {g}: {res['cps']:,.0f} commits/s "
                         f"({res['platform']}, warmup {res['warmup_s']}s)\n")
        emit(headline(best))

    if best is None:
        # Device ladder skipped (dead tunnel) or its smoke scale failed.
        # Emit a CPU number so the artifact is NEVER empty; the child is
        # env-pinned to CPU (see run_scale) so a wedged tunnel cannot hang
        # it, and the probe-first structure means nearly the whole budget
        # is still available here.
        sys.stderr.write("[bench] device unreachable — CPU fallback\n")
        fb_scale = only if only else 100_000
        fb_timeout = max(
            60, min(300, budget - (time.monotonic() - t_start)))
        # Tuned pipeline budget, applied all-or-nothing: mixing tuned
        # values with operator-pinned ones could produce an invalid hybrid
        # (e.g. batch > log_slots) and kill the last-resort fallback.
        tuned = ({} if any(k in os.environ for k in TUNED_ENV)
                 else TUNED_ENV)
        why = getattr(run_scale, "last_failure", "device unreachable")
        res = run_scale(fb_scale, 96, 48, fb_timeout, platform="cpu",
                        extra_env=tuned)
        if res is not None:
            best = res
            best_is_tuned = bool(tuned)
            best_env = dict(tuned)
            best_shape = (96, 48)
            emit(headline(best, fallback=why, tuned=bool(tuned)))

    if best is None:
        emit({"metric": "AppendEntries commits/sec (no scale survived — "
                        "device and CPU fallback both failed)",
              "value": 0, "unit": "commits/sec", "vs_baseline": 0.0})
        sys.exit(1)

    # Bonus stages: the conservative number is banked; if the top scale
    # passed, try better configurations and publish whichever wins, tagged
    # so the artifact records which config produced it.
    #
    # 1. Pallas quorum kernel — same per-tick cost as the main ladder
    #    (fits the normal scale timeout) and measured +6% over inline jnp
    #    at 16k on TPU (r4 A/B).  Device only: on CPU the kernel runs
    #    interpret-mode at 1000x cost.
    # 2. Tuned pipeline budget (S=32/B=32/L=256) — 2x+ on CPU.  CPU-only:
    #    on device at the top scale its 4x per-tick work cannot fit any
    #    reasonable deadline (r4 rehearsal: timed out at 256 ticks/420 s
    #    while the Pallas stage had already improved the headline).
    def bonus(extra_env, tag, ticks, warmup, timeout_s):
        nonlocal best, best_env
        remaining = budget - (time.monotonic() - t_start)
        if remaining < timeout_s * 0.4:
            return
        res = run_scale(best["scale"], ticks, warmup,
                        min(timeout_s, remaining),
                        profile_dir=profile_dir, extra_env=extra_env)
        if res is not None and res["cps"] > best["cps"]:
            sys.stderr.write(f"[bench] {tag}: {res['cps']:,.0f} commits/s\n")
            emit(headline(res, tuned=(extra_env is TUNED_ENV),
                          extra_note="" if extra_env is TUNED_ENV else tag))
            best = res
            best_env = dict(extra_env)
            best_shape = (ticks, warmup)

    if (scales and best["scale"] == scales[-1] and only is None
            and not best_is_tuned):
        bonus_timeout = float(os.environ.get("BENCH_BONUS_TIMEOUT", "420"))
        if (best["platform"] != "cpu"
                and "BENCH_USE_PALLAS" not in os.environ):
            bonus({"BENCH_USE_PALLAS": "1"}, "pallas quorum kernel",
                  512, 128, scale_timeout)
        if (best["platform"] == "cpu"
                and not any(k in os.environ for k in TUNED_ENV)):
            bonus(TUNED_ENV, "tuned budget", 96, 48, bonus_timeout)

    # Read-plane stage: linearizable reads/sec (mixed 90/10 read/write,
    # ReadIndex + lease) at the best surviving scale — a SEPARATE headline
    # that never replaces the commits/sec number.  Skipped when the
    # operator pinned BENCH_READS (then the whole ladder measured reads)
    # or BENCH_NEMESIS (the flags are mutually exclusive in the child).
    if (best is not None and "BENCH_READS" not in os.environ
            and "BENCH_NEMESIS" not in os.environ):
        remaining = budget - (time.monotonic() - t_start)
        rd_timeout = float(os.environ.get("BENCH_READS_TIMEOUT", "300"))
        if remaining >= rd_timeout * 0.4:
            ticks, warmup = ((512, 128) if best["platform"] != "cpu"
                             else (96, 48))
            res = run_scale(best["scale"], ticks, warmup,
                            min(rd_timeout, remaining),
                            platform="cpu" if best["platform"] == "cpu"
                            else "",
                            extra_env={"BENCH_READS": "1"})
            if res is not None and "rps" in res:
                sys.stderr.write(f"[bench] read plane: "
                                 f"{res['rps']:,.0f} reads/s "
                                 f"({res['lease_hits']} lease hits)\n")
                emit(headline_reads(res))
    elif best is not None and "rps" in best:
        # Operator-pinned BENCH_READS ladder: the banked headline above
        # was commits/sec — emit the reads/sec number it was run for.
        emit(headline_reads(best))

    # Faults-on stage: commits/sec under the standard nemesis schedule at
    # the best surviving scale — a SEPARATE headline (chaos throughput is
    # not comparable to the healthy number, so it never replaces `best`).
    # Skipped when the operator already pinned BENCH_NEMESIS (then the
    # whole ladder above was the faults-on run) or BENCH_READS (the child
    # refuses the flag combination).
    if (best is not None and "BENCH_NEMESIS" not in os.environ
            and "BENCH_READS" not in os.environ):
        remaining = budget - (time.monotonic() - t_start)
        nem_timeout = float(os.environ.get("BENCH_NEMESIS_TIMEOUT", "300"))
        if remaining >= nem_timeout * 0.4:
            ticks, warmup = ((512, 128) if best["platform"] != "cpu"
                             else (96, 48))
            res = run_scale(best["scale"], ticks, warmup,
                            min(nem_timeout, remaining),
                            platform="cpu" if best["platform"] == "cpu"
                            else "",
                            extra_env={"BENCH_NEMESIS": "1"})
            if res is not None:
                sys.stderr.write(f"[bench] nemesis faults-on: "
                                 f"{res['cps']:,.0f} commits/s\n")
                emit(headline(res))

    # Flight-recorder overhead stage (BENCH_TRACE=1 in the child): the
    # same ladder load with cfg.trace_depth event rings compiled into the
    # scan, compared against the banked traceless number — the "tracing
    # is cheap enough to leave on" evidence (acceptance: <= 5% commits/sec
    # regression).  vs_baseline here is with-trace / without-trace, so
    # 0.95+ passes.  Skipped when the operator pinned any stage flag (a
    # pinned ladder already measured what they asked for).
    if (best is not None and "BENCH_TRACE" not in os.environ
            and "BENCH_READS" not in os.environ
            and "BENCH_NEMESIS" not in os.environ):
        remaining = budget - (time.monotonic() - t_start)
        tr_timeout = float(os.environ.get("BENCH_TRACE_TIMEOUT", "300"))
        if remaining >= tr_timeout * 0.4:
            ticks, warmup = best_shape
            res = run_scale(best["scale"], ticks, warmup,
                            min(tr_timeout, remaining),
                            platform="cpu" if best["platform"] == "cpu"
                            else "",
                            # Same config AND run shape that produced
                            # `best`, plus the recorder — the ratio
                            # isolates trace cost.
                            extra_env={**best_env, "BENCH_TRACE": "1"})
            if res is not None:
                ratio = res["cps"] / best["cps"]
                sys.stderr.write(
                    f"[bench] flight recorder on: {res['cps']:,.0f} "
                    f"commits/s ({(1 - ratio) * 100:+.1f}% overhead, "
                    f"{res.get('trace_events', 0)} events)\n")
                emit({
                    "metric": f"flight-recorder overhead "
                              f"@{res['scale'] // 1000}k Raft groups: "
                              f"commits/sec with trace_depth="
                              f"{res['trace_depth']} vs "
                              f"{round(best['cps'])} without "
                              f"({res['platform']})",
                    "value": round(res["cps"]),
                    "unit": "commits/sec",
                    "vs_baseline": round(ratio, 3),
                })


if __name__ == "__main__":
    main()
