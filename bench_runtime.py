#!/usr/bin/env python
"""Durable-runtime benchmark: commits/sec through the FULL node stack.

Unlike ``bench.py`` (the headline device-sim kernel number), this drives
the product path users actually run: real RaftNodes with WAL durability
(persist-before-send barrier), state-machine applies, snapshots/compaction
maintenance and the loopback transport, across a 3-node in-process cluster.

Prints one JSON line per scale; the host runtime is the subject, so the
engine is pinned to CPU by default (pass --default-backend to benchmark the
runtime over a real accelerator engine — and note a wedged TPU plugin hangs
at backend init, the exact failure bench.py's ladder defends against).

Usage: bench_runtime.py [n_groups ...] [--default-backend]
"""

import json
import shutil
import sys
import tempfile
import time

import numpy as np


def run(n_groups: int = 1024, rounds: int = 60) -> dict:
    from rafting_tpu.core.types import EngineConfig, LEADER
    from rafting_tpu.machine.spi import MachineProvider, RaftMachine
    from rafting_tpu.testkit.harness import LocalCluster

    class NullMachine(RaftMachine):
        """Counts applies; no per-entry I/O so the bench measures the
        framework (WAL + engine + transport), not fixture file appends."""

        def __init__(self):
            self._applied = 0

        def last_applied(self) -> int:
            return self._applied

        def apply(self, index: int, payload: bytes):
            self._applied = index
            return index

        def checkpoint(self, must_include: int):
            import os
            import tempfile as tf
            from rafting_tpu.machine.spi import Checkpoint
            fd, path = tf.mkstemp()
            os.write(fd, str(self._applied).encode())
            os.close(fd)
            return Checkpoint(path=path, index=self._applied)

        def recover(self, ckpt) -> None:
            with open(ckpt.path) as f:
                self._applied = int(f.read() or 0)

        def close(self) -> None:
            pass

        def destroy(self) -> None:
            pass

    class NullProvider(MachineProvider):
        def __init__(self, _root):
            pass

        def bootstrap(self, group: int) -> RaftMachine:
            return NullMachine()

    cfg = EngineConfig(n_groups=n_groups, n_peers=3, log_slots=64, batch=8,
                       max_submit=8, election_ticks=10, heartbeat_ticks=3,
                       rpc_timeout_ticks=8)
    root = tempfile.mkdtemp(prefix="bench-runtime-")
    c = LocalCluster(cfg, root, provider_factory=NullProvider, seed=0)
    payload = b"x" * 64
    try:
        c.wait_leader(0, max_rounds=300)
        c.tick(20)
        leaders = np.array([c.leader_of(g) if c.leader_of(g) is not None
                            else -1 for g in range(n_groups)])
        assert (leaders >= 0).all()

        burst = [payload] * cfg.max_submit

        def offer():
            # Dense load at the design point: fill every group's per-tick
            # acceptance budget (max_submit) through the batch API (one
            # future + one lock acquisition per group per round).
            for g in range(n_groups):
                n = c.nodes[int(leaders[g])]
                if n.h_role[g] == LEADER and n.h_ready[g]:
                    n.submit_batch(g, burst)

        # Warmup.
        for _ in range(5):
            offer()
            c.tick(1)
        start = sum(int(n.h_commit.astype(np.int64).sum())
                    for n in c.nodes.values()) / len(c.nodes)
        t0 = time.perf_counter()
        for _ in range(rounds):
            offer()
            c.tick(1)
        elapsed = time.perf_counter() - t0
        end = sum(int(n.h_commit.astype(np.int64).sum())
                  for n in c.nodes.values()) / len(c.nodes)
        commits = end - start
        return {
            "metric": f"durable-runtime commits/sec @{n_groups} groups "
                      "(3 nodes, WAL fsync barrier, applies, loopback)",
            "value": round(commits / elapsed),
            "unit": "commits/sec",
            "vs_baseline": None,
        }
    finally:
        c.close()
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    args = sys.argv[1:]
    if "--default-backend" in args:
        args.remove("--default-backend")
    else:
        import jax
        jax.config.update("jax_platforms", "cpu")
    scales = [int(a) for a in args] or [1024]
    for n in scales:
        print(json.dumps(run(n_groups=n)), flush=True)
