#!/usr/bin/env python
"""Durable-runtime benchmark: commits/sec through the FULL node stack.

Unlike ``bench.py`` (the headline device-engine number, payload-free), this
drives the product path users actually run: real RaftNodes with WAL
durability (persist-before-send barrier), state-machine applies,
snapshot/compaction maintenance and the loopback transport, across a
3-node in-process cluster.  Nodes tick sequentially in one thread —
threading them was measured 2x SLOWER (three jax host programs sharing
one GIL + oversubscribed XLA threadpools); a real deployment runs one
process per node, so the honest single-process number is per-node cost x
3, not a thread-contended mess.  The output carries the slowest node's
tick-latency histogram so host-path stalls are visible, not averaged
away.

Offered load is shaped like the BASELINE scale story: dense per group at
small group counts, aggregate-heavy / per-group-light at 32k-100k (the
100k-group regime is many quiet groups, not 100k firehoses — per-group
rate at the 1M/s target is ~10 commits/s/group).

Prints one JSON line per scale.  The host runtime is the subject, so the
engine is pinned to CPU by default (pass --default-backend to benchmark
the runtime over a real accelerator engine).

Usage: bench_runtime.py [n_groups ...] [--default-backend]
"""

import json
import shutil
import sys
import tempfile
import time

import numpy as np


def _shape(n_groups: int):
    """(per-group burst, measured rounds, log_slots) per scale: dense at
    small G, aggregate-heavy at large G (the 100k regime is many quiet
    groups — per-group rate at the 1M/s aggregate target is ~10
    commits/s).  log_slots grows with scale because sustained acceptance
    is bounded by checkpoint-throughput x ring-capacity / n_groups
    (see RaftNode.max_checkpoints_per_tick): a 256-slot ring at 100k
    groups caps the drain far below the offered load no matter how fast
    the host tier gets.  Device-ring cost of L=1024 at 100k groups is
    ~400MB per node — HBM-realistic for the v5e target."""
    if n_groups <= 8_192:
        return 32, 40, 1024
    if n_groups <= 32_768:
        return 8, 25, 512
    return 8, 12, 1024


def run(n_groups: int = 1024, rounds: int = 0, burst_n: int = 0,
        transport: str = "loopback", pipeline=None,
        host_workers=None, native=None, lat_sample=None,
        heat=None, hops=None) -> dict:
    """``pipeline``: True/False forces the durable pipeline on/off for
    every node; None uses the runtime default (RAFT_PIPELINE env if set,
    else on only for accelerator engine backends — see RaftNode).
    ``host_workers``: striped host tier width per node (None = the
    runtime default, env RAFT_HOST_WORKERS else 1 = serial).
    ``native``: True/False pins the C++ stage_and_sync host tier on/off
    via RAFT_NATIVE_HOST for the run; None = runtime auto-selection
    (native whenever the .so loads).
    ``lat_sample``: pins RAFT_LAT_SAMPLE (1/N span sampling; 0 disables
    the latency plane entirely) for the run; None = env default.  When
    the plane is on, the result carries per-entry commit-path latency
    distributions (e2e + per-phase), not just throughput.
    ``heat``: True/False compiles the per-group heat lanes
    (EngineConfig.heat — device activity counters + host heat registry)
    in/out; None = off (the config default).
    ``hops``: True/False pins RAFT_HOP_TRACE (cross-node hop tracing)
    on/off for the run; None = env default (on)."""
    from rafting_tpu.core.types import EngineConfig, LEADER
    from rafting_tpu.testkit.fixtures import NullProvider
    from rafting_tpu.testkit.harness import LocalCluster

    d_burst, d_rounds, d_slots = _shape(n_groups)
    burst_n = burst_n or d_burst
    rounds = rounds or d_rounds

    # The tuned pipeline budget (S=32/B=32/L=256, the 32k-group sweep from
    # bench.py's bonus stage): more commits per Python-visited group per
    # tick, which is exactly what the host tier's O(groups-visited) cost
    # structure wants.  (L=1024 was measured and does NOT help — the cap
    # is host per-entry work, not ring/compaction coupling.)  BENCH_RT_*
    # env knobs override.
    import os
    slots = int(os.environ.get("BENCH_RT_SLOTS", str(d_slots)))
    cfg = EngineConfig(
        n_groups=n_groups, n_peers=3, log_slots=slots,
        batch=int(os.environ.get("BENCH_RT_BATCH", "32")),
        max_submit=int(os.environ.get("BENCH_RT_SUBMIT", "32")),
        election_ticks=10, heartbeat_ticks=3, rpc_timeout_ticks=8,
        heat=bool(heat))
    root = tempfile.mkdtemp(prefix="bench-runtime-")
    pins = {}
    if native is not None:
        pins["RAFT_NATIVE_HOST"] = "1" if native else "0"
    if lat_sample is not None:
        pins["RAFT_LAT_SAMPLE"] = str(lat_sample)
    if hops is not None:
        pins["RAFT_HOP_TRACE"] = "1" if hops else "0"
    env_prev = {k: os.environ.get(k) for k in pins}
    os.environ.update(pins)
    try:
        c = LocalCluster(cfg, root, provider_factory=NullProvider, seed=0,
                         transport=transport, pipeline=pipeline,
                         host_workers=host_workers)
    finally:
        for k, v in env_prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    payload = b"x" * 64
    burst = [payload] * burst_n

    def tick_round():
        for n in c.nodes.values():
            n.tick()

    def offer():
        # Fill every led+ready group's per-round budget through the BULK
        # batch API: one arena build + one lock acquisition per node for
        # the whole fan-out (the per-group submit_batch loop was ~100k
        # calls/round at the top scale — ~30% of the durable tick).
        for n in c.nodes.values():
            mask = (n.h_role == LEADER) & n.h_ready
            n.submit_batch_many(np.nonzero(mask)[0], burst)

    try:
        c.wait_leader(0, max_rounds=300)
        # Settle until EVERY group elected (condition-driven: the
        # pipelined runtime adds one tick of message latency, so a fixed
        # settle count that worked serially under-waits at 32k+ groups).
        for _ in range(40):
            c.tick(5)
            roles = np.stack([m.h_role for m in c.nodes.values()])
            if (roles == LEADER).any(axis=0).all():
                break
        leaders = np.array([c.leader_of(g) if c.leader_of(g) is not None
                            else -1 for g in range(n_groups)])
        assert (leaders >= 0).all()

        # Warmup (also compiles every jit variant the loop will hit).
        for _ in range(5):
            offer()
            tick_round()
        # The reported latency histogram covers the MEASURE phase only:
        # election warmup + first-tick XLA compiles are one-time costs
        # (tens of seconds on CPU at 100k groups) that otherwise own the
        # p99 of a 15-round run and bury the steady-state number the
        # durable tier is actually judged on.
        for n in c.nodes.values():
            n.metrics.histogram("tick_latency_s").reset()
            for stage in n.metrics.breakdown():
                n.metrics.histogram(f"tick_stage_{stage}").reset()
            # Per-entry latency distributions are measure-phase only too
            # (a warmup span that waited out an election would own p999).
            for name in list(n.metrics._histograms):
                if name.startswith("lat_"):
                    n.metrics.histogram(name).reset()
            # Windowed-rate baseline: rates(since_last=True) below then
            # reports measure-phase throughput, not a lifetime average
            # diluted by election warmup + compile ticks.
            n.metrics.checkpoint()
        start = sum(int(n.h_commit.astype(np.int64).sum())
                    for n in c.nodes.values()) / len(c.nodes)
        t0 = time.perf_counter()
        for _ in range(rounds):
            offer()
            tick_round()
        elapsed = time.perf_counter() - t0
        end = sum(int(n.h_commit.astype(np.int64).sum())
                  for n in c.nodes.values()) / len(c.nodes)
        commits = end - start
        lat = {}
        for n in c.nodes.values():
            h = n.metrics.histogram("tick_latency_s")
            if h.n and (not lat or h.quantile(0.5) > lat.get("p50_s", 0)):
                lat = {"p50_s": round(h.quantile(0.5), 5),
                       "p99_s": round(h.quantile(0.99), 5),
                       "max_s": round(h.max, 4),
                       "ticks": h.n}
        # Measure-window rates from the checkpointed registries (the
        # "commits" counter is the absolute frontier, so its windowed
        # delta/sec is a per-node commits/sec cross-check of the headline;
        # applies/sec is the state-machine drain the aggregate hides).
        applies_ps = max((n.metrics.rates(since_last=True)
                          .get("applies_per_sec", 0.0))
                         for n in c.nodes.values())
        # Per-stage tick breakdown (scan-wait / wal / fsync / send / apply
        # / maintain) from the slowest node — measure-phase only, mean
        # seconds per tick — so a regression shows WHERE the tick went,
        # not just that it got slower.  The same histograms back the
        # /metrics exposition (runtime/obsrv.py).
        slow = max(c.nodes.values(),
                   key=lambda n: n.metrics.histogram("tick_latency_s").total)
        stages = {k: round(v["mean"], 6)
                  for k, v in slow.metrics.breakdown().items()}
        # Per-entry commit-path latency distributions (the sampled span
        # plane, utils/latency.py) from the node with the most completed
        # spans — leadership is spread across nodes, so any single node
        # sees ~1/3 of the sampled population.
        latency = {"sample_rate": 0}
        lat_node = max(c.nodes.values(),
                       key=lambda n: n.metrics.histogram("lat_e2e_s").n)
        if lat_node._lat is not None:
            def _summ(name):
                h = lat_node.metrics._histograms.get(name)
                if h is None or not h.n:
                    return None
                s = h.summary()
                return {"count": s["count"], "mean_s": round(s["mean"], 6),
                        "p50_s": round(s["p50"], 6),
                        "p99_s": round(s["p99"], 6),
                        "p999_s": round(h.quantile(0.999), 6),
                        "max_s": round(s["max"], 6)}
            latency = {
                "sample_rate": lat_node._lat.rate,
                "counts": dict(lat_node._lat.counts),
                "e2e": _summ("lat_e2e_s"),
                "phases": {name: s for name in (
                    "submit_offer", "offer_stage", "stage_fsync",
                    "fsync_send", "send_commit", "commit_apply",
                    "apply_ack")
                    if (s := _summ(f"lat_{name}_s")) is not None},
            }
        return {
            "metric": f"durable-runtime commits/sec @{n_groups} groups "
                      f"(3 nodes, WAL fsync barrier, applies, {transport})",
            "value": round(commits / elapsed),
            "unit": "commits/sec",
            "vs_baseline": None,
            "burst_per_group": burst_n,
            "rounds": rounds,
            "pipeline": bool(slow.pipeline),
            "host_workers": int(slow._w_eff),
            "native_host": bool(slow._native_host),
            "native_workers": int(slow._w_native) if slow._native_host
                              else 0,
            "wal_shards": getattr(getattr(slow.store, "wal", None),
                                  "n_shards", 1),
            "tick_latency": lat,
            "tick_stages_mean_s": stages,
            "applies_per_sec_windowed": round(applies_ps),
            "latency": latency,
            "heat": ({"enabled": True,
                      "active_set": slow.heatmap_snapshot(8)
                      .get("active_set")}
                     if slow.heat is not None else {"enabled": False}),
        }
    finally:
        c.close()
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    args = sys.argv[1:]
    if "--default-backend" in args:
        args.remove("--default-backend")
    else:
        import jax
        jax.config.update("jax_platforms", "cpu")
    transport = "loopback"
    if "--tcp" in args:
        # Real localhost sockets: measures the transport plane's framing,
        # sender queues, reader threads and accumulator under durable
        # load (the reference system test's topology,
        # test/resources/raft1.xml:3-7).
        args.remove("--tcp")
        transport = "tcp"
    scales = [int(a) for a in args] or [1024]
    import os
    for n in scales:
        out = run(n_groups=n, transport=transport)
        print(json.dumps(out), flush=True)
        if os.environ.get("BENCH_PIPELINE", "") == "1":
            # Serial-vs-pipelined A/B at the same scale: the headline run
            # above used the backend-aware default, so only the OTHER
            # mode is re-run (forced explicitly — on a CPU host the
            # default is serial, and a None-vs-False comparison would
            # silently measure serial against itself).  The comparison
            # line reports the speedup plus both runs' per-stage tick
            # breakdowns.
            other = run(n_groups=n, transport=transport,
                        pipeline=not out["pipeline"])
            print(json.dumps(other), flush=True)
            piped, serial = ((out, other) if out["pipeline"]
                             else (other, out))
            print(json.dumps({
                "metric": f"durable pipeline speedup @{n} groups "
                          f"({transport})",
                "value": round(piped["value"] / max(serial["value"], 1), 3),
                "unit": "x (pipelined / serial commits/sec)",
                "pipelined_commits_per_sec": piped["value"],
                "serial_commits_per_sec": serial["value"],
                "pipelined_stages_mean_s": piped["tick_stages_mean_s"],
                "serial_stages_mean_s": serial["tick_stages_mean_s"],
            }), flush=True)
        if os.environ.get("BENCH_HOSTPAR", "") == "1":
            # Serial-vs-striped host tier A/B at the same scale: re-run
            # with host_workers forced to 1 (serial orchestration, the
            # pre-stripe behaviour), then W=2 and W=4 striped.  Each run
            # prints its own JSON line (per-stage tick breakdown included
            # — the striped runs report the max-across-workers stage
            # times, so stage sums can exceed wall tick time); the
            # comparison line is striped-vs-serial commits/sec.
            base = run(n_groups=n, transport=transport, host_workers=1)
            print(json.dumps(base), flush=True)
            for w in (2, 4):
                striped = run(n_groups=n, transport=transport,
                              host_workers=w)
                print(json.dumps(striped), flush=True)
                print(json.dumps({
                    "metric": f"striped host tier speedup @{n} groups "
                              f"(W={striped['host_workers']}, {transport})",
                    "value": round(striped["value"] /
                                   max(base["value"], 1), 3),
                    "unit": "x (striped / serial commits/sec)",
                    "striped_commits_per_sec": striped["value"],
                    "serial_commits_per_sec": base["value"],
                    "striped_stages_mean_s": striped["tick_stages_mean_s"],
                    "serial_stages_mean_s": base["tick_stages_mean_s"],
                }), flush=True)
        if os.environ.get("BENCH_NATIVE", "") == "1":
            # Native-vs-Python host tier A/B at the same scale: the C++
            # stage_and_sync path (GIL released, real OS threads) against
            # the pure-Python serial staging loop.  Both runs print their
            # own JSON line; the comparison line carries the per-backend
            # wal/fsync/send stage means — the tentpole's acceptance
            # metric is mean wal_s, not just the commits/sec headline
            # (which also folds in scan-wait and apply cost that the
            # native tier doesn't touch).
            py = run(n_groups=n, transport=transport, native=False,
                     host_workers=1)
            print(json.dumps(py), flush=True)
            nat = run(n_groups=n, transport=transport, native=True,
                      host_workers=4)
            print(json.dumps(nat), flush=True)

            def _st(d, k):
                return d["tick_stages_mean_s"].get(k, 0.0)
            print(json.dumps({
                "metric": f"native host tier wal speedup @{n} groups "
                          f"(W={nat['native_workers']}, {transport})",
                "value": round(_st(py, "wal_s") /
                               max(_st(nat, "wal_s"), 1e-9), 3),
                "unit": "x (python wal_s / native wal_s, mean per tick)",
                "native_commits_per_sec": nat["value"],
                "python_commits_per_sec": py["value"],
                "native": {k: _st(nat, k)
                           for k in ("wal_s", "fsync_s", "send_s")},
                "python": {k: _st(py, k)
                           for k in ("wal_s", "fsync_s", "send_s")},
            }), flush=True)
