#!/usr/bin/env python
"""Bisect the >=32k-group TPU fault (VERDICT r3 weak #1).

Runs ONE configuration in-process (invoke per-config in a subprocess; a
kernel fault kills the child, not the sweep):

    python tools/bisect_tpu.py <n_groups> <group_block> <donate:0|1> \
        [n_calls] [ticks]

Prints one JSON line with the outcome.  The r3 ladder showed warmup (first
call) SUCCEEDS at 65k and the measure (second, donated-buffer) call faults
UNAVAILABLE — so the sweep separates (a) program size per block, (b) buffer
donation, (c) call count.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    n_groups = int(sys.argv[1])
    block = int(sys.argv[2])
    donate = bool(int(sys.argv[3]))
    n_calls = int(sys.argv[4]) if len(sys.argv) > 4 else 2
    ticks = int(sys.argv[5]) if len(sys.argv) > 5 else 128

    import faulthandler
    faulthandler.enable()
    faulthandler.dump_traceback_later(300, exit=False)

    import jax
    import jax.numpy as jnp
    import numpy as np
    from functools import partial
    from rafting_tpu import DeviceCluster, EngineConfig
    from rafting_tpu.core import sim

    dev = jax.devices()[0]
    cfg = EngineConfig(n_groups=n_groups, n_peers=3, log_slots=64, batch=8,
                       max_submit=8, election_ticks=10, heartbeat_ticks=3,
                       rpc_timeout_ticks=8, pre_vote=True)

    if donate:
        fn = (partial(sim.run_cluster_ticks_blocked, group_block=block)
              if block < n_groups else sim.run_cluster_ticks)
    else:
        # Re-jit the underlying functions WITHOUT donate_argnums.
        if block < n_groups:
            raw = partial(jax.jit, static_argnums=(0, 1, 7))(
                sim.run_cluster_ticks_blocked.__wrapped__)
            fn = partial(raw, group_block=block)
        else:
            fn = partial(jax.jit, static_argnums=(0, 1))(
                sim.run_cluster_ticks.__wrapped__)

    c = DeviceCluster(cfg, seed=0)
    submit = jnp.full((3, n_groups), cfg.max_submit, jnp.int32)
    states, inflight, info = c.states, c.inflight, c.last_info
    out = {"n_groups": n_groups, "block": block, "donate": donate,
           "platform": dev.platform, "calls": []}
    for k in range(n_calls):
        t0 = time.perf_counter()
        states, inflight, info = fn(cfg, ticks, states, inflight, info,
                                    c.conn, submit)
        jax.block_until_ready(states.commit)
        out["calls"].append(round(time.perf_counter() - t0, 2))
    out["commits"] = int(np.asarray(states.commit).max(axis=0)
                         .astype(np.int64).sum())
    out["ok"] = True
    faulthandler.cancel_dump_traceback_later()
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
