#!/usr/bin/env python
"""Decode a flight-recorder dump into per-group event timelines.

Post-mortem half of the observability plane: a run that went wrong saves
its raw device event rings with ``rafting_tpu.utils.tracelog.save_dump``
(a JSON artifact under ``artifacts/`` by convention), and this CLI turns
them back into the human timeline — which replica did what, when — with
no engine, device, or live process required.

Usage:
    tools/dump_timeline.py DUMP.json [--group G] [--node N] [--json]

With ``--group`` omitted, every group with events is printed.  ``--node``
selects the node axis of a stacked [N, G, D] cluster dump (default 0).
``--json`` emits machine-readable output instead of the table.

Dumps saved with ``meta={"latency": node.latency_snapshot()}`` also
carry the PR 13 latency plane: sampled lifecycle spans interleave with
the group's flight-recorder events on the shared tick axis (a span
prints after the last event at or before its accept tick), and the
striped host tier's per-worker utilization intervals print per tick.
Use tools/latency_report.py for the percentile/SLO view of the same
snapshot.
"""

import argparse
import importlib.util
import json
import os
import sys

sys.path.insert(0, ".")


def _load_tracelog():
    """Load the decoder module by FILE PATH, not via the package: the
    package __init__ imports the whole engine (jax/flax), and the whole
    point of this CLI is decoding on a box that has neither."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "rafting_tpu", "utils", "tracelog.py")
    spec = importlib.util.spec_from_file_location("_tracelog_standalone",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _print_span(sp: dict) -> None:
    phases = " ".join(f"{k}={v * 1e3:.3f}ms"
                      for k, v in (sp.get("phases") or {}).items())
    print(f"  span  tick {sp.get('tick', -1):<8d} seq {sp.get('seq')} "
          f"{sp.get('kind')} idx={sp.get('idx')} "
          f"[{sp.get('outcome')}] {phases}")


def main(argv=None) -> int:
    tracelog = _load_tracelog()
    decode_group, load_dump = tracelog.decode_group, tracelog.load_dump

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dump", help="JSON dump written by tracelog.save_dump")
    ap.add_argument("--group", type=int, default=None,
                    help="decode one group (default: all with events)")
    ap.add_argument("--node", type=int, default=0,
                    help="node index for stacked cluster dumps")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit JSON instead of a table")
    args = ap.parse_args(argv)

    lanes = load_dump(args.dump)
    # Latency-plane meta (optional): sampled spans + per-worker
    # utilization ride the artifact's _meta lane, which load_dump's
    # typed-lane view drops — read the raw JSON for it
    # (gzip-transparent: dumps may be .json or .json.gz).
    with tracelog._open_dump(args.dump) as f:
        meta = json.load(f).get("_meta") or {}
    lat = meta.get("latency") or {}
    spans_by_g = {}
    for sp in lat.get("recent") or []:
        spans_by_g.setdefault(sp.get("group", -1), []).append(sp)
    util_by_tick = {u.get("tick"): u.get("workers") or []
                    for u in lat.get("worker_util") or []}
    stacked = lanes["n"].ndim == 2
    counts = lanes["n"][args.node] if stacked else lanes["n"]
    groups = ([args.group] if args.group is not None
              else [g for g in range(counts.shape[0]) if counts[g] > 0])

    out = []
    for g in groups:
        events, dropped = decode_group(
            lanes, g, node=args.node if stacked else None)
        out.append({"group": g, "events": events, "dropped": dropped,
                    "total": int(counts[g]), "spans": spans_by_g.get(g, [])})
    try:
        if args.as_json:
            print(json.dumps({"groups": out,
                              "worker_util": lat.get("worker_util") or []}))
            return 0
        for doc in out:
            head = (f"group {doc['group']}: {doc['total']} events"
                    + (f" ({doc['dropped']} overwritten before this window)"
                       if doc["dropped"] else ""))
            print(head)
            # Interleave sampled spans on the shared tick axis: a span
            # prints after the last event at or before its accept tick.
            spans = sorted(doc["spans"], key=lambda s: s.get("tick", -1))
            si = 0
            for ev in doc["events"]:
                while si < len(spans) \
                        and spans[si].get("tick", -1) <= ev["tick"]:
                    _print_span(spans[si])
                    si += 1
                print(f"  #{ev['seq']:<5d} tick {ev['tick']:<8d} "
                      f"term {ev['term']:<6d} {ev['event']:<22s} "
                      f"aux={tracelog.format_aux(ev['kind'], ev['aux'])}")
                util = util_by_tick.pop(ev["tick"], None)
                if util is not None:
                    print(f"         tick {ev['tick']:<8d} workers "
                          f"[stage,fsync,send,apply]s: {util}")
            for sp in spans[si:]:
                _print_span(sp)
        if not out:
            print("no events recorded")
    except BrokenPipeError:   # `... | head` is the normal workflow
        import os
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
    return 0


if __name__ == "__main__":
    sys.exit(main())
