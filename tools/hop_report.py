#!/usr/bin/env python
"""Post-mortem hop-attribution report over a save_dump artifact.

The live half of the hop plane is ``GET /hops`` (runtime/obsrv.py);
this CLI is the post-mortem half: a run saves its flight-recorder
rings with ``rafting_tpu.utils.tracelog.save_dump(path, trace,
meta={"latency": node.latency_snapshot()})`` — the latency snapshot
embeds the hop tracer's document — and this tool renders the
cross-node decomposition of ``send_commit``: per-segment percentile
tables (leader_pack / wire / follower_fsync / ack_return /
quorum_wait), the same split per peer, the tracer's bookkeeping
counters, and the recent finalized traces with a reconciliation
column (sum of segments vs the span's end-to-end send→commit).  Zero
dependencies — no engine, device, or live process required (same
contract as tools/latency_report.py).

Usage:
    tools/hop_report.py DUMP.json[.gz] [--traces N] [--json]

Accepts a full save_dump artifact (hops under ``_meta.latency.hops``),
a raw ``latency_snapshot()`` document, or a bare ``hops_snapshot()``
document.  ``--traces`` caps how many recent traces print (default 8;
0 hides them).  ``--json`` re-emits the raw hops document.
"""

import argparse
import gzip
import json
import os
import sys

sys.path.insert(0, ".")

SEGMENTS = ("leader_pack", "wire", "follower_fsync", "ack_return",
            "quorum_wait")


def _open_dump(path: str):
    """Gzip-transparent read: .gz decompresses; a bare path falls back
    to its .gz sibling when only the compressed form exists."""
    if path.endswith(".gz"):
        return gzip.open(path, "rt")
    if not os.path.exists(path) and os.path.exists(path + ".gz"):
        return gzip.open(path + ".gz", "rt")
    return open(path)


def _fmt_s(v) -> str:
    v = float(v)
    if v >= 1.0:
        return f"{v:.3f}s"
    if v >= 1e-3:
        return f"{v * 1e3:.3f}ms"
    return f"{v * 1e6:.1f}us"


def _row(label: str, s: dict, out) -> None:
    print(f"  {label:<18s} n={s.get('n', 0):<7d} "
          f"p50={_fmt_s(s.get('p50', 0))} "
          f"p90={_fmt_s(s.get('p90', 0))} "
          f"p99={_fmt_s(s.get('p99', 0))} "
          f"p999={_fmt_s(s.get('p999', 0))} "
          f"max={_fmt_s(s.get('max', 0))}", file=out)


def render(hops: dict, traces: int = 8, out=sys.stdout) -> None:
    counts = hops.get("counts") or {}
    print("hop tracer: "
          + " ".join(f"{k}={v}" for k, v in sorted(counts.items())),
          file=out)
    print(f"pending={hops.get('pending', 0)} "
          f"foreign_pending={hops.get('foreign_pending', 0)}", file=out)
    segments = hops.get("segments") or {}
    if segments:
        print("segments (aggregate):", file=out)
        for seg in SEGMENTS:
            doc = segments.get(seg)
            if doc and doc.get("all"):
                _row(seg, doc["all"], out)
        peers = sorted({p for doc in segments.values()
                        for p in (doc.get("peers") or {})})
        for p in peers:
            print(f"segments (peer {p}):", file=out)
            for seg in SEGMENTS:
                s = (segments.get(seg) or {}).get("peers", {}).get(p)
                if s:
                    _row(seg, s, out)
    recent = hops.get("recent") or []
    if traces and recent:
        print(f"recent traces (last {min(traces, len(recent))}):",
              file=out)
        for tr in recent[-traces:]:
            sc = float(tr.get("send_commit_s", 0.0))
            print(f"  seq {tr.get('seq')} group={tr.get('group')} "
                  f"idx={tr.get('idx')} tick={tr.get('tick')} "
                  f"send_commit={_fmt_s(sc)}", file=out)
            for p, segs in sorted((tr.get("peers") or {}).items()):
                total = sum(float(segs.get(s, 0.0)) for s in SEGMENTS)
                parts = " ".join(f"{s}={_fmt_s(segs.get(s, 0.0))}"
                                 for s in SEGMENTS)
                recon = (f" (sum={_fmt_s(total)}, "
                         f"{total / sc * 100:.1f}% of e2e)"
                         if sc > 0 else f" (sum={_fmt_s(total)})")
                print(f"    peer {p}: {parts}{recon}", file=out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dump", help="save_dump artifact, latency_snapshot "
                                 "document, or hops_snapshot document")
    ap.add_argument("--traces", type=int, default=8,
                    help="recent traces to print (0 hides them)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="re-emit the raw hops document as JSON")
    args = ap.parse_args(argv)

    with _open_dump(args.dump) as f:
        doc = json.load(f)
    # Accept a full save_dump artifact (_meta.latency.hops), a raw
    # latency snapshot (hops), or a bare hops document (segments).
    meta = doc.get("_meta", doc) if isinstance(doc, dict) else {}
    lat = meta.get("latency") if isinstance(meta, dict) else None
    hops = (lat or {}).get("hops") or doc.get("hops")
    if hops is None and "segments" in doc and "counts" in doc:
        hops = doc
    if hops is None:
        print(f"{args.dump}: no hops document found (save the dump "
              "with meta={'latency': node.latency_snapshot()} and "
              "RAFT_HOP_TRACE on)", file=sys.stderr)
        return 1
    if args.as_json:
        print(json.dumps(hops))
        return 0
    try:
        render(hops, traces=args.traces)
    except BrokenPipeError:
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
    return 0


if __name__ == "__main__":
    sys.exit(main())
