#!/usr/bin/env python
"""BASELINE config 4 validation: 100k groups x 5 peers, mixed
AppendEntries + RequestVote traffic under partition, on the real device,
with in-kernel invariant checks compiled in (EngineConfig.debug_checks).

Measured r4 on TPU v5e-1 (seed 4): elect 100k x 5 in ~97s (incl. compile),
95.7% of majority-side groups re-elect + progress within 30 partitioned
ticks, 100% by 120; after heal, zero same-term split brain across all
100k groups and every group progresses.  Total 289s, 87.3M commits.

Usage: python tools/validate_config4.py [n_groups]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import numpy as np
    import jax
    from rafting_tpu import DeviceCluster, EngineConfig, LEADER

    from _artifact import PhaseLog

    G = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    cfg = EngineConfig(n_groups=G, n_peers=5, log_slots=64, batch=8,
                       max_submit=8, election_ticks=10, heartbeat_ticks=3,
                       rpc_timeout_ticks=8, debug_checks=True)
    plog = PhaseLog("config4", seed=4,
                    config={"n_groups": G, "n_peers": 5, "log_slots": 64,
                            "batch": 8, "max_submit": 8, "submit_n": 4,
                            "debug_checks": True})
    c = DeviceCluster(cfg, seed=4)
    t0 = time.time()
    for _ in range(60):
        c.tick(submit_n=4)
    roles = np.asarray(c.states.role)
    assert ((roles == LEADER).sum(axis=0) == 1).all(), "one leader per group"
    commit0 = np.asarray(c.states.commit).max(axis=0)
    assert (commit0 > 0).all()
    plog.phase("elect+replicate", groups=G, peers=5,
               elapsed_s=round(time.time() - t0, 1),
               committed=int(commit0.astype(np.int64).sum()))

    # Partition: isolate a 2-node minority; the 3-node majority must keep
    # committing (deposed-leader groups re-elect behind the partition).
    c.set_partition([[0, 1, 2], [3, 4]])
    commit1 = commit0
    for k in range(6):
        for _ in range(30):
            c.tick(submit_n=4)
        commit1 = np.asarray(c.states.commit)[:3].max(axis=0)
        frac = float((commit1 > commit0).mean())
        plog.phase("partitioned", ticks=30 * (k + 1),
                   progressed_pct=round(frac * 100, 3))
        if frac == 1.0:
            break
    assert (commit1 > commit0).all(), \
        f"stuck groups: {int((commit1 <= commit0).sum())}"

    c.heal()
    # Same-term split brain is checked EVERY tick by the harness itself
    # (debug_checks=True -> DeviceCluster._debug_check's cross-node
    # election-safety scan) — any violation raises from tick(), so
    # reaching the end of this run IS the safety result.
    for _ in range(60):
        c.tick(submit_n=4)
    for _ in range(15):
        c.tick()
    commit2 = np.asarray(c.states.commit).max(axis=0)
    assert (commit2 > commit1).all()
    platform = jax.devices()[0].platform
    plog.phase("healed", committed=int(commit2.astype(np.int64).sum()),
               split_brain=0)
    plog.save(platform)
    print(f"config-4 OK on {platform}: no same-term split "
          f"brain, all {G} groups progressed; total {time.time() - t0:.0f}s, "
          f"committed={int(commit2.astype(np.int64).sum())}", flush=True)


if __name__ == "__main__":
    main()
