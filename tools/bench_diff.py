#!/usr/bin/env python
"""Compare two bench-runtime rounds and flag regressions.

The repo banks every round's durable-runtime numbers as committed
JSON-lines files (``BENCH_RUNTIME_r*.json``, one document per scale /
stage).  This CLI diffs two rounds metric-by-metric and flags, per
scale:

* a **throughput regression**: a durable commits/sec (or ops/sec /
  reads/sec) line whose value dropped more than ``--threshold``
  (default 10%) against the older round;
* a **p999 blowup**: a tail latency (sampled e2e ``p999_s`` when the
  round carries the latency plane, else the tick-latency ``p99_s``
  proxy) that grew past ``--p999-factor`` x the older round's
  (default 2x).

Metrics are matched by their exact ``metric`` string; lines present in
only one round are reported informationally, never flagged (a new
stage is not a regression).  Zero dependencies, gzip-transparent
(``.json`` or ``.json.gz``).

Usage:
    tools/bench_diff.py OLD.json NEW.json [--threshold 0.10]
        [--p999-factor 2.0] [--json]

Exit status: 0 = no flags, 1 = at least one regression flagged (so CI
can gate on it), 2 = unreadable input.
"""

import argparse
import gzip
import json
import os
import sys

RATE_UNITS = ("commits/sec", "ops/sec", "reads/sec")


def _open(path: str):
    if path.endswith(".gz"):
        return gzip.open(path, "rt")
    if not os.path.exists(path) and os.path.exists(path + ".gz"):
        return gzip.open(path + ".gz", "rt")
    return open(path)


def load_round(path: str) -> dict:
    """Parse a JSON-lines bench round into {metric: doc}; non-JSON
    lines (log noise) are skipped.  Duplicate metric names keep the
    LAST occurrence (re-runs append)."""
    docs = {}
    with _open(path) as f:
        for line in f:
            line = line.strip()
            if not line or not line.startswith("{"):
                continue
            try:
                doc = json.loads(line)
            except ValueError:
                continue
            if isinstance(doc, dict) and "metric" in doc:
                docs[doc["metric"]] = doc
    return docs


def _p999(doc: dict):
    """Best available tail-latency figure for one metric line: the
    sampled e2e p999 when the latency plane rode along, else the tick
    p99 proxy.  Returns (seconds, source) or (None, None)."""
    lat = doc.get("latency") or {}
    e2e = lat.get("e2e") or {}
    if isinstance(e2e, dict) and e2e.get("p999_s"):
        return float(e2e["p999_s"]), "e2e_p999_s"
    tick = doc.get("tick_latency") or {}
    if isinstance(tick, dict) and tick.get("p99_s"):
        return float(tick["p99_s"]), "tick_p99_s"
    return None, None


def diff(old: dict, new: dict, threshold: float = 0.10,
         p999_factor: float = 2.0) -> dict:
    flags, infos = [], []
    for metric in sorted(set(old) | set(new)):
        o, n = old.get(metric), new.get(metric)
        if o is None or n is None:
            infos.append({"metric": metric,
                          "note": "only in " + ("new" if o is None
                                                else "old")})
            continue
        try:
            ov, nv = float(o.get("value", 0)), float(n.get("value", 0))
        except (TypeError, ValueError):
            continue
        row = {"metric": metric, "old": ov, "new": nv}
        unit = str(n.get("unit", ""))
        if any(u in unit for u in RATE_UNITS) and ov > 0:
            ratio = nv / ov
            row["ratio"] = round(ratio, 3)
            if ratio < 1.0 - threshold:
                flags.append({**row, "kind": "throughput_regression",
                              "drop_pct": round((1 - ratio) * 100, 1)})
                continue
        op, osrc = _p999(o)
        np_, nsrc = _p999(n)
        if op and np_ and osrc == nsrc and np_ > op * p999_factor:
            flags.append({**row, "kind": "p999_blowup", "source": osrc,
                          "old_p999_s": op, "new_p999_s": np_,
                          "factor": round(np_ / op, 2)})
            continue
        infos.append(row)
    return {"flags": flags, "compared": len(set(old) & set(new)),
            "info": infos}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("old", help="older round (JSON-lines, .gz ok)")
    ap.add_argument("new", help="newer round (JSON-lines, .gz ok)")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="flag rate drops beyond this fraction "
                         "(default 0.10)")
    ap.add_argument("--p999-factor", type=float, default=2.0,
                    help="flag tail growth beyond this factor "
                         "(default 2.0)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the full diff document as JSON")
    args = ap.parse_args(argv)

    try:
        old, new = load_round(args.old), load_round(args.new)
    except OSError as e:
        print(f"bench_diff: {e}", file=sys.stderr)
        return 2
    if not old or not new:
        print("bench_diff: no metric lines found in "
              + (args.old if not old else args.new), file=sys.stderr)
        return 2
    res = diff(old, new, threshold=args.threshold,
               p999_factor=args.p999_factor)
    if args.as_json:
        print(json.dumps(res, indent=1))
    else:
        print(f"compared {res['compared']} shared metrics "
              f"({len(res['flags'])} flagged)")
        for f in res["flags"]:
            if f["kind"] == "throughput_regression":
                print(f"  REGRESSION {f['drop_pct']}% drop: "
                      f"{f['metric']} ({f['old']:.0f} -> "
                      f"{f['new']:.0f})")
            else:
                print(f"  P999 BLOWUP {f['factor']}x "
                      f"({f['source']}): {f['metric']} "
                      f"({f['old_p999_s']}s -> {f['new_p999_s']}s)")
    return 1 if res["flags"] else 0


if __name__ == "__main__":
    sys.exit(main())
