#!/usr/bin/env python
"""Post-mortem latency report over a save_dump artifact.

The live half of the latency plane is ``GET /latency``
(runtime/obsrv.py); this CLI is the post-mortem half: a run that went
wrong saves its flight-recorder rings with
``rafting_tpu.utils.tracelog.save_dump(path, trace,
meta={"latency": node.latency_snapshot()})``, and this tool renders the
embedded snapshot — per-phase and end-to-end percentile tables, the SLO
burn, recent sampled spans with per-phase breakdowns, per-stripe WAL
engine timings and striped-worker utilization — with no engine, device,
or live process required (same zero-dependency contract as
tools/dump_timeline.py).

Usage:
    tools/latency_report.py DUMP.json [--spans N] [--json]

``--spans`` caps how many recent spans print (default 8; 0 hides them).
``--json`` re-emits the raw latency snapshot for scripting.
"""

import argparse
import gzip
import json
import os
import sys

sys.path.insert(0, ".")


def _open_dump(path: str):
    """Gzip-transparent read: .gz decompresses; a bare path falls back
    to its .gz sibling when only the compressed form exists."""
    if path.endswith(".gz"):
        return gzip.open(path, "rt")
    if not os.path.exists(path) and os.path.exists(path + ".gz"):
        return gzip.open(path + ".gz", "rt")
    return open(path)


def _fmt_s(v) -> str:
    """Seconds to a human unit (latencies span ns..s)."""
    v = float(v)
    if v >= 1.0:
        return f"{v:.3f}s"
    if v >= 1e-3:
        return f"{v * 1e3:.3f}ms"
    return f"{v * 1e6:.1f}us"


def _percentile_table(doc: dict, out) -> None:
    rows = []
    for name in ("submit_offer", "offer_stage", "stage_fsync",
                 "fsync_send", "send_commit", "commit_apply", "apply_ack"):
        s = (doc.get("phases") or {}).get(name)
        if s:
            rows.append((name, s))
    for key in ("lat_e2e", "lat_read_e2e"):
        s = doc.get(key)
        if s:
            rows.append((key[4:], s))
    if not rows:
        print("  (no completed spans harvested)", file=out)
        return
    print(f"  {'phase':<14s} {'count':>7s} {'p50':>10s} {'p99':>10s} "
          f"{'p999':>10s} {'max':>10s}", file=out)
    for name, s in rows:
        print(f"  {name:<14s} {s.get('count', 0):>7d} "
              f"{_fmt_s(s.get('p50', 0)):>10s} "
              f"{_fmt_s(s.get('p99', 0)):>10s} "
              f"{_fmt_s(s.get('p999', 0)):>10s} "
              f"{_fmt_s(s.get('max', 0)):>10s}", file=out)


def render(doc: dict, spans: int = 8, out=sys.stdout) -> None:
    if not doc.get("enabled", True):
        print("latency plane disabled for this run (RAFT_LAT_SAMPLE=0)",
              file=out)
    sampling = doc.get("sampling") or {}
    if sampling:
        c = sampling.get("counts") or {}
        print(f"sampling: 1/{sampling.get('rate', '?')} "
              f"seed={sampling.get('seed', '?')} "
              f"sampled={c.get('sampled', 0)} ok={c.get('ok', 0)} "
              f"unknown={c.get('unknown', 0)} "
              f"refused={c.get('refused', 0)} "
              f"overflow={c.get('overflow', 0)}", file=out)
    slo = doc.get("slo") or {}
    if slo:
        print(f"slo: target={_fmt_s(slo.get('target_s', 0))} "
              f"e2e_p999={_fmt_s(slo.get('e2e_p999_s', 0))} "
              f"burn_ratio={slo.get('burn_ratio', 0):.4f}", file=out)
    print("percentiles:", file=out)
    _percentile_table(doc, out)
    recent = doc.get("recent") or []
    if spans and recent:
        print(f"recent spans (last {min(spans, len(recent))} "
              f"of {len(recent)}):", file=out)
        for sp in recent[-spans:]:
            phases = " ".join(f"{k}={_fmt_s(v)}"
                              for k, v in (sp.get("phases") or {}).items())
            print(f"  seq={sp.get('seq')} {sp.get('kind')} "
                  f"g={sp.get('group')} idx={sp.get('idx')} "
                  f"tick={sp.get('tick')} [{sp.get('outcome')}] {phases}",
                  file=out)
    stripes = doc.get("wal_stripes") or []
    if stripes:
        print("wal engine per-stripe (cumulative):", file=out)
        for s in stripes:
            print(f"  stripe {s.get('stripe', '?')}: "
                  f"stage={_fmt_s(s.get('stage_ns', 0) / 1e9)} "
                  f"fsync={_fmt_s(s.get('fsync_ns', 0) / 1e9)} "
                  f"pack={_fmt_s(s.get('pack_ns', 0) / 1e9)} "
                  f"bytes={s.get('bytes', 0)} "
                  f"fsyncs={s.get('fsync_calls', 0)}", file=out)
    util = doc.get("worker_util") or []
    if util:
        last = util[-1]
        print(f"striped workers (tick {last.get('tick')}, "
              f"{len(util)} intervals recorded): "
              "[stage, fsync, send, apply] seconds", file=out)
        for k, w in enumerate(last.get("workers") or []):
            print(f"  worker {k}: {w}", file=out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dump", help="JSON artifact written by "
                                 "tracelog.save_dump (or a raw "
                                 "latency_snapshot() document)")
    ap.add_argument("--spans", type=int, default=8,
                    help="recent spans to print (0 hides them)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="re-emit the raw latency snapshot as JSON")
    args = ap.parse_args(argv)

    with _open_dump(args.dump) as f:
        doc = json.load(f)
    # Accept a full save_dump artifact (snapshot under _meta.latency), a
    # bare meta dict, or a raw latency_snapshot() document.
    lat = doc.get("_meta", doc).get("latency") \
        if isinstance(doc.get("_meta", doc), dict) else None
    if lat is None and ("sampling" in doc or "enabled" in doc):
        lat = doc
    if lat is None:
        print(f"{args.dump}: no latency snapshot found (save the dump "
              "with meta={'latency': node.latency_snapshot()})",
              file=sys.stderr)
        return 1
    if args.as_json:
        print(json.dumps(lat))
        return 0
    try:
        render(lat, spans=args.spans)
    except BrokenPipeError:
        import os
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
    return 0


if __name__ == "__main__":
    sys.exit(main())
