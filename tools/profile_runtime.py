#!/usr/bin/env python
"""Profile the durable-runtime host path (bench_runtime.run) under cProfile.

The durable tier's scaling wall lives in per-tick host Python
(VERDICT r4 weak #3: 32.1k commits/sec @100k groups, p99 tick 8.39s).
This tool answers WHERE: it runs one bench_runtime scale with cProfile
and prints the top functions by cumulative and by self time, so an
optimization round targets the measured wall instead of a guessed one.

Each run also persists its top-N tables (plus the bench result) as a
JSON artifact under ``artifacts/`` via tools/_artifact.py, so profile
shape is DIFFABLE across rounds — "what got slower since round 4" is a
file comparison, not scrollback archaeology.

Usage: tools/profile_runtime.py [n_groups] [rounds]
"""

import cProfile
import io
import pstats
import sys

sys.path.insert(0, ".")

TOP_N = 35


def top_rows(stats: pstats.Stats, key: str, n: int = TOP_N) -> list:
    """Extract the top-n functions by ``key`` as JSON-ready rows."""
    stats.sort_stats(key)
    rows = []
    for func in stats.fcn_list[:n]:
        cc, nc, tt, ct, _callers = stats.stats[func]
        fname, line, name = func
        rows.append({
            "func": f"{fname}:{line}({name})",
            "ncalls": nc,
            "primitive_calls": cc,
            "tottime_s": round(tt, 6),
            "cumtime_s": round(ct, 6),
        })
    return rows


def main() -> None:
    import jax
    jax.config.update("jax_platforms", "cpu")
    from bench_runtime import run
    from tools._artifact import PhaseLog

    n_groups = int(sys.argv[1]) if len(sys.argv) > 1 else 32_768
    rounds = int(sys.argv[2]) if len(sys.argv) > 2 else 0
    log = PhaseLog("profile_runtime", seed=0,
                   config={"n_groups": n_groups, "rounds": rounds})
    prof = cProfile.Profile()
    prof.enable()
    res = run(n_groups=n_groups, rounds=rounds)
    prof.disable()
    print(res)
    log.phase("bench", commits_per_sec=res["value"],
              rounds=res["rounds"],
              p99_tick_s=res["tick_latency"].get("p99_s", 0))
    st = pstats.Stats(prof)
    for key in ("cumulative", "tottime"):
        s = io.StringIO()
        pstats.Stats(prof, stream=s).sort_stats(key).print_stats(TOP_N)
        print(f"\n==== top by {key} ====")
        # Strip the long header boilerplate, keep the table.
        lines = s.getvalue().splitlines()
        start = next(i for i, l in enumerate(lines) if "ncalls" in l)
        print("\n".join(lines[start - 2:start + 40]))
        rows = top_rows(st, key)
        log.phase(f"top_{key}", shown=len(rows))
        log.phases[-1]["rows"] = rows
    log.save(platform="cpu")


if __name__ == "__main__":
    main()
