#!/usr/bin/env python
"""Profile the durable-runtime host path (bench_runtime.run) under cProfile.

The durable tier's scaling wall lives in per-tick host Python
(VERDICT r4 weak #3: 32.1k commits/sec @100k groups, p99 tick 8.39s).
This tool answers WHERE: it runs one bench_runtime scale with cProfile
and prints the top functions by cumulative and by self time, so an
optimization round targets the measured wall instead of a guessed one.

Usage: tools/profile_runtime.py [n_groups] [rounds]
"""

import cProfile
import io
import pstats
import sys

sys.path.insert(0, ".")


def main() -> None:
    import jax
    jax.config.update("jax_platforms", "cpu")
    from bench_runtime import run

    n_groups = int(sys.argv[1]) if len(sys.argv) > 1 else 32_768
    rounds = int(sys.argv[2]) if len(sys.argv) > 2 else 0
    prof = cProfile.Profile()
    prof.enable()
    res = run(n_groups=n_groups, rounds=rounds)
    prof.disable()
    print(res)
    for key in ("cumulative", "tottime"):
        s = io.StringIO()
        pstats.Stats(prof, stream=s).sort_stats(key).print_stats(35)
        print(f"\n==== top by {key} ====")
        # Strip the long header boilerplate, keep the table.
        lines = s.getvalue().splitlines()
        start = next(i for i, l in enumerate(lines) if "ncalls" in l)
        print("\n".join(lines[start - 2:start + 40]))


if __name__ == "__main__":
    main()
