#!/usr/bin/env python
"""Seeded chaos soak: every nemesis at once, judged by the checker.

Drives a LocalCluster of full node runtimes (engine + WAL + machines +
read plane) under a seeded mixed-nemesis timeline — asymmetric
partitions, flaky links, crash/restart, clock stalls, slow storage,
membership churn (testkit/chaos.py) — while seeded client threads
drive load through recording stubs (testkit/history.py).

Two workloads:

* ``--workload kv`` (default): register+list KV traffic at one group;
  afterwards the Wing & Gong checker (testkit/linz.py) must find the
  recorded history linearizable.
* ``--workload transfer``: the Jepsen BANK TEST over the cross-group
  2PC plane (runtime/txn.py) — concurrent bank transfers between
  accounts in different Raft groups, coordinated by a replicated 2PC
  coordinator group.  The judgment is
  testkit/invariants.py:check_transfer_atomicity over converged state:
  total balance conserved, no lost / phantom / half-applied transfer,
  zero stranded intents after the deadline sweep.  ``--min-transfers``
  replays fresh seeded timelines (seed, seed+1, ...) until that many
  transfers were attempted, so long soaks stay replayable round by
  round.

Either way the run saves an auditable artifact under ``artifacts/``
embedding the canonical timeline(s) (byte-for-byte reproducible from
the seed), the applied-event audit, the transport fault counters, the
raw history and the verdict.

A third nemesis mode targets the GRAY failure (``--nemesis
leader-isolate``): every link INTO one group's current leader is cut
while its outbound heartbeats keep suppressing follower timers — the
hostage scenario CheckQuorum (core/step.py phase 6c) exists for.  The
judgment adds a GOODPUT-RECOVERY assertion on top of the checker: after
every isolate lands, new client ops must commit within
``--recovery-ticks`` WHILE THE CUT IS STILL ACTIVE (the old leader
steps itself down, the healthy majority re-elects).  With
``--no-check-quorum`` the verdict is EXPECTED to fail — the group is
hostage for the whole window, goodput flatlines, and the saved
artifact is the committed availability counterexample the self-healing
plane closes.  (The lease cannot serve stale reads here either way:
its evidence is ack-receipt based, so the inbound cut starves it —
unavailability, not corruption.  tests/test_linz.py carries the same
framing at test scale.)

Usage:
    JAX_PLATFORMS=cpu python tools/chaos_run.py --seed 7 --ticks 400
    ... --no-lease        # strict ReadIndex instead of the lease path
    ... --transport tcp   # real localhost sockets (slower, full plane)
    ... --stale-reads     # inject the stale-read defect: MUST fail,
                          # prints the minimal counterexample (checker
                          # self-test; exits 0 when the bug is caught)
    ... --workload transfer --min-transfers 5000   # the bank soak
    ... --nemesis leader-isolate                   # gray-failure soak
    ... --nemesis leader-isolate --no-check-quorum # hostage proof

Exit status: 0 = verdict matches expectation, 1 = it does not.
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _artifact import PhaseLog  # noqa: E402  (tools/ sibling)


def run_kv(args, log, cluster, history, events, tl):
    """The register+list workload judged by the per-key checker."""
    from rafting_tpu.testkit import linz
    from rafting_tpu.testkit.chaos import ChaosConductor, KVWorkload

    conductor = ChaosConductor(cluster, events)
    load = KVWorkload(cluster, history, group=args.group,
                      clients=args.clients, seed=args.seed)
    load.start()
    conductor.run(extra_ticks=40, tick_sleep=args.tick_sleep)
    load.stop()
    load.join(tick_fn=conductor.step)
    conductor.finish()
    log.phase("soak done", ticks=conductor.t,
              applied=len(conductor.applied),
              ops=load.ops_attempted, **history.counts())

    verdict = linz.check(history)
    print(verdict.render(), flush=True)
    counters = cluster.faults.snapshot()["counters"]
    log.phase("checked", ok=verdict.ok, keys=verdict.checked_keys,
              **{f"net_{k}": v for k, v in counters.items()})
    expected_ok = not args.stale_reads
    return verdict.ok == expected_ok, {
        "timeline": json.loads(tl),
        "timeline_canonical": tl,
        "applied": conductor.applied,
        "fault_counters": counters,
        "history": history.to_json(),
        "verdict": {
            "ok": verdict.ok,
            "key": verdict.key,
            "counterexample": [op.describe()
                               for op in verdict.counterexample],
        },
    }


def run_kv_isolate(args, log, cluster, history, events, tl):
    """The gray-failure soak: leader_isolate nemesis + KV workload,
    judged by the checker AND per-window goodput recovery."""
    from rafting_tpu.testkit import linz
    from rafting_tpu.testkit.chaos import ChaosConductor, KVWorkload

    conductor = ChaosConductor(cluster, events)
    # op_timeout=3: a client op stuck forwarding into the cut fails in
    # 3s wall and retries against the re-elected leader — the 6s
    # default would burn most of the recovery budget on one dead
    # forward.
    load = KVWorkload(cluster, history, group=args.group,
                      clients=args.clients, seed=args.seed,
                      op_timeout=3.0)
    load.start()
    # Per-tick cumulative ok-op series: the goodput trace the recovery
    # judgment (and the artifact's flatline evidence) reads.
    ok_series = []
    end = conductor.horizon + 1 + 40
    while conductor.t < end:
        conductor.step()
        ok_series.append(history.counts()["ok"])
        if args.tick_sleep:
            time.sleep(args.tick_sleep)
    load.stop()
    load.join(tick_fn=conductor.step)
    conductor.finish()
    stepdowns = sum(
        n.metrics._counters.get("checkquorum_stepdowns", 0)
        for n in cluster.nodes.values())
    log.phase("soak done", ticks=conductor.t,
              applied=len(conductor.applied),
              ops=load.ops_attempted, stepdowns=stepdowns,
              **history.counts())

    # Recovery judgment: after each applied isolate, NEW ok ops must
    # land within the budget — while the cut is still open (the budget
    # is sized under the isolate duration: step-down <= 2 election
    # timeouts, re-election, first commits).
    windows = []
    for ev in conductor.applied:
        if ev["kind"] != "leader_isolate" or "victim" not in ev:
            continue
        t0 = min(ev["t"], len(ok_series) - 1)
        t1 = min(t0 + args.recovery_ticks, len(ok_series) - 1)
        first = next((t for t in range(t0 + 1, len(ok_series))
                      if ok_series[t] > ok_series[t0]), None)
        windows.append({
            "cut_tick": ev["t"], "victim": ev["victim"],
            "ok_at_cut": ok_series[t0], "ok_at_budget": ok_series[t1],
            "first_ok_tick": first,
            "recovered": ok_series[t1] > ok_series[t0],
        })
    recovered = bool(windows) and all(w["recovered"] for w in windows)
    verdict = linz.check(history)
    print(verdict.render(), flush=True)
    counters = cluster.faults.snapshot()["counters"]
    log.phase("checked", ok=verdict.ok, recovered=recovered,
              windows=len(windows), keys=verdict.checked_keys,
              **{f"net_{k}": v for k, v in counters.items()})
    # The self-healing claim needs all three legs: clean history, the
    # step-down actually fired, and goodput resumed inside the budget.
    ok = verdict.ok and recovered and stepdowns >= 1
    # CheckQuorum off is the EXPECTED-fail counterexample run: the
    # history stays clean (nothing commits through a hostage leader)
    # but no step-down fires and goodput never recovers inside any
    # window.
    expected_ok = not args.no_check_quorum
    return ok == expected_ok, {
        "timeline": json.loads(tl),
        "timeline_canonical": tl,
        "applied": conductor.applied,
        "fault_counters": counters,
        "history": history.to_json(),
        "goodput_ok_series": ok_series,
        "recovery_windows": windows,
        "checkquorum_stepdowns": stepdowns,
        "verdict": {
            "ok": verdict.ok,
            "recovered": recovered,
            "key": verdict.key,
            "counterexample": [op.describe()
                               for op in verdict.counterexample],
        },
    }


def run_transfer(args, log, cluster, history):
    """The bank-transfer workload judged by the 2PC atomicity invariant."""
    from rafting_tpu.testkit.chaos import (
        ChaosConductor, TransferWorkload, plan_chaos, timeline_json,
    )
    from rafting_tpu.testkit.invariants import (
        InvariantViolation, check_transfer_atomicity,
    )

    coord = args.coord_group
    participants = [g for g in range(args.groups) if g != coord]
    assert len(participants) >= 2, \
        "transfer mode needs >= 2 participant groups besides the coordinator"
    for n in cluster.nodes.values():
        n.txn.sweep_every = 8   # brisk in-doubt recovery under chaos

    # Seed the bank before any nemesis fires (lockstep, no ticker yet).
    for g in participants:
        for a in range(args.accounts):
            cluster.submit_via_leader(g, json.dumps(
                {"op": "set", "k": f"acct{a}",
                 "v": args.seed_balance}).encode())
    initial_total = len(participants) * args.accounts * args.seed_balance
    log.phase("bank seeded", participants=len(participants),
              accounts=args.accounts, initial_total=initial_total)

    load = TransferWorkload(cluster, history, coord_group=coord,
                            groups=participants, clients=args.clients,
                            seed=args.seed, accounts=args.accounts,
                            deadline_s=2.0, op_timeout=6.0)
    load.start()
    timelines, applied = [], []
    conductor = None
    rnd = 0
    while True:
        events = plan_chaos(args.peers, args.ticks, seed=args.seed + rnd,
                            period=args.period,
                            churn_group=participants[0])
        timelines.append(timeline_json(events))
        conductor = ChaosConductor(cluster, events)
        conductor.run(extra_ticks=40, tick_sleep=args.tick_sleep)
        conductor.finish()   # heal fully: each round replays standalone
        applied.extend(conductor.applied)
        rnd += 1
        log.phase(f"round {rnd}", **load.counts())
        if load.attempted >= args.min_transfers or rnd >= args.max_rounds:
            break
    load.stop()
    load.join(tick_fn=conductor.step)
    log.phase("soak done", rounds=rnd, applied=len(applied),
              **load.counts())

    # Drain: tick until the deadline sweep resolved every in-doubt
    # intent everywhere (the no-key-locked-past-deadline guarantee).
    def clean():
        for node in cluster.nodes.values():
            for g in participants:
                m = node.dispatcher.machine(g)
                if m.intents or m.locks:
                    return False
        return True
    deadline = time.time() + args.drain_s
    while not clean() and time.time() < deadline:
        conductor.step()
        time.sleep(args.tick_sleep)
    drained = clean()
    log.phase("drained", clean=drained)

    def leader_machine(g):
        lead = cluster.leader_of(g)
        return cluster.nodes[lead].dispatcher.machine(g)

    violation = None
    report = {}
    try:
        report = check_transfer_atomicity(
            leader_machine(coord),
            {g: leader_machine(g) for g in participants},
            initial_total=initial_total)
    except InvariantViolation as e:
        violation = str(e)
    ok = drained and violation is None
    plane = {i: n.txn.snapshot() for i, n in cluster.nodes.items()}
    counters = cluster.faults.snapshot()["counters"]
    log.phase("judged", ok=ok, violation=violation or "none", **report)
    if violation:
        print(f"INVARIANT VIOLATION: {violation}", flush=True)
    else:
        print(f"bank invariant holds: {report}", flush=True)
    return ok, {
        "timelines_canonical": timelines,
        "applied": applied,
        "fault_counters": counters,
        "history": history.to_json(),
        "workload": load.counts(),
        "txn_plane": plane,
        "verdict": {"ok": ok, "drained": drained,
                    "violation": violation, "report": report},
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--peers", type=int, default=3)
    ap.add_argument("--groups", type=int, default=3)
    ap.add_argument("--ticks", type=int, default=300,
                    help="timeline horizon (nemesis events stop here)")
    ap.add_argument("--period", type=int, default=12,
                    help="ticks between nemesis draws")
    ap.add_argument("--clients", type=int, default=3)
    ap.add_argument("--group", type=int, default=1,
                    help="group the kv workload targets")
    ap.add_argument("--no-lease", action="store_true",
                    help="strict ReadIndex reads (read_lease=False)")
    ap.add_argument("--transport", choices=("loopback", "tcp"),
                    default="loopback")
    ap.add_argument("--stale-reads", action="store_true",
                    help="arm the KV machine's stale-read defect; the "
                         "checker is then EXPECTED to fail")
    ap.add_argument("--tick-sleep", type=float, default=None,
                    help="conductor sleep per tick (yields to clients). "
                         "Default 0.002; leader-isolate mode defaults "
                         "to 0.25: client recovery is WALL-bound "
                         "(op timeouts, retry backoff sleeps) while the "
                         "recovery budget is counted in TICKS, so the "
                         "tick must be slow enough that a couple of "
                         "seconds of client wall time spans only a "
                         "handful of ticks")
    ap.add_argument("--root", default=None,
                    help="data dir (default: a fresh temp dir)")
    ap.add_argument("--workload", choices=("kv", "transfer"),
                    default="kv")
    ap.add_argument("--coord-group", type=int, default=0,
                    help="transfer mode: the 2PC coordinator group")
    ap.add_argument("--accounts", type=int, default=12,
                    help="transfer mode: accounts per participant group")
    ap.add_argument("--seed-balance", type=int, default=1000,
                    help="transfer mode: initial balance per account")
    ap.add_argument("--min-transfers", type=int, default=0,
                    help="transfer mode: replay fresh seeded timelines "
                         "until this many transfers were attempted")
    ap.add_argument("--max-rounds", type=int, default=200,
                    help="transfer mode: hard cap on timeline replays")
    ap.add_argument("--drain-s", type=float, default=120.0,
                    help="transfer mode: max seconds to drain intents")
    ap.add_argument("--nemesis", choices=("mixed", "leader-isolate"),
                    default="mixed",
                    help="mixed = the full seeded nemesis mix; "
                         "leader-isolate = inbound-only cuts of the "
                         "workload group's current leader (gray "
                         "failure; kv workload only)")
    ap.add_argument("--no-check-quorum", action="store_true",
                    help="disable CheckQuorum (leader-isolate then "
                         "EXPECTS the recovery verdict to fail — the "
                         "hostage counterexample artifact)")
    ap.add_argument("--isolate-period", type=int, default=100,
                    help="leader-isolate: ticks between cuts")
    ap.add_argument("--isolate-dur", type=int, default=70,
                    help="leader-isolate: ticks each cut stays open")
    ap.add_argument("--recovery-ticks", type=int, default=60,
                    help="leader-isolate: goodput must resume within "
                         "this many ticks of each cut (must be under "
                         "--isolate-dur so recovery happens under the "
                         "live cut; the budget covers step-down <= 2 "
                         "election timeouts + follower timeout + "
                         "re-election + client retry backoff)")
    args = ap.parse_args()
    if args.tick_sleep is None:
        args.tick_sleep = (0.25 if args.nemesis == "leader-isolate"
                           else 0.002)
    if args.nemesis == "leader-isolate":
        assert args.workload == "kv", \
            "leader-isolate judges kv goodput; transfer mode keeps mixed"
        assert args.recovery_ticks < args.isolate_dur, \
            "--recovery-ticks must fit inside --isolate-dur"

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from rafting_tpu.core.types import EngineConfig
    from rafting_tpu.machine.kv_machine import KVMachineProvider
    from rafting_tpu.testkit.chaos import plan_chaos, timeline_json
    from rafting_tpu.testkit.harness import LocalCluster
    from rafting_tpu.testkit.history import History

    cfg = EngineConfig(n_groups=args.groups, n_peers=args.peers,
                       log_slots=64, batch=8, max_submit=8,
                       election_ticks=10, heartbeat_ticks=3,
                       rpc_timeout_ticks=8,
                       read_lease=not args.no_lease,
                       check_quorum=not args.no_check_quorum)
    name = ("chaos_soak_isolate" if args.nemesis == "leader-isolate"
            else "chaos_soak" if args.workload == "kv"
            else "chaos_soak_transfer")
    log = PhaseLog(name, args.seed, {
        "peers": args.peers, "groups": args.groups, "ticks": args.ticks,
        "period": args.period, "clients": args.clients,
        "lease": not args.no_lease, "transport": args.transport,
        "stale_reads": args.stale_reads, "workload": args.workload,
        "nemesis": args.nemesis,
        "check_quorum": not args.no_check_quorum,
    })

    root = args.root or tempfile.mkdtemp(prefix="chaos_soak_")
    cluster = LocalCluster(
        cfg, root, seed=args.seed,
        provider_factory=lambda i: KVMachineProvider(
            os.path.join(root, f"node{i}", "kv"),
            stale_reads=args.stale_reads),
        transport=args.transport)
    history = History()
    try:
        for g in range(args.groups):
            cluster.wait_leader(g)
        log.phase("cluster up", nodes=args.peers)
        if args.nemesis == "leader-isolate":
            from rafting_tpu.testkit.chaos import plan_leader_isolate
            events = plan_leader_isolate(
                args.ticks, seed=args.seed, group=args.group,
                period=args.isolate_period, dur=args.isolate_dur)
            tl = timeline_json(events)
            log.phase("planned", events=len(events),
                      timeline_bytes=len(tl))
            success, doc_extra = run_kv_isolate(args, log, cluster,
                                                history, events, tl)
        elif args.workload == "kv":
            events = plan_chaos(args.peers, args.ticks, seed=args.seed,
                                period=args.period,
                                churn_group=args.group)
            tl = timeline_json(events)
            log.phase("planned", events=len(events),
                      timeline_bytes=len(tl))
            success, doc_extra = run_kv(args, log, cluster, history,
                                        events, tl)
        else:
            success, doc_extra = run_transfer(args, log, cluster,
                                              history)
    finally:
        cluster.close()

    log.config.update(doc_extra)
    log.save("cpu", ok=success)
    if not success:
        print("FAIL: verdict did not match expectation", flush=True)
    return 0 if success else 1


if __name__ == "__main__":
    sys.exit(main())
