#!/usr/bin/env python
"""Seeded chaos soak: every nemesis at once, judged by the checker.

Drives a LocalCluster of full node runtimes (engine + WAL + machines +
read plane) under a seeded mixed-nemesis timeline — asymmetric
partitions, flaky links, crash/restart, clock stalls, slow storage,
membership churn (testkit/chaos.py) — while seeded client threads
run a register+list KV workload through recording stubs
(testkit/history.py).  Afterwards the Wing & Gong checker
(testkit/linz.py) must find the recorded history linearizable, and the
run saves an auditable artifact under ``artifacts/`` embedding the
canonical timeline (byte-for-byte reproducible from the seed), the
applied-event audit, the transport fault counters, the raw history and
the verdict.

Usage:
    JAX_PLATFORMS=cpu python tools/chaos_run.py --seed 7 --ticks 400
    ... --no-lease        # strict ReadIndex instead of the lease path
    ... --transport tcp   # real localhost sockets (slower, full plane)
    ... --stale-reads     # inject the stale-read defect: MUST fail,
                          # prints the minimal counterexample (checker
                          # self-test; exits 0 when the bug is caught)

Exit status: 0 = verdict matches expectation, 1 = it does not.
"""

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _artifact import PhaseLog  # noqa: E402  (tools/ sibling)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--peers", type=int, default=3)
    ap.add_argument("--groups", type=int, default=3)
    ap.add_argument("--ticks", type=int, default=300,
                    help="timeline horizon (nemesis events stop here)")
    ap.add_argument("--period", type=int, default=12,
                    help="ticks between nemesis draws")
    ap.add_argument("--clients", type=int, default=3)
    ap.add_argument("--group", type=int, default=1,
                    help="group the workload targets")
    ap.add_argument("--no-lease", action="store_true",
                    help="strict ReadIndex reads (read_lease=False)")
    ap.add_argument("--transport", choices=("loopback", "tcp"),
                    default="loopback")
    ap.add_argument("--stale-reads", action="store_true",
                    help="arm the KV machine's stale-read defect; the "
                         "checker is then EXPECTED to fail")
    ap.add_argument("--tick-sleep", type=float, default=0.002,
                    help="conductor sleep per tick (yields to clients)")
    ap.add_argument("--root", default=None,
                    help="data dir (default: a fresh temp dir)")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from rafting_tpu.core.types import EngineConfig
    from rafting_tpu.machine.kv_machine import KVMachineProvider
    from rafting_tpu.testkit.chaos import (
        ChaosConductor, KVWorkload, plan_chaos, timeline_json,
    )
    from rafting_tpu.testkit.harness import LocalCluster
    from rafting_tpu.testkit.history import History
    from rafting_tpu.testkit import linz

    cfg = EngineConfig(n_groups=args.groups, n_peers=args.peers,
                       log_slots=64, batch=8, max_submit=8,
                       election_ticks=10, heartbeat_ticks=3,
                       rpc_timeout_ticks=8,
                       read_lease=not args.no_lease)
    log = PhaseLog("chaos_soak", args.seed, {
        "peers": args.peers, "groups": args.groups, "ticks": args.ticks,
        "period": args.period, "clients": args.clients,
        "lease": not args.no_lease, "transport": args.transport,
        "stale_reads": args.stale_reads,
    })

    root = args.root or tempfile.mkdtemp(prefix="chaos_soak_")
    events = plan_chaos(args.peers, args.ticks, seed=args.seed,
                        period=args.period, churn_group=args.group)
    tl = timeline_json(events)
    log.phase("planned", events=len(events), timeline_bytes=len(tl))

    cluster = LocalCluster(
        cfg, root, seed=args.seed,
        provider_factory=lambda i: KVMachineProvider(
            os.path.join(root, f"node{i}", "kv"),
            stale_reads=args.stale_reads),
        transport=args.transport)
    history = History()
    try:
        for g in range(args.groups):
            cluster.wait_leader(g)
        log.phase("cluster up", nodes=args.peers)

        conductor = ChaosConductor(cluster, events)
        load = KVWorkload(cluster, history, group=args.group,
                          clients=args.clients, seed=args.seed)
        load.start()
        conductor.run(extra_ticks=40, tick_sleep=args.tick_sleep)
        load.stop()
        load.join(tick_fn=conductor.step)
        conductor.finish()
        log.phase("soak done", ticks=conductor.t,
                  applied=len(conductor.applied),
                  ops=load.ops_attempted, **history.counts())

        verdict = linz.check(history)
        print(verdict.render(), flush=True)
        counters = cluster.faults.snapshot()["counters"]
        log.phase("checked", ok=verdict.ok, keys=verdict.checked_keys,
                  **{f"net_{k}": v for k, v in counters.items()})
    finally:
        cluster.close()

    expected_ok = not args.stale_reads
    success = verdict.ok == expected_ok
    doc_extra = {
        "timeline": json.loads(tl),
        "timeline_canonical": tl,
        "applied": conductor.applied,
        "fault_counters": counters,
        "history": history.to_json(),
        "verdict": {
            "ok": verdict.ok,
            "key": verdict.key,
            "counterexample": [op.describe()
                               for op in verdict.counterexample],
        },
    }
    log.config.update(doc_extra)
    log.save("cpu", ok=success)
    if not success:
        print(f"FAIL: linearizable={verdict.ok}, expected "
              f"{'ok' if expected_ok else 'a violation'}", flush=True)
    return 0 if success else 1


if __name__ == "__main__":
    sys.exit(main())
