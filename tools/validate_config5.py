#!/usr/bin/env python
"""BASELINE config 5 validation: 100k groups with InstallSnapshot
lagging-follower catch-up, on the real device, with in-kernel invariant
checks compiled in.

Scenario: one node is isolated while the majority keeps committing and
COMPACTING until every group's log floor has passed the victim's frozen
tail — at that point log replication alone cannot catch it up anywhere
(reference Leadership.java:111-113 pendingInstallation trigger).  After
heal, the leader's InstallSnapshot offers drive the victim's snapshot
plane (device phases 5/9; the sim's host inbox services the bulk
transfer instantly — the payload-free analog of the out-of-band snapshot
channel), and every group must converge via a FLOOR JUMP, not log replay.

Usage: python tools/validate_config5.py [n_groups]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import numpy as np
    import jax
    from rafting_tpu import DeviceCluster, EngineConfig, LEADER

    from _artifact import PhaseLog

    G = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    cfg = EngineConfig(n_groups=G, n_peers=3, log_slots=64, batch=8,
                       max_submit=8, election_ticks=10, heartbeat_ticks=3,
                       rpc_timeout_ticks=8, debug_checks=True)
    plog = PhaseLog("config5", seed=5,
                    config={"n_groups": G, "n_peers": 3, "log_slots": 64,
                            "batch": 8, "max_submit": 8, "submit_n": 4,
                            "compact_every": 16, "debug_checks": True})
    c = DeviceCluster(cfg, seed=5)
    # Discrete compaction cadence (every 16 ticks), matching real
    # checkpoint-gated compaction: a floor advancing EVERY tick outruns
    # any snapshot install under sustained load and no laggard could ever
    # converge (see auto_host_inbox).
    c.compact = 16
    t0 = time.time()
    for _ in range(60):
        c.tick(submit_n=4)
    roles = np.asarray(c.states.role)
    assert ((roles == LEADER).sum(axis=0) == 1).all()
    plog.phase("elect+replicate", groups=G,
               elapsed_s=round(time.time() - t0, 1))

    victim = 2
    victim_tail = np.asarray(c.states.log.last)[victim].copy()
    c.isolate(victim)
    # Majority commits + compacts until every group's floor passes the
    # victim's frozen tail (floor chases commit - L/4 via the sim's
    # maintain policy, so ~L more commits per group suffice).
    for k in range(12):
        for _ in range(30):
            c.tick(submit_n=4)
        floors = np.asarray(c.states.log.base)[:2].min(axis=0)
        frac = float((floors > victim_tail).mean())
        plog.phase("isolated", ticks=30 * (k + 1),
                   floors_past_victim_pct=round(frac * 100, 2))
        if frac == 1.0:
            break
    assert (np.asarray(c.states.log.base)[:2].min(axis=0)
            > victim_tail).all(), "compaction never passed the victim"

    c.heal()
    commit_majority = np.asarray(c.states.commit)[:2].max(axis=0)
    for k in range(10):
        for _ in range(30):
            c.tick(submit_n=4)
        v_commit = np.asarray(c.states.commit)[victim]
        frac = float((v_commit >= commit_majority).mean())
        plog.phase("healed", ticks=30 * (k + 1),
                   caught_up_pct=round(frac * 100, 2))
        if frac == 1.0:
            break
    v_commit = np.asarray(c.states.commit)[victim]
    assert (v_commit >= commit_majority).all(), \
        f"victim stuck on {int((v_commit < commit_majority).sum())} groups"
    # Drain without load so in-flight installs/replication settle before
    # the lane checks (flags mid-clear at the convergence instant are
    # normal operation, not stuck state).
    for _ in range(40):
        c.tick()
    # The catch-up must have been via snapshot installation: the victim's
    # floor jumped past its pre-heal tail on every group.
    v_base = np.asarray(c.states.log.base)[victim]
    assert (v_base > victim_tail).all(), "catch-up without a floor jump"
    # Pending installations must be gone on LIVE leader lanes (deposed
    # leaders keep stale need_snap bookkeeping by design — it is inert
    # and reset on the next election win).
    lead_lanes = (np.asarray(c.states.role) == LEADER)[:, :, None]
    assert not (np.asarray(c.states.need_snap) & lead_lanes).any(), \
        "pending installations remain on live leaders after convergence"
    platform = jax.devices()[0].platform
    plog.phase("converged", floor_jump_groups=G)
    plog.save(platform)
    print(f"config-5 OK on {platform}: all {G} groups "
          f"caught up via snapshot floor jump; total {time.time() - t0:.0f}s",
          flush=True)


if __name__ == "__main__":
    main()
