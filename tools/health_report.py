#!/usr/bin/env python
"""Post-mortem gray-failure report over a health snapshot artifact.

The live half of the self-healing plane is the ``peers`` block on
``GET /healthz`` (runtime/obsrv.py); this CLI is the post-mortem half:
a run that went gray saves its scorecards — a bare
``node.health_snapshot()`` document, a full ``/healthz`` capture, or a
``rafting_tpu.utils.tracelog.save_dump`` artifact with
``meta={"health": node.health_snapshot()}`` — and this tool renders
the story with no engine, device, or live process required (same
contract as tools/hop_report.py):

* the self scorecard: decayed score vs the degraded threshold;
* the per-peer table: score, degraded flag, last-contact age (the
  CheckQuorum lanes' view of who this node could actually HEAR);
* the score timeline: per-sample rows showing WHEN each score crossed
  the threshold — the minutes-before-the-page view;
* the evacuation audit: which groups were handed where, at which tick.

Usage:
    tools/health_report.py SNAP.json[.gz] [--peer P] [--json]

``--peer`` restricts the timeline columns to one peer (plus self).
``--json`` re-emits the raw health document.
"""

import argparse
import gzip
import json
import os
import sys

sys.path.insert(0, ".")


def _open_doc(path: str):
    """Gzip-transparent read: .gz decompresses; a bare path falls back
    to its .gz sibling when only the compressed form exists."""
    if path.endswith(".gz"):
        return gzip.open(path, "rt")
    if not os.path.exists(path) and os.path.exists(path + ".gz"):
        return gzip.open(path + ".gz", "rt")
    return open(path)


def extract_health(doc: dict):
    """Find the health snapshot inside any of the accepted shapes:
    a bare ``health_snapshot()``, a ``/healthz`` document (under
    ``peers``), or a save_dump artifact (under ``_meta.health``).
    Returns None when the document carries no scorecards (e.g. the
    plane was disabled)."""
    if not isinstance(doc, dict):
        return None
    if "self_score" in doc and "peers" in doc:
        return doc
    for key in ("peers", "health"):
        inner = doc.get(key)
        if isinstance(inner, dict) and "self_score" in inner:
            return inner
    meta = doc.get("_meta")
    if isinstance(meta, dict):
        return extract_health(meta)
    return None


def _bar(score: float, thr: float, width: int = 20) -> str:
    """A threshold-relative score bar: full at 2x the degraded
    threshold, '!' past it."""
    full = max(thr * 2.0, 1e-9)
    n = min(width, int(round(width * min(score, full) / full)))
    mark = "!" if score >= thr else ""
    return ("#" * n).ljust(width) + mark


def render(health: dict, peer: int = None, out=None) -> None:
    out = out if out is not None else sys.stdout
    thr = float(health.get("degraded_after", 4.0))
    print(f"health @ tick {health.get('tick', 0)}  "
          f"half_life={health.get('half_life_ticks', 0):g} ticks  "
          f"degraded_after={thr:g}", file=out)
    flag = "DEGRADED" if health.get("self_degraded") else "healthy"
    print(f"self: score={health.get('self_score', 0.0):g} [{flag}]",
          file=out)
    peers = health.get("peers") or []
    if peers:
        print("peers:", file=out)
        for p in peers:
            age = p.get("contact_age_ticks")
            age_s = f"heard {age} ticks ago" if age is not None \
                else "never heard"
            tag = " DEGRADED" if p.get("degraded") else \
                (" (self)" if p.get("self") else "")
            print(f"  peer {p.get('peer'):<3d} "
                  f"score={p.get('score', 0.0):<8g} "
                  f"|{_bar(float(p.get('score', 0.0)), thr)}| "
                  f"{age_s}{tag}", file=out)
    timeline = health.get("timeline") or []
    if timeline:
        cols = ([peer] if peer is not None
                else list(range(len(timeline[-1].get("peers") or []))))
        head = "  ".join(f"p{c:<7d}" for c in cols)
        print(f"timeline ({len(timeline)} samples):", file=out)
        print(f"  {'tick':<8s} {'self':<8s} {head}", file=out)
        for row in timeline:
            scores = row.get("peers") or []
            cells = "  ".join(
                f"{scores[c]:<8g}" if c < len(scores) else f"{'-':<8s}"
                for c in cols)
            mark = "  <-- degraded" if row.get("self", 0.0) >= thr else ""
            print(f"  {row.get('tick', 0):<8d} "
                  f"{row.get('self', 0.0):<8g} {cells}{mark}", file=out)
    evs = health.get("recent_evacuations") or []
    print(f"evacuations: {health.get('evacuations', 0)}", file=out)
    for e in evs:
        print(f"  tick {e.get('tick'):<8d} group {e.get('group'):<5d} "
              f"-> peer {e.get('target')}", file=out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("snap", help="health_snapshot / healthz / save_dump "
                                 "document (.json or .json.gz)")
    ap.add_argument("--peer", type=int, default=None,
                    help="restrict timeline columns to one peer")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="re-emit the raw health document")
    args = ap.parse_args(argv)

    with _open_doc(args.snap) as f:
        doc = json.load(f)
    health = extract_health(doc)
    if health is None:
        print("no health scorecards in document (plane disabled, or "
              "not a health/healthz/save_dump artifact)",
              file=sys.stderr)
        return 2
    if args.as_json:
        json.dump(health, sys.stdout, indent=2, sort_keys=True)
        print()
        return 0
    render(health, peer=args.peer)
    return 0


if __name__ == "__main__":
    sys.exit(main())
