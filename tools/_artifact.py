"""Shared validation-artifact writer for the tools/ validators.

Every validator run persists its raw evidence — seed, config, per-phase
numbers, platform, wall-clock — as a committed JSON file under
``artifacts/``, so on-device results survive as auditable artifacts
instead of prose (the reference's verification ethos is artifact-driven:
byte-identical output files, /root/reference/README.md:28-33).
"""

from __future__ import annotations

import json
import os
import sys
import time

ARTIFACT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "artifacts")


class PhaseLog:
    """Collects (phase, numbers) pairs and mirrors lines to stdout."""

    def __init__(self, name: str, seed: int, config: dict):
        self.name = name
        self.seed = seed
        self.config = config
        self.phases: list = []
        self.t0 = time.time()

    def phase(self, title: str, **numbers) -> None:
        self.phases.append({"phase": title, "t_s": round(time.time()
                                                         - self.t0, 2),
                            **numbers})
        nums = " ".join(f"{k}={v}" for k, v in numbers.items())
        print(f"[{self.name}] {title}: {nums}", flush=True)

    def save(self, platform: str, ok: bool = True) -> str:
        os.makedirs(ARTIFACT_DIR, exist_ok=True)
        stem = f"{self.name}_{platform}"
        seq = 0
        while os.path.exists(os.path.join(ARTIFACT_DIR,
                                          f"{stem}_{seq:03d}.json")):
            seq += 1
        path = os.path.join(ARTIFACT_DIR, f"{stem}_{seq:03d}.json")
        doc = {
            "name": self.name,
            "ok": ok,
            "seed": self.seed,
            "platform": platform,
            "config": self.config,
            "phases": self.phases,
            "total_s": round(time.time() - self.t0, 2),
            "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "argv": sys.argv[1:],
        }
        with open(path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        print(f"[{self.name}] artifact saved: {path}", flush=True)
        return path
