"""Shared validation-artifact writer for the tools/ validators.

Every validator run persists its raw evidence — seed, config, per-phase
numbers, platform, wall-clock — as a committed gzip-compressed JSON
file (``.json.gz``) under ``artifacts/``, so on-device results survive
as auditable artifacts
instead of prose (the reference's verification ethos is artifact-driven:
byte-identical output files, /root/reference/README.md:28-33).
"""

from __future__ import annotations

import gzip
import json
import os
import sys
import time

ARTIFACT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "artifacts")


def open_artifact(path: str, mode: str = "rt"):
    """Open an artifact transparently: ``.gz`` paths decompress, and a
    bare ``.json`` path falls back to its ``.json.gz`` sibling when only
    the compressed form exists (new runs write compressed; committed
    history may hold either)."""
    if path.endswith(".gz"):
        return gzip.open(path, mode)
    if not os.path.exists(path) and os.path.exists(path + ".gz"):
        return gzip.open(path + ".gz", mode)
    return open(path, mode)


class PhaseLog:
    """Collects (phase, numbers) pairs and mirrors lines to stdout."""

    def __init__(self, name: str, seed: int, config: dict):
        self.name = name
        self.seed = seed
        self.config = config
        self.phases: list = []
        self.t0 = time.time()

    def phase(self, title: str, **numbers) -> None:
        self.phases.append({"phase": title, "t_s": round(time.time()
                                                         - self.t0, 2),
                            **numbers})
        nums = " ".join(f"{k}={v}" for k, v in numbers.items())
        print(f"[{self.name}] {title}: {nums}", flush=True)

    def save(self, platform: str, ok: bool = True) -> str:
        os.makedirs(ARTIFACT_DIR, exist_ok=True)
        stem = f"{self.name}_{platform}"
        seq = 0
        # Sequence numbers must not collide with either form —
        # committed history holds bare .json, new runs write .json.gz.
        while any(os.path.exists(os.path.join(
                ARTIFACT_DIR, f"{stem}_{seq:03d}.json{ext}"))
                for ext in ("", ".gz")):
            seq += 1
        path = os.path.join(ARTIFACT_DIR, f"{stem}_{seq:03d}.json.gz")
        doc = {
            "name": self.name,
            "ok": ok,
            "seed": self.seed,
            "platform": platform,
            "config": self.config,
            "phases": self.phases,
            "total_s": round(time.time() - self.t0, 2),
            "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "argv": sys.argv[1:],
        }
        with gzip.open(path, "wt") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        print(f"[{self.name}] artifact saved: {path}", flush=True)
        return path
